"""Bench regression sentinel (ISSUE 18).

The repo accumulated a trajectory of ``BENCH_*.json`` results — one per
growth round — that until now was compared by eyeball.  This module makes
the trajectory machine-checked: :func:`load_trajectory` flattens every
numeric leaf of each result's ``parsed`` payload into dotted metric paths
(``detail.llm.mfu``, ``detail.fedavg_cifar10_resnet20.rounds_per_sec``,
...), and :func:`compare` judges a fresh run metric-by-metric with
noise-aware thresholds:

    slack = max(rel_tol * |mean|, nsigma * std, abs_tol)

so a metric that historically wobbles (std captures it) gets proportional
headroom while a rock-stable one is held tight — but never tighter than
``rel_tol`` of its mean, because a 5-point trajectory's std is itself
noisy.  Direction is inferred from the leaf name (``*_seconds``, ``lag``,
``bytes`` ... regress UP; throughputs and MFU regress DOWN) — a metric
the heuristic can't classify is checked in its inferred direction only,
never both (a genuinely ambiguous name would otherwise always flag).

Config-shaped leaves (batch sizes, client counts, chip peaks) are
excluded: they describe the experiment, not its performance, and a
deliberate config change must not read as a regression.

``bench.py --mode compare`` wraps this into the exit-code contract the
driver consumes: ``detail.regression`` in the result JSON, exit 3 on any
regression.
"""

from __future__ import annotations

import glob
import json
import logging
import math
import os
from typing import Any, Optional, Sequence

log = logging.getLogger("fedml_tpu.obs.regress")

__all__ = ["load_trajectory", "flatten_numeric", "compare",
           "compare_candidate", "lower_is_better"]

#: leaf names that describe the experiment's configuration, not its
#: performance — excluded from comparison entirely
_CONFIG_LEAVES = frozenset({
    "batch", "seq_len", "clients_total", "clients_per_round", "n_params_m",
    "flops_per_token_g", "chip_peak_tflops", "n", "rc", "vs_baseline",
    "comm_round", "epochs",
})

#: leaf-name fragments whose metrics regress UPWARD (cost-like); anything
#: else is treated as throughput-like and regresses DOWNWARD
_LOWER_BETTER_FRAGMENTS = (
    "seconds", "_s", "lag", "staleness", "bytes", "loss", "dropped",
    "violations", "latency", "host_gap", "compile", "wait", "retries",
    "deduped", "breaches", "unaccounted", "skipped",
)


def lower_is_better(metric_path: str) -> bool:
    leaf = metric_path.rsplit(".", 1)[-1].lower()
    return any(f in leaf for f in _LOWER_BETTER_FRAGMENTS)


def flatten_numeric(parsed: Any, prefix: str = "") -> dict[str, float]:
    """Dotted-path -> value for every numeric leaf, config leaves and
    non-numerics skipped (bools are config, not measurements)."""
    out: dict[str, float] = {}
    if isinstance(parsed, dict):
        for k, v in parsed.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, (dict, list)):
                out.update(flatten_numeric(v, key))
            elif isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            elif str(k) in _CONFIG_LEAVES:
                continue
            elif math.isfinite(float(v)):
                out[key] = float(v)
    elif isinstance(parsed, list):
        # lists in bench results are violation/event collections — their
        # LENGTH is the comparable quantity
        if prefix:
            out[prefix + ".len"] = float(len(parsed))
    return out


def load_trajectory(root: str, pattern: str = "BENCH_*.json") -> list[dict]:
    """Every readable bench result under ``root``, flattened and sorted by
    its round number: ``[{"path", "round", "metrics": {...}}]``."""
    out = []
    for path in sorted(glob.glob(os.path.join(str(root), pattern))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            log.warning("regress: skipping unreadable %s (%s)", path, e)
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        out.append({
            "path": path,
            "round": int(doc.get("n", 0) or 0),
            "metrics": flatten_numeric(parsed),
        })
    out.sort(key=lambda r: (r["round"], r["path"]))
    return out


def _mean_std(values: Sequence[float]) -> tuple[float, float]:
    n = len(values)
    mean = sum(values) / n
    var = sum((v - mean) ** 2 for v in values) / n
    return mean, math.sqrt(var)


def compare(trajectory: Sequence[dict], candidate: dict, *,
            rel_tol: float = 0.10, nsigma: float = 3.0,
            abs_tol: float = 1e-9) -> dict:
    """Judge ``candidate`` (flattened metrics) against the trajectory.

    Returns ``{"ok", "checked", "regressions": [...], "improvements":
    [...], "new_metrics": [...], "thresholds": {...}}`` — regressions
    carry the full evidence (candidate, mean, std, slack, direction) so
    the driver's log is the postmortem."""
    by_metric: dict[str, list[float]] = {}
    for entry in trajectory:
        for k, v in entry.get("metrics", {}).items():
            by_metric.setdefault(k, []).append(float(v))
    regressions, improvements, checked = [], [], 0
    new_metrics = sorted(set(candidate) - set(by_metric))
    for metric, cand in sorted(candidate.items()):
        history = by_metric.get(metric)
        if not history:
            continue
        checked += 1
        mean, std = _mean_std(history)
        slack = max(rel_tol * abs(mean), nsigma * std, abs_tol)
        lower = lower_is_better(metric)
        delta = (cand - mean) if lower else (mean - cand)
        row = {"metric": metric, "candidate": round(cand, 9),
               "mean": round(mean, 9), "std": round(std, 9),
               "slack": round(slack, 9), "n_history": len(history),
               "direction": "lower_better" if lower else "higher_better"}
        if delta > slack:
            regressions.append(row)
        elif delta < -slack:
            improvements.append(row)
    return {
        "ok": not regressions,
        "checked": checked,
        "regressions": regressions,
        "improvements": improvements,
        "new_metrics": new_metrics,
        "thresholds": {"rel_tol": rel_tol, "nsigma": nsigma,
                       "abs_tol": abs_tol},
    }


def compare_candidate(candidate_path: str, baseline_dir: str, *,
                      rel_tol: float = 0.10, nsigma: float = 3.0,
                      abs_tol: float = 1e-9,
                      exclude_self: bool = True) -> dict:
    """Load + flatten one candidate result file and judge it against the
    ``BENCH_*.json`` trajectory under ``baseline_dir`` (the candidate's
    own file is excluded from the trajectory when it lives there).
    Raises ``ValueError`` on an unreadable/shape-less candidate — an
    absent input is an invocation error, not a clean pass."""
    try:
        with open(candidate_path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"candidate {candidate_path}: {e}") from e
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    if not isinstance(parsed, dict):
        # allow a bare parsed payload (a BENCH_RESULT line's JSON)
        parsed = doc if isinstance(doc, dict) else None
    if not isinstance(parsed, dict):
        raise ValueError(f"candidate {candidate_path}: no parsed payload")
    candidate = flatten_numeric(parsed)
    if not candidate:
        raise ValueError(f"candidate {candidate_path}: no numeric metrics")
    trajectory = load_trajectory(baseline_dir)
    if exclude_self:
        cand_abs = os.path.abspath(candidate_path)
        trajectory = [t for t in trajectory
                      if os.path.abspath(t["path"]) != cand_abs]
    result = compare(trajectory, candidate, rel_tol=rel_tol, nsigma=nsigma,
                     abs_tol=abs_tol)
    result["candidate_path"] = candidate_path
    result["baseline_dir"] = str(baseline_dir)
    result["trajectory"] = [{"path": t["path"], "round": t["round"]}
                            for t in trajectory]
    return result
