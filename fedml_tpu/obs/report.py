"""Offline round-timeline reconstruction from collector JSONL trails.

The server-side :class:`~fedml_tpu.obs.remote.ObsCollector` persists every
telemetry record — client train spans, server round/aggregate/eval spans,
per-client round-trip metrics — as one JSON object per line.  This module
reads those trails back, reassembles the per-round span tree by
(trace_id, span_id, parent_id), and renders the operational answers the
communication-perspective FL surveys call the cross-silo blind spot: where
did each round's time go (p50/p95 per phase) and which client is the
straggler.

Pure stdlib; consumed by ``fedml-tpu obs report`` and the tests.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional, Sequence

__all__ = [
    "load_jsonl", "SpanNode", "build_span_trees", "round_rows",
    "phase_percentiles", "slowest_clients", "pallas_kernel_stats",
    "client_health_rows", "hier_rows", "render_report",
]


def _dur(rec: dict) -> float:
    """Span duration, tolerant of records that never carried one (a crash
    before ``end()``, a foreign trail): missing/None/non-numeric -> 0.0."""
    try:
        return float(rec.get("dur_s") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _ts(rec: dict):
    """Wall timestamp or None when absent/non-numeric — callers fall back
    to collector ingest order (cross-host clocks skew; ingest order never
    lies about what the collector saw first)."""
    try:
        ts = rec.get("ts")
        return None if ts is None else float(ts)
    except (TypeError, ValueError):
        return None


def load_jsonl(path) -> list[dict]:
    """Parse a JSONL trail, skipping malformed lines (a crash mid-write must
    not make the whole trail unreadable)."""
    records = []
    text = Path(path).read_text()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


@dataclass
class SpanNode:
    record: dict
    children: list = field(default_factory=list)
    ingest: int = 0  # position in the collector trail (skew-proof ordering)

    @property
    def name(self) -> str:
        return str(self.record.get("name", ""))

    @property
    def span_id(self) -> Optional[str]:
        return self.record.get("span_id")

    @property
    def dur_s(self) -> float:
        return _dur(self.record)


def _spans(records: Iterable[dict]) -> list[dict]:
    return [r for r in records if r.get("kind") == "span" and r.get("trace_id")]


def _node_order(node: SpanNode) -> tuple:
    """Start-timestamp order with ingest-order fallback: a record without a
    usable ``ts`` (or from a skew-drifted host) sorts by when the collector
    saw it instead of raising or landing arbitrarily."""
    ts = _ts(node.record)
    return (0, ts, node.ingest) if ts is not None else (1, node.ingest, 0)


def build_span_trees(records: Iterable[dict]) -> dict[str, list[SpanNode]]:
    """trace_id -> root SpanNodes (children attached by parent_id, ordered by
    start timestamp with collector-ingest-order fallback).  Spans whose
    parent never arrived (a client's collector batch lost in transit)
    surface as extra roots instead of disappearing."""
    records = list(records)
    nodes: dict[str, SpanNode] = {}
    spans = [(i, r) for i, r in enumerate(records)
             if r.get("kind") == "span" and r.get("trace_id")]
    for i, rec in spans:
        sid = rec.get("span_id")
        if sid:
            nodes[sid] = SpanNode(rec, ingest=i)
    trees: dict[str, list[SpanNode]] = {}
    for i, rec in spans:
        node = nodes.get(rec.get("span_id")) or SpanNode(rec, ingest=i)
        parent = nodes.get(rec.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            trees.setdefault(str(rec["trace_id"]), []).append(node)
    for node in nodes.values():
        node.children.sort(key=_node_order)
    for roots in trees.values():
        roots.sort(key=_node_order)
    return trees


def round_rows(records: Iterable[dict]) -> list[dict]:
    """One row per federated round, keyed by the round span's trace.

    Each row: round_idx, trace_id, round span duration, aggregate/eval
    durations, the client train spans ({sender, client_idx, dur_s}), and the
    server-measured per-client round trips."""
    records = list(records)
    by_trace: dict[str, dict] = {}
    for ingest, rec in enumerate(records):
        if rec.get("kind") != "span" or not rec.get("trace_id"):
            continue
        row = by_trace.setdefault(str(rec["trace_id"]), {
            "trace_id": str(rec["trace_id"]), "round_idx": None,
            "round_dur_s": None, "aggregate_dur_s": None, "eval_dur_s": None,
            "train": [], "round_trips": {}, "payload_bytes": None,
            "_ingest": ingest,
        })
        name = rec.get("name")
        if name == "round":
            row["round_idx"] = rec.get("round_idx")
            row["round_dur_s"] = _dur(rec)
            row["ts"] = rec.get("ts", 0.0)
        elif name == "aggregate":
            row["aggregate_dur_s"] = _dur(rec)
            if row["round_idx"] is None:
                row["round_idx"] = rec.get("round_idx")
        elif name == "eval":
            row["eval_dur_s"] = _dur(rec)
        elif name == "train":
            row["train"].append({
                "sender": rec.get("sender"),
                "client_idx": rec.get("client_idx"),
                "dur_s": _dur(rec),
            })
            if row["round_idx"] is None:
                row["round_idx"] = rec.get("round_idx")
    for rec in records:
        if rec.get("kind") == "metric" and rec.get("metric") == "client_round_trip_s":
            trace_id = str(rec.get("trace_id", ""))
            if trace_id in by_trace:
                try:
                    by_trace[trace_id]["round_trips"][str(rec.get("client"))] = \
                        float(rec.get("value", 0.0))
                except (TypeError, ValueError):
                    pass
        elif rec.get("kind") == "metric" and rec.get("metric") == "comm_payload_bytes":
            # wire bytes of the round's model uploads (ISSUE-4 compression
            # shows up as this column shrinking across the trail)
            trace_id = str(rec.get("trace_id", ""))
            if trace_id in by_trace:
                try:
                    by_trace[trace_id]["payload_bytes"] = float(rec.get("value", 0.0))
                except (TypeError, ValueError):
                    pass
    rows = [row for row in by_trace.values() if row["round_idx"] is not None]

    def row_key(row):
        # numeric round index first; non-numeric indexes (foreign trails)
        # fall back to collector ingest order.  The tiebreak within a round
        # index is ALSO ingest order, not wall clocks: cross-host clock skew
        # must not reshuffle the timeline.
        try:
            return (0, float(row["round_idx"]), row["_ingest"])
        except (TypeError, ValueError):
            return (1, float(row["_ingest"]), 0)

    rows.sort(key=row_key)
    return rows


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile on a pre-sorted sequence (stdlib-only
    twin of numpy.percentile's default)."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (len(sorted_vals) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def phase_percentiles(records: Iterable[dict]) -> dict[str, dict]:
    """phase name -> {n, p50_s, p95_s, max_s} over every span of that name."""
    durs: dict[str, list[float]] = {}
    for rec in _spans(records):
        durs.setdefault(str(rec.get("name")), []).append(_dur(rec))
    out = {}
    for name, values in sorted(durs.items()):
        values.sort()
        out[name] = {
            "n": len(values),
            "p50_s": _percentile(values, 50),
            "p95_s": _percentile(values, 95),
            "max_s": values[-1],
        }
    return out


def slowest_clients(records: Iterable[dict]) -> list[dict]:
    """Clients ranked slowest-first by mean train-span duration (the
    straggler attribution table); round trips ride along when the server
    recorded them."""
    records = list(records)
    per_client: dict[str, list[float]] = {}
    rtts: dict[str, list[float]] = {}
    for rec in _spans(records):
        if rec.get("name") == "train":
            key = str(rec.get("sender", rec.get("client_idx")))
            per_client.setdefault(key, []).append(_dur(rec))
    for rec in records:
        if rec.get("kind") == "metric" and rec.get("metric") == "client_round_trip_s":
            try:
                rtts.setdefault(str(rec.get("client")), []).append(float(rec.get("value", 0.0)))
            except (TypeError, ValueError):
                pass
    out = []
    for client, durations in per_client.items():
        row = {
            "client": client,
            "rounds": len(durations),
            "mean_train_s": sum(durations) / len(durations),
            "max_train_s": max(durations),
        }
        if rtts.get(client):
            row["mean_round_trip_s"] = sum(rtts[client]) / len(rtts[client])
        out.append(row)
    out.sort(key=lambda r: -r["mean_train_s"])
    return out


def pallas_kernel_stats(records: Iterable[dict]) -> list[dict]:
    """Per-kernel summary of ``pallas_kernel_seconds`` metric records (shipped
    by clients via the Pallas timing sink, ``ops/pallas/timing.py``): kernel,
    n, total/mean/max seconds — slowest-total first."""
    per_kernel: dict[str, list[float]] = {}
    for rec in records:
        if rec.get("kind") == "metric" and rec.get("metric") == "pallas_kernel_seconds":
            per_kernel.setdefault(str(rec.get("kernel")), []).append(
                float(rec.get("value", 0.0) or 0.0))
    out = []
    for kernel, values in per_kernel.items():
        out.append({
            "kernel": kernel,
            "n": len(values),
            "total_s": sum(values),
            "mean_s": sum(values) / len(values),
            "max_s": max(values),
        })
    out.sort(key=lambda r: -r["total_s"])
    return out


def client_health_rows(records: Iterable[dict]) -> list[dict]:
    """Latest ``client_health`` ledger record per client (the cross-silo
    server persists one per client per round), worst score first — the
    health counterpart of the straggler table."""
    latest: dict[str, dict] = {}
    for rec in records:
        if rec.get("kind") == "metric" and rec.get("metric") == "client_health":
            latest[str(rec.get("client"))] = rec
    out = []
    for client, rec in latest.items():
        try:
            score = float(rec.get("score", 1.0))
        except (TypeError, ValueError):
            score = 1.0
        out.append({
            "client": client,
            "score": score,
            "ewma_rtt_s": rec.get("ewma_rtt_s"),
            "breaches": rec.get("breaches", 0.0),
            "comm_failures": rec.get("comm_failures", 0.0),
        })
    out.sort(key=lambda r: r["score"])
    return out


_HOPS = ("client_edge", "edge_region", "edge_root")


def hier_rows(records: Iterable[dict]) -> list[dict]:
    """Per-round hierarchy rows from the ``hier_tree`` trail records the
    cross-silo server persists at round close when an aggregation tree is
    configured.  The recorded counters are CUMULATIVE (straight reads of the
    ``fedml_hier_*`` families), so each row differences consecutive records —
    the first row's deltas are its absolute values, which is correct for a
    trail that starts at round 0.  Tree-shape gauges (depth/fanout/edges) are
    level values and pass through undifferenced."""
    recs = []
    for rec in records:
        if rec.get("kind") == "metric" and rec.get("metric") == "hier_tree":
            recs.append(rec)

    def _num(rec, key, default=0.0):
        try:
            return float(rec.get(key, default) or 0.0)
        except (TypeError, ValueError):
            return float(default)

    def rec_key(item):
        i, rec = item
        try:
            return (0, float(rec.get("round_idx")), i)
        except (TypeError, ValueError):
            return (1, float(i), 0)

    ordered = [rec for _, rec in sorted(enumerate(recs), key=rec_key)]
    out = []
    prev = None
    for rec in ordered:
        hop_bytes = rec.get("hop_bytes") or {}
        if not isinstance(hop_bytes, dict):
            hop_bytes = {}
        cum = {
            "hop_bytes": {hop: _num(hop_bytes, hop) for hop in _HOPS},
            "folds": _num(rec, "folds"),
            "relays": _num(rec, "relays"),
            "deduped": _num(rec, "deduped"),
            "partials_sent": _num(rec, "partials_sent"),
        }
        row = {
            "round_idx": rec.get("round_idx"),
            "hop_bytes": dict(cum["hop_bytes"]),
            "folds": cum["folds"],
            "relays": cum["relays"],
            "deduped": cum["deduped"],
            "partials_sent": cum["partials_sent"],
            "depth": _num(rec, "depth"),
            "fanout": _num(rec, "fanout"),
            "edges": _num(rec, "edges"),
        }
        if prev is not None:
            # counters only move forward; a negative delta means the trail
            # spans a process restart — clamp rather than report nonsense
            for hop in _HOPS:
                row["hop_bytes"][hop] = max(
                    0.0, cum["hop_bytes"][hop] - prev["hop_bytes"][hop])
            for key in ("folds", "relays", "deduped", "partials_sent"):
                row[key] = max(0.0, cum[key] - prev[key])
        prev = cum
        out.append(row)
    return out


def _table(headers: list[str], rows: list[list[str]]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    def fmt(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()
    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt(r) for r in rows]
    return "\n".join(lines)


def _s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.4f}"


def render_report(records: Iterable[dict]) -> str:
    """The ``fedml-tpu obs report`` output: per-round timeline, per-phase
    p50/p95, and the slowest-client ranking."""
    records = list(records)
    rows = round_rows(records)
    sections = []

    timeline = []
    for row in rows:
        train = sorted(row["train"], key=lambda t: -t["dur_s"])
        if train:
            who = train[0]["sender"] if train[0]["sender"] is not None else train[0]["client_idx"]
            slowest = f"{who} ({train[0]['dur_s']:.4f}s)"
        else:
            slowest = "-"
        pb = row.get("payload_bytes")
        timeline.append([
            str(row["round_idx"]), str(row["trace_id"]), _s(row["round_dur_s"]),
            _s(row["aggregate_dur_s"]), _s(row["eval_dur_s"]),
            str(len(train)), "-" if pb is None else str(int(pb)), slowest,
        ])
    sections.append("== round timeline ==\n" + _table(
        ["round", "trace_id", "round_s", "aggregate_s", "eval_s", "clients",
         "upload_bytes", "slowest client (train_s)"],
        timeline,
    ))

    phases = phase_percentiles(records)
    sections.append("== phase durations ==\n" + _table(
        ["phase", "n", "p50_s", "p95_s", "max_s"],
        [[name, str(st["n"]), f"{st['p50_s']:.4f}", f"{st['p95_s']:.4f}", f"{st['max_s']:.4f}"]
         for name, st in phases.items()],
    ))

    stragglers = slowest_clients(records)
    sections.append("== slowest clients ==\n" + _table(
        ["client", "rounds", "mean_train_s", "max_train_s", "mean_round_trip_s"],
        [[r["client"], str(r["rounds"]), f"{r['mean_train_s']:.4f}",
          f"{r['max_train_s']:.4f}",
          f"{r['mean_round_trip_s']:.4f}" if "mean_round_trip_s" in r else "-"]
         for r in stragglers],
    ))

    health = client_health_rows(records)
    if health:
        sections.append("== client health ==\n" + _table(
            ["client", "score", "ewma_rtt_s", "breaches", "comm_failures"],
            [[r["client"], f"{r['score']:.4f}",
              _s(r["ewma_rtt_s"] if isinstance(r["ewma_rtt_s"], (int, float)) else None),
              _s(float(r["breaches"] or 0.0)), _s(float(r["comm_failures"] or 0.0))]
             for r in health],
        ))

    hier = hier_rows(records)
    if hier:
        last = hier[-1]
        shape = (f"tree depth={int(last['depth'])} "
                 f"fanout={int(last['fanout'])} edges={int(last['edges'])}")
        sections.append("== hierarchy ==\n" + shape + "\n" + _table(
            ["round", "client_edge_B", "edge_region_B", "edge_root_B",
             "folds", "relays", "deduped", "partials"],
            [[str(r["round_idx"]),
              str(int(r["hop_bytes"]["client_edge"])),
              str(int(r["hop_bytes"]["edge_region"])),
              str(int(r["hop_bytes"]["edge_root"])),
              str(int(r["folds"])), str(int(r["relays"])),
              str(int(r["deduped"])), str(int(r["partials_sent"]))]
             for r in hier],
        ))

    kernels = pallas_kernel_stats(records)
    if kernels:
        sections.append("== pallas kernels ==\n" + _table(
            ["kernel", "n", "total_s", "mean_s", "max_s"],
            [[r["kernel"], str(r["n"]), f"{r['total_s']:.4f}",
              f"{r['mean_s']:.6f}", f"{r['max_s']:.6f}"] for r in kernels],
        ))
    return "\n\n".join(sections) + "\n"
