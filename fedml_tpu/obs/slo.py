"""Declarative SLO watchdog over registry snapshots (ISSUE 16).

The registry already *measures* everything that matters (round latency,
async throughput, fold lag, buffered peak, dedup pressure, canary health,
prefetch overlap) — but nothing *watched* it: a regression only surfaced
when a soak's final assertion tripped.  :class:`SLOEngine` evaluates
declarative specs against ``MetricsRegistry.snapshot()`` on the existing
``cross_silo/runtime.py`` timer wheel — NO new threads; the engine is one
more ``(owner, name)`` timer on the server's (or control plane's shared)
runtime.

A spec is data, not code::

    {"round_latency": {"metric": "fedml_crosssilo_round_seconds",
                       "stat": "p95", "op": "<=", "threshold": 2.0},
     "versions_per_sec": {"metric": "fedml_async_virtual_rounds_total",
                          "stat": "rate", "op": ">=", "threshold": 0.5},
     "dedup_ratio": {"metric": "fedml_crosssilo_uploads_deduped_total",
                     "per": "fedml_async_arrivals_total",
                     "stat": "value", "op": "<=", "threshold": 0.2}}

``stat``: ``value`` (sum of matching counter/gauge samples), ``sum`` /
``count`` / ``mean`` (histogram scalars), ``rate`` (per-second delta of
``value`` between ticks), or ``pNN`` (bucket-interpolated percentile).
``per`` divides by a second metric's ``value`` (ratios: dedup/arrivals,
compressed/raw bytes).  ``labels`` restricts matching samples; an engine
built with ``job=<id>`` adds that filter to every spec — the multi-tenant
scoping path (``ScopedRegistry`` writes carry the ``job`` label, so a
per-job engine sees only its tenant's series).

Breach handling is edge-triggered: entering breach emits one alert record
into the collector trail (and from there OTLP), increments
``fedml_slo_breaches_total{slo}``, optionally triggers a flight-recorder
dump (once per SLO), and flips ``fedml_slo_healthy{slo}`` to 0; recovery
flips it back.  A healthy run records ZERO breaches.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from ..core.flags import cfg_extra
from . import registry as obsreg

log = logging.getLogger("fedml_tpu.obs.slo")

__all__ = ["SLOEngine", "engine_from_config", "evaluate_spec"]

SLO_BREACHES = obsreg.REGISTRY.counter(
    "fedml_slo_breaches_total",
    "SLO breach transitions (edge-triggered: one per entry into breach), "
    "by SLO name and tenant job ('' outside multi-tenant).",
    labels=("slo", "job"),
)
SLO_EVALUATIONS = obsreg.REGISTRY.counter(
    "fedml_slo_evaluations_total",
    "SLO engine evaluation ticks, by tenant job ('' outside multi-tenant).",
    labels=("job",),
)
SLO_HEALTHY = obsreg.REGISTRY.gauge(
    "fedml_slo_healthy",
    "1 while the SLO holds, 0 while breached, by SLO name and tenant job.",
    labels=("slo", "job"),
)

_OPS: dict[str, Callable[[float, float], bool]] = {
    "<=": lambda v, t: v <= t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    ">": lambda v, t: v > t,
}


def _matches(sample_labels: dict, want: dict) -> bool:
    return all(str(sample_labels.get(k)) == str(v) for k, v in want.items())


def _family(snapshot: list[dict], name: str) -> Optional[dict]:
    for fam in snapshot:
        if fam.get("name") == name:
            return fam
    return None


def _scalar(fam: dict, labels: dict, field: str) -> float:
    """Sum one scalar field over matching samples (counters/gauges use
    ``value``; histograms expose ``count``/``sum``)."""
    total = 0.0
    for s in fam.get("samples", ()):
        if _matches(s.get("labels", {}), labels):
            total += float(s.get(field, 0.0))
    return total


def _percentile(fam: dict, labels: dict, q: float) -> Optional[float]:
    """Bucket-interpolated percentile over the matching histogram samples
    (aggregated counts; returns the bucket upper bound at the quantile)."""
    buckets = fam.get("buckets")
    if not buckets:
        return None
    counts = [0] * len(buckets)
    for s in fam.get("samples", ()):
        if _matches(s.get("labels", {}), labels):
            for i, c in enumerate(s.get("counts", ())):
                counts[i] += int(c)
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    cumulative = 0
    for bound, c in zip(buckets, counts):
        cumulative += c
        if cumulative >= target:
            return float(bound)
    return float(buckets[-1])


def evaluate_spec(spec: dict, snapshot: list[dict], *,
                  extra_labels: Optional[dict] = None,
                  rate_state: Optional[dict] = None,
                  now: Optional[float] = None) -> Optional[float]:
    """Resolve one spec's observed value against a snapshot; ``None`` when
    the metric has no matching data yet (no data = no breach — an SLO must
    not fire before the subsystem it watches has run)."""
    fam = _family(snapshot, str(spec["metric"]))
    if fam is None:
        return None
    labels = {**(spec.get("labels") or {}), **(extra_labels or {})}
    # drop filter keys the family does not declare (a job-scoped engine can
    # still watch global single-series families like the buffered peak)
    declared = set(fam.get("labels", ()))
    labels = {k: v for k, v in labels.items() if k in declared}
    stat = str(spec.get("stat", "value")).lower()
    hist = fam.get("kind") == "histogram"
    if stat.startswith("p") and stat[1:].isdigit():
        return _percentile(fam, labels, int(stat[1:]) / 100.0) if hist else None
    if stat == "mean":
        if not hist:
            return None
        n = _scalar(fam, labels, "count")
        return (_scalar(fam, labels, "sum") / n) if n else None
    if stat in ("sum", "count"):
        if not hist:
            return None
        v = _scalar(fam, labels, stat)
    elif stat == "rate":
        if rate_state is None:
            return None
        v = _scalar(fam, labels, "count" if hist else "value")
    else:  # "value"
        v = _scalar(fam, labels, "count" if hist else "value")
    if stat == "rate":
        t = now if now is not None else time.monotonic()
        prev = rate_state.get("prev")
        rate_state["prev"] = (t, v)
        if prev is None:
            return None
        dt = t - prev[0]
        if dt <= 0:
            return None
        v = (v - prev[1]) / dt
    per = spec.get("per")
    if per:
        per_fam = _family(snapshot, str(per))
        if per_fam is None:
            return None
        denom = _scalar(per_fam, labels if set(per_fam.get("labels", ())) >= set(labels) else {},
                        "count" if per_fam.get("kind") == "histogram" else "value")
        if denom == 0:
            return None
        v = v / denom
    return float(v)


class SLOEngine:
    """Evaluate declarative SLO specs on the timer wheel; emit breaches."""

    def __init__(self, specs: dict, *, runtime=None, interval_s: float = 1.0,
                 registry: Optional[obsreg.MetricsRegistry] = None,
                 collector=None, otlp=None, flight=None, job: str = ""):
        self.specs = {str(k): dict(v) for k, v in dict(specs or {}).items()}
        for name, spec in self.specs.items():
            op = str(spec.get("op", "<="))
            if op not in _OPS:
                raise ValueError(f"SLO {name!r}: unknown op {op!r}")
            if "metric" not in spec or "threshold" not in spec:
                raise ValueError(f"SLO {name!r}: needs 'metric' and 'threshold'")
        self.runtime = runtime
        self.interval_s = max(0.05, float(interval_s))
        self.registry = registry or obsreg.REGISTRY
        self.collector = collector
        self.otlp = otlp
        self.flight = flight
        self.job = str(job or "")
        self._rate_state: dict[str, dict] = {n: {} for n in self.specs}
        self._breached: dict[str, bool] = {n: False for n in self.specs}
        self._dumped: set[str] = set()
        self.evaluations = 0
        self.breach_records: list[dict] = []
        self._started = False
        self._stopped = False

    # -- timer-wheel lifecycle ------------------------------------------------
    def start(self) -> "SLOEngine":
        if self.runtime is None:
            raise ValueError("SLOEngine.start needs a ServerRuntime")
        self._started = True
        self.runtime.arm(self, "slo_tick", self.interval_s, self._tick)
        return self

    def _tick(self) -> None:
        if self._stopped:
            return
        try:
            self.evaluate_now()
        except Exception:
            log.exception("slo: evaluation tick failed")
        if not self._stopped:
            self.runtime.arm(self, "slo_tick", self.interval_s, self._tick)

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        try:
            # final pass: even a run shorter than one tick interval gets one
            # end-of-run evaluation, and a breach that lands between the last
            # tick and teardown still gets caught before the registry goes
            # quiet
            self.evaluate_now()
        except Exception:
            log.exception("slo: final evaluation failed")
        if self._started and self.runtime is not None:
            self.runtime.cancel(self)

    close = stop

    # -- evaluation -----------------------------------------------------------
    def evaluate_now(self, snapshot: Optional[list[dict]] = None) -> list[dict]:
        """One evaluation pass; returns this pass's NEW breach records
        (edge-triggered).  Public so tests and harnesses can drive the
        engine without a timer."""
        snap = snapshot if snapshot is not None else self.registry.snapshot()
        self.evaluations += 1
        SLO_EVALUATIONS.inc(job=self.job)
        extra = {"job": self.job} if self.job else None
        new_breaches: list[dict] = []
        for name, spec in self.specs.items():
            value = evaluate_spec(spec, snap, extra_labels=extra,
                                  rate_state=self._rate_state[name])
            if value is None:
                continue
            ok = _OPS[str(spec.get("op", "<="))](value, float(spec["threshold"]))
            SLO_HEALTHY.set(1.0 if ok else 0.0, slo=name, job=self.job)
            was = self._breached[name]
            self._breached[name] = not ok
            if ok or was:
                continue
            # entering breach: alert once per transition
            SLO_BREACHES.inc(slo=name, job=self.job)
            rec = {"kind": "slo_breach", "slo": name, "ts": round(time.time(), 6),
                   "metric": spec["metric"], "stat": spec.get("stat", "value"),
                   "op": spec.get("op", "<="), "threshold": float(spec["threshold"]),
                   "value": round(value, 9)}
            if self.job:
                rec["job"] = self.job
            new_breaches.append(rec)
            self.breach_records.append(rec)
            self._emit(rec)
        return new_breaches

    def _emit(self, rec: dict) -> None:
        if self.collector is not None:
            try:
                self.collector.ingest(0, [dict(rec)])
            except Exception:
                pass
        if self.otlp is not None and self.collector is None:
            # collector-less processes still ship the breach (the collector
            # path already tees into its own exporter)
            try:
                self.otlp.export_metrics_now()
            except Exception:
                pass
        if self.flight is not None:
            try:
                self.flight.note("slo_breach", **{k: v for k, v in rec.items()
                                                  if k not in ("kind", "ts")})
                if rec["slo"] not in self._dumped:
                    self._dumped.add(rec["slo"])
                    self.flight.trigger("slo_breach", breach=dict(rec))
            except Exception:
                pass

    def summary(self) -> dict:
        return {
            "job": self.job,
            "evaluations": self.evaluations,
            "breaches": len(self.breach_records),
            "breached_slos": sorted({r["slo"] for r in self.breach_records}),
        }


def engine_from_config(cfg, *, runtime, collector=None, otlp=None,
                       flight=None) -> Optional[SLOEngine]:
    """The gate: ``extra.slo_specs`` unset/empty -> ``None`` (no engine, no
    timer, bit-identical default path).  Multi-tenant configs scope the
    engine to their ``mt_job_id`` automatically."""
    specs = cfg_extra(cfg, "slo_specs")
    if not specs:
        return None
    use_flight = flight if cfg_extra(cfg, "slo_flight_dump") else None
    try:
        return SLOEngine(
            specs, runtime=runtime,
            interval_s=float(cfg_extra(cfg, "slo_interval_s")),
            collector=collector, otlp=otlp, flight=use_flight,
            job=str(cfg_extra(cfg, "mt_job_id") or ""))
    except (ValueError, TypeError) as e:
        log.warning("slo: invalid specs (%s) — engine disabled", e)
        return None
