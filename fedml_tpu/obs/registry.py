"""Process-global metrics registry with Prometheus text exposition.

The reference publishes run metrics to its SaaS over MQTT; nothing local can
be scraped by a standard collector.  This module is the self-hosted
replacement: ``Counter`` / ``Gauge`` / ``Histogram`` families (labels
supported, fixed log-scale latency buckets, stdlib only), a text-format
0.0.4 ``render()``, and a tiny ``http.server`` endpoint serving ``/metrics``
and ``/healthz`` that the scheduler control plane and the cross-silo server
can start — any Prometheus-compatible scraper works against it unchanged.

Everything is thread-safe: the hot paths (comm receive loop, server round
handlers, simulator chunks) update metrics from different threads.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "ScopedRegistry", "MetricsHTTPServer", "maybe_start_metrics_server",
    "default_latency_buckets",
]

_INF = float("inf")
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def default_latency_buckets() -> tuple:
    """Fixed log-scale buckets: 100µs doubling up to ~419s (22 buckets) —
    spans FL phase durations from a metrics-registry update to a straggling
    cross-silo round, with constant relative resolution and no deps."""
    out, v = [], 1e-4
    for _ in range(22):
        out.append(v)
        v *= 2.0
    return tuple(out)


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _format_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _format_labels(names: Sequence[str], values: Sequence[str],
                   extra: Sequence[tuple] = ()) -> str:
    pairs = [f'{n}="{_escape_label_value(str(v))}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label_value(str(v))}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    """One metric family: a name, a help string, and label-keyed children."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for label in labels:
            if not _LABEL_RE.match(label) or label == "le":
                raise ValueError(f"invalid label name {label!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, declared {list(self.labelnames)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def clear(self) -> None:
        with self._lock:
            self._children.clear()

    def _snapshot(self) -> dict:
        """Structured point-in-time view of this family (scalar children;
        Histogram overrides).  Input shape of the OTLP exporter."""
        with self._lock:
            samples = [
                {"labels": dict(zip(self.labelnames, key)), "value": float(v)}
                for key, v in sorted(self._children.items())
            ]
        return {"name": self.name, "kind": self.kind, "help": self.help,
                "labels": list(self.labelnames), "samples": samples}


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {amount})")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._children.get(self._key(labels), 0.0))

    def _render_into(self, out: list) -> None:
        with self._lock:
            for key in sorted(self._children):
                out.append(f"{self.name}{_format_labels(self.labelnames, key)} "
                           f"{_format_value(self._children[key])}")


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._children.get(self._key(labels), 0.0))

    _render_into = Counter._render_into


class Histogram(_Metric):
    """Fixed-bucket histogram (log-scale latency buckets by default).

    Children store per-bucket counts plus sum/count; ``render`` emits the
    Prometheus cumulative form (``_bucket{le=...}``, ``+Inf`` == ``_count``,
    ``_sum``, ``_count``)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labels)
        bounds = tuple(sorted(float(b) for b in (buckets or default_latency_buckets())))
        if not bounds or any(b != b for b in bounds):
            raise ValueError(f"histogram {name}: invalid buckets {buckets!r}")
        self.buckets = bounds if bounds[-1] == _INF else bounds + (_INF,)

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        value = float(value)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = {"counts": [0] * len(self.buckets), "sum": 0.0, "count": 0}
                self._children[key] = child
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child["counts"][i] += 1
                    break
            child["sum"] += value
            child["count"] += 1

    def count(self, **labels) -> int:
        with self._lock:
            child = self._children.get(self._key(labels))
            return int(child["count"]) if child else 0

    def sum(self, **labels) -> float:
        with self._lock:
            child = self._children.get(self._key(labels))
            return float(child["sum"]) if child else 0.0

    def _snapshot(self) -> dict:
        with self._lock:
            samples = [
                {"labels": dict(zip(self.labelnames, key)),
                 "counts": list(child["counts"]),
                 "sum": float(child["sum"]), "count": int(child["count"])}
                for key, child in sorted(self._children.items())
            ]
        return {"name": self.name, "kind": self.kind, "help": self.help,
                "labels": list(self.labelnames), "buckets": list(self.buckets),
                "samples": samples}

    def _render_into(self, out: list) -> None:
        with self._lock:
            for key in sorted(self._children):
                child = self._children[key]
                cumulative = 0
                for bound, n in zip(self.buckets, child["counts"]):
                    cumulative += n
                    labels = _format_labels(self.labelnames, key,
                                            extra=[("le", _format_value(bound))])
                    out.append(f"{self.name}_bucket{labels} {cumulative}")
                base = _format_labels(self.labelnames, key)
                out.append(f"{self.name}_sum{base} {_format_value(child['sum'])}")
                out.append(f"{self.name}_count{base} {child['count']}")


class MetricsRegistry:
    """Named metric families + the text-format exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labels: Sequence[str],
                       **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} re-registered as {cls.kind} with labels "
                        f"{tuple(labels)}; existing is {existing.kind} with "
                        f"{existing.labelnames}"
                    )
                return existing
            metric = cls(name, help, labels, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def scoped(self, **bound: str) -> "ScopedRegistry":
        """A view of this registry with ``bound`` labels pre-applied — the
        multi-tenant namespace mechanism (ISSUE 14): two tenants registering
        the SAME family name through ``REGISTRY.scoped(job=...)`` share one
        family whose samples stay fully separated by the ``job`` label, so
        neither can observe (or clobber) the other's series.  Bound label
        names are appended to every family's declared labels; re-registering
        an existing family with a different label set still refuses exactly
        as the base registry does."""
        return ScopedRegistry(self, bound)

    def snapshot(self) -> list[dict]:
        """Structured point-in-time view of every family: name/kind/help/
        labels plus samples (and buckets for histograms).  This is what the
        OTLP exporter (``obs/otlp.py``) maps to ``ResourceMetrics``, and a
        JSON-friendly debugging surface for tests and ``bench.py``."""
        with self._lock:
            metrics = list(self._metrics.values())
        return [m._snapshot() for m in metrics]

    def render(self) -> str:
        """Prometheus text format 0.0.4: HELP + TYPE per family, then the
        family's samples; ends with a newline as the format requires."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: list[str] = []
        for metric in metrics:
            out.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
            out.append(f"# TYPE {metric.name} {metric.kind}")
            metric._render_into(out)
        return "\n".join(out) + "\n"


class _BoundChild:
    """One family viewed through fixed label values: every write/read call
    merges the bound labels in, so tenant code uses the plain metric API
    while its samples land in its own label series."""

    __slots__ = ("_metric", "_bound")

    def __init__(self, metric: _Metric, bound: dict):
        self._metric = metric
        self._bound = bound

    def _merge(self, labels: dict) -> dict:
        overlap = set(labels) & set(self._bound)
        if overlap:
            raise ValueError(
                f"{self._metric.name}: labels {sorted(overlap)} are bound by "
                "the scoped registry and cannot be overridden")
        return {**self._bound, **labels}

    def inc(self, amount: float = 1.0, **labels) -> None:
        self._metric.inc(amount, **self._merge(labels))

    def dec(self, amount: float = 1.0, **labels) -> None:
        self._metric.dec(amount, **self._merge(labels))

    def set(self, value: float, **labels) -> None:
        self._metric.set(value, **self._merge(labels))

    def observe(self, value: float, **labels) -> None:
        self._metric.observe(value, **self._merge(labels))

    def value(self, **labels) -> float:
        return self._metric.value(**self._merge(labels))

    def count(self, **labels) -> int:
        return self._metric.count(**self._merge(labels))

    def sum(self, **labels) -> float:
        return self._metric.sum(**self._merge(labels))

    @property
    def name(self) -> str:
        return self._metric.name


class ScopedRegistry:
    """Label-bound view over a :class:`MetricsRegistry` (see
    :meth:`MetricsRegistry.scoped`).  Family names must still carry the
    ``fedml_`` namespace — GL005 and the runtime metric lint see the same
    underlying families."""

    def __init__(self, registry: "MetricsRegistry", bound: dict):
        for name in bound:
            if not _LABEL_RE.match(name) or name == "le":
                raise ValueError(f"invalid bound label name {name!r}")
        self.registry = registry
        self.bound = {k: str(v) for k, v in bound.items()}

    def _labels(self, labels: Sequence[str]) -> tuple:
        clash = set(labels) & set(self.bound)
        if clash:
            raise ValueError(f"labels {sorted(clash)} already bound by this scope")
        return tuple(self.bound) + tuple(labels)

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> _BoundChild:
        return _BoundChild(self.registry.counter(name, help, self._labels(labels)),
                           self.bound)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> _BoundChild:
        return _BoundChild(self.registry.gauge(name, help, self._labels(labels)),
                           self.bound)

    def histogram(self, name: str, help: str = "", labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> _BoundChild:
        return _BoundChild(
            self.registry.histogram(name, help, self._labels(labels), buckets=buckets),
            self.bound)


#: the process-global registry every instrumented layer writes to
REGISTRY = MetricsRegistry()


class MetricsHTTPServer:
    """``/metrics`` + ``/healthz`` on a stdlib ThreadingHTTPServer.

    ``port=0`` binds an ephemeral port (read it back from ``.port``); the
    serve loop runs on a daemon thread so nothing blocks or outlives the
    process.  Scrape with any Prometheus-compatible collector."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "0.0.0.0"):
        registry = registry or REGISTRY
        started = time.time()

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = registry.render().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body = json.dumps({"status": "ok", "uptime_s": round(time.time() - started, 3)}).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fedml-metrics-http", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def maybe_start_metrics_server(cfg) -> Optional[MetricsHTTPServer]:
    """Start the exposition endpoint when ``cfg.extra['metrics_port']`` is
    set (0 = ephemeral port); None (and no server) otherwise — shared gate
    for the control plane and the cross-silo server."""
    from ..core.flags import cfg_extra

    port = cfg_extra(cfg, "metrics_port")
    if port is None:
        return None
    return MetricsHTTPServer(REGISTRY, port=int(port)).start()
