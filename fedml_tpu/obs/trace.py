"""Distributed round tracing — trace/span identity that crosses the wire.

The reference correlates a round's client train phases and server aggregation
only through its SaaS backend's run ids; locally there is no way to line up
"client 3 trained for 1.2s" with "the server aggregated round 7".  This
module gives every phase a span (trace_id / span_id / parent_id + monotonic
and wall clocks) and propagates the (trace_id, span_id) pair over the comm
layer's ``Message`` trace header, so one round-scoped trace links the
server's round/aggregate spans with every client's train span — across
processes and transports.

Design constraints: stdlib + jax only.  ``traced`` doubles as decorator and
context manager and mirrors every span into ``jax.profiler.TraceAnnotation``
so the same names show up in XLA device profiles; the current span rides a
``contextvars.ContextVar`` so nested spans parent automatically, including
under the comm receive loop's per-message ``activate`` window.
"""

from __future__ import annotations

import contextvars
import functools
import secrets
import time
from typing import Any, Callable, Optional, Union

import jax

__all__ = [
    "Span", "traced", "activate", "current", "start_span",
    "inject", "extract", "new_id",
]


def new_id() -> str:
    """128-bit-ish random hex id (16 chars is plenty for run-local traces)."""
    return secrets.token_hex(8)


class Span:
    """One timed phase. ``trace_id`` groups spans of one logical operation
    (a federated round); ``parent_id`` is the enclosing span's ``span_id``."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "start_wall", "start_mono", "end_wall", "end_mono", "attrs")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None, **attrs):
        self.name = name
        self.trace_id = trace_id or new_id()
        self.span_id = new_id()
        self.parent_id = parent_id
        self.start_wall = time.time()
        self.start_mono = time.monotonic()
        self.end_wall: Optional[float] = None
        self.end_mono: Optional[float] = None
        self.attrs = attrs

    def end(self) -> "Span":
        if self.end_mono is None:
            self.end_mono = time.monotonic()
            self.end_wall = time.time()
        return self

    @property
    def duration_s(self) -> float:
        return (self.end_mono if self.end_mono is not None else time.monotonic()) - self.start_mono

    def header(self) -> dict:
        """The wire propagation context: what a child on the far side needs."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def to_record(self) -> dict:
        """JSONL shape the collector trail stores and ``obs.report`` reads."""
        return {
            "kind": "span",
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts": self.start_wall,
            "dur_s": round(self.duration_s, 9),
            **self.attrs,
        }

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, trace={self.trace_id}, span={self.span_id}, "
                f"parent={self.parent_id}, dur={self.duration_s:.6f}s)")


#: current span (a Span) or remote parent context (a header dict) — set by
#: ``traced`` locally and ``activate`` at the comm receive boundary
_current: contextvars.ContextVar[Optional[Union[Span, dict]]] = contextvars.ContextVar(
    "fedml_tpu_current_span", default=None
)


def current() -> Optional[Union[Span, dict]]:
    """The ambient span (or remote header dict) new spans will parent to."""
    return _current.get()


def start_span(name: str, parent: Any = None, **attrs) -> Span:
    """Open a span under ``parent`` (a Span, a wire header dict, or None =
    ambient context; no ambient context starts a fresh trace)."""
    if parent is None:
        parent = _current.get()
    if isinstance(parent, Span):
        return Span(name, trace_id=parent.trace_id, parent_id=parent.span_id, **attrs)
    if isinstance(parent, dict) and parent.get("trace_id"):
        return Span(name, trace_id=parent["trace_id"],
                    parent_id=parent.get("span_id"), **attrs)
    return Span(name, **attrs)


class traced:
    """Span context manager AND decorator.

    ``with traced("train", round_idx=3) as span: ...`` opens a span under the
    ambient context, makes it the ambient context for the body, mirrors it
    into ``jax.profiler.TraceAnnotation`` (TPU profile visibility), ends it
    on exit, and hands the record to ``sink`` when one is given.  ``sink``
    failures are swallowed — telemetry must never take down the traced path.
    """

    def __init__(self, name: str, parent: Any = None,
                 sink: Optional[Callable[[dict], None]] = None, **attrs):
        self.name = name
        self.parent = parent
        self.sink = sink
        self.attrs = attrs
        self.span: Optional[Span] = None

    def __enter__(self) -> Span:
        self.span = start_span(self.name, parent=self.parent, **self.attrs)
        self._token = _current.set(self.span)
        self._annotation = jax.profiler.TraceAnnotation(self.name)
        self._annotation.__enter__()
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._annotation.__exit__(exc_type, exc, tb)
        _current.reset(self._token)
        self.span.end()
        if self.sink is not None:
            try:
                self.sink(self.span.to_record())
            except Exception:
                pass
        return False

    def __call__(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with traced(self.name, parent=self.parent, sink=self.sink, **self.attrs):
                return fn(*args, **kwargs)

        return wrapper


class activate:
    """Install a remote parent context (a wire header) as the ambient span
    for the duration of a message handler — the receive-side half of
    propagation.  A missing/invalid header is a no-op, so the receive loop
    can wrap every dispatch unconditionally."""

    def __init__(self, header: Optional[dict]):
        self.header = header if (isinstance(header, dict) and header.get("trace_id")) else None
        self._token = None

    def __enter__(self) -> Optional[dict]:
        if self.header is not None:
            self._token = _current.set(self.header)
        return self.header

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _current.reset(self._token)
        return False


def inject(msg, context: Any = None) -> None:
    """Stamp a trace header onto an outgoing protocol message (send-side half
    of propagation).  ``context`` defaults to the ambient span; an existing
    header on the message is never overwritten (an explicit round stamp wins
    over the ambient context of whatever thread sends the message)."""
    if msg.get_trace() is not None:
        return
    src = context if context is not None else _current.get()
    if isinstance(src, Span):
        msg.set_trace(src.header())
    elif isinstance(src, dict) and src.get("trace_id"):
        msg.set_trace({"trace_id": src["trace_id"], "span_id": src.get("span_id")})


def extract(msg) -> Optional[dict]:
    """Read the trace header off an incoming message (None when absent)."""
    header = msg.get_trace()
    if isinstance(header, dict) and header.get("trace_id"):
        return header
    return None
