"""Remote observability: ship metrics/events/log batches over any comm
backend to a server-side collector.

Parity with the reference's MLOps telemetry plane: ``MLOpsMetrics``
(``core/mlops/mlops_metrics.py``) publishes metrics/events over MQTT to a
backend, and ``mlops_runtime_log_daemon.py`` POSTs batched log lines.  Here
the SAME transports the FL protocol already rides carry the telemetry:

- :class:`RemoteObsShipper` (client side) buffers metric records, span
  events, and raw log-line batches, and flushes them as one OBS message
  (``MSG_TYPE_C2S_OBS``) to rank 0 through any ``send(Message)`` callable —
  INPROC, gRPC, TCP, or real MQTT alike.  The :class:`~fedml_tpu.obs.sampler.
  RuntimeLogDaemon` plugs in directly via ``shipper.log_lines`` as its sink.
- :class:`ObsCollector` (server side) registers on an existing comm manager,
  aggregates per-sender, and persists every record to a JSONL file — the
  cross-silo run becomes observable from the server without any extra
  connection or port.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from ..comm.message import Message
from . import registry as obsreg

#: C2S observability batch (cross-silo protocol ids 0-8 are taken;
#: collectors register this on the same comm manager as the FL protocol)
MSG_TYPE_C2S_OBS = 9

MSG_ARG_KEY_OBS_BATCH = "obs_batch"

OBS_SHIPPED = obsreg.REGISTRY.counter(
    "fedml_obs_records_shipped_total",
    "Telemetry records delivered to the server-side collector.",
)
OBS_DROPPED = obsreg.REGISTRY.counter(
    "fedml_obs_records_dropped_total",
    "Telemetry records lost after the bounded re-buffer retry.",
)
OBS_REBUFFERED = obsreg.REGISTRY.counter(
    "fedml_obs_records_rebuffered_total",
    "Telemetry records re-buffered once after a failed send.",
)


class RemoteObsShipper:
    """Buffer + batch telemetry records and ship them through ``send``.

    ``send`` is any callable taking a :class:`Message` (typically a comm
    manager's ``send_message``).  Records are flushed when ``flush_every``
    accumulate, every ``flush_interval_s`` (daemon thread, joined in
    ``close()``), and at ``close()``.  Shipping never raises into the
    training path; a failed send re-buffers the batch ONCE (bounded by
    ``max_rebuffer``) so a transient transport blip loses nothing, while a
    batch that fails twice is dropped — both outcomes land in the
    ``fedml_obs_records_*`` registry counters.
    """

    def __init__(self, send: Callable[[Message], None], rank: int,
                 flush_every: int = 16, flush_interval_s: float = 2.0,
                 receiver_id: int = 0, max_rebuffer: int = 256):
        self._send = send
        self.rank = rank
        self.receiver_id = receiver_id
        self.flush_every = flush_every
        self.flush_interval_s = flush_interval_s
        self.max_rebuffer = max_rebuffer
        self._buf: list[dict] = []
        self._rebuffer: list[dict] = []  # one failed batch awaiting its retry
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.shipped = 0
        self.dropped = 0
        self._thread: Optional[threading.Thread] = None
        if flush_interval_s > 0:
            self._thread = threading.Thread(
                target=self._flush_loop, args=(flush_interval_s,),
                name=f"fedml-obs-ship-{rank}", daemon=True,
            )
            self._thread.start()

    # -- record kinds ---------------------------------------------------------
    def metric(self, record: dict) -> None:
        self._push({"kind": "metric", **record})

    def event(self, name: str, phase: str, value=None, **extra) -> None:
        self._push({"kind": "event", "event": name, "phase": phase,
                    "value": value, **extra})

    def span(self, span, **extra) -> None:
        """Ship a finished :class:`~fedml_tpu.obs.trace.Span` (or a raw span
        record dict) — the trace identity travels with it, so the server-side
        trail can stitch client spans into the round's span tree."""
        record = span.to_record() if hasattr(span, "to_record") else dict(span)
        self._push({**record, **extra})

    def log_lines(self, lines: list[str]) -> None:
        """RuntimeLogDaemon sink signature: one record per batch of lines."""
        self._push({"kind": "log", "lines": list(lines)})

    def _push(self, record: dict) -> None:
        record.setdefault("ts", time.time())
        with self._lock:
            self._buf.append(record)
            ready = len(self._buf) >= self.flush_every
        if ready:
            self.flush()

    # -- shipping -------------------------------------------------------------
    def flush(self) -> int:
        with self._lock:
            retrying, self._rebuffer = self._rebuffer, []
            batch, self._buf = self._buf, []
        payload = retrying + batch
        if not payload:
            return 0
        msg = Message(MSG_TYPE_C2S_OBS, self.rank, self.receiver_id)
        msg.add_params(MSG_ARG_KEY_OBS_BATCH, json.dumps(payload))
        try:
            self._send(msg)
            # the flush thread and a caller-side flush can both land here:
            # the += must run under the lock or concurrent flushes lose counts
            with self._lock:
                self.shipped += len(payload)
            OBS_SHIPPED.inc(len(payload))
            return len(payload)
        except Exception:
            # best-effort: telemetry loss must never take down training.
            # Records that already failed once are dropped; fresh records get
            # ONE bounded second chance on the next flush.
            lost = len(retrying)
            keep = batch[-self.max_rebuffer:] if batch else []
            lost += len(batch) - len(keep)
            if lost:
                with self._lock:
                    self.dropped += lost
                OBS_DROPPED.inc(lost)
            if keep:
                OBS_REBUFFERED.inc(len(keep))
                with self._lock:
                    self._rebuffer = keep + self._rebuffer
            return 0

    def _flush_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.flush()

    def close(self) -> None:
        self._stop.set()
        self.flush()
        with self._lock:
            pending = bool(self._rebuffer)
        if pending:
            self.flush()  # the bounded retry of a batch that failed at close
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.flush_interval_s))
            self._thread = None


class ObsCollector:
    """Server-side telemetry aggregation + JSONL persistence.

    ``attach(comm_manager)`` registers the OBS handler on an existing
    manager (FL protocol and telemetry share one transport); records land in
    ``by_sender`` and, when ``jsonl_path`` is set, one JSON object per line
    tagged with the sender rank.  ``otlp`` (an
    :class:`~fedml_tpu.obs.otlp.OTLPExporter`) tees every span record of
    every ingested batch — the server's own rank-0 records AND the
    client-shipped ones — so rank 0 exports the WHOLE distributed round
    tree to a standard OpenTelemetry collector.

    ``stamp`` is merged into every ingested record (record keys win).  The
    multi-tenant control plane stamps ``{"job": <id>}`` so trail metric
    records from different tenants stay distinct series through
    ``trail_metrics_to_otlp`` instead of collapsing by metric name."""

    def __init__(self, jsonl_path: Optional[str] = None, otlp=None,
                 stamp: Optional[dict] = None):
        self.jsonl_path = jsonl_path
        self.otlp = otlp
        self.stamp = dict(stamp) if stamp else None
        self.by_sender: dict[int, list[dict]] = {}
        self._lock = threading.Lock()
        self._fh = open(jsonl_path, "a") if jsonl_path else None

    def attach(self, comm_manager) -> "ObsCollector":
        comm_manager.register_message_receive_handler(MSG_TYPE_C2S_OBS, self.handle)
        return self

    def handle(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        try:
            batch = json.loads(msg.get(MSG_ARG_KEY_OBS_BATCH))
        except (TypeError, ValueError):
            return  # malformed telemetry must never disturb the FL server
        self.ingest(sender, batch)

    def ingest(self, sender: int, batch: list[dict]) -> None:
        """Aggregate + persist a batch of records for ``sender``.  Also the
        server's own entry point: rank 0 records its round/aggregate spans
        into the same trail its clients ship to, so one JSONL holds the whole
        distributed round."""
        if self.stamp:
            batch = [{**self.stamp, **rec} if isinstance(rec, dict) else rec
                     for rec in batch]
        with self._lock:
            self.by_sender.setdefault(sender, []).extend(batch)
            if self._fh:
                for rec in batch:
                    self._fh.write(json.dumps({"sender": sender, **rec}) + "\n")
                self._fh.flush()
        if self.otlp is not None:
            try:
                self.otlp.tee(sender, batch)
            except Exception:
                pass  # export loss must never disturb the FL server

    # -- queries --------------------------------------------------------------
    def records(self, sender: Optional[int] = None, kind: Optional[str] = None) -> list[dict]:
        with self._lock:
            if sender is not None:
                pool = list(self.by_sender.get(sender, []))
            else:
                pool = [r for recs in self.by_sender.values() for r in recs]
        return [r for r in pool if kind is None or r.get("kind") == kind]

    def counts(self) -> dict[int, int]:
        with self._lock:
            return {s: len(r) for s, r in self.by_sender.items()}

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None
