"""Device perf sampler + runtime log daemon.

Parity with ``core/mlops/mlops_device_perfs.py:30`` (a background process
streaming CPU/memory/GPU utilization at an interval) and
``mlops_runtime_log_daemon.py:18`` (a daemon batching run log lines and
shipping them to the backend).  TPU translation:

- :class:`DevicePerfSampler` — a daemon thread sampling host CPU/memory
  (psutil when present, /proc fallback) and per-device accelerator memory
  (``jax.Device.memory_stats()``, which TPU backends expose) into a
  MetricsLogger sink — consumable as jsonl streams by any collector.
- :class:`RuntimeLogDaemon` — tails a log file, batches complete lines, and
  hands them to a sink callable (local default: an offset-tracked spool
  file; a SaaS uploader is just a different sink).
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Callable, Optional

from .metrics import MetricsLogger

try:  # psutil is optional; /proc fallback below
    import psutil as _psutil
except ImportError:  # pragma: no cover
    _psutil = None


def read_host_stats() -> dict:
    """CPU/memory utilization for this host (reference system_stats.py)."""
    out: dict = {}
    if _psutil is not None:
        out["cpu_utilization"] = _psutil.cpu_percent(interval=None)
        vm = _psutil.virtual_memory()
        out["system_memory_utilization"] = vm.percent
        p = _psutil.Process()
        out["process_memory_in_use_mb"] = p.memory_info().rss / 1e6
        out["process_cpu_threads_in_use"] = p.num_threads()
        return out
    # /proc fallback (linux)
    try:
        with open("/proc/loadavg") as f:
            out["loadavg_1m"] = float(f.read().split()[0])
        with open("/proc/meminfo") as f:
            mem = {l.split(":")[0]: int(l.split()[1]) for l in f if ":" in l}
        total, avail = mem.get("MemTotal", 1), mem.get("MemAvailable", 0)
        out["system_memory_utilization"] = round(100.0 * (1 - avail / total), 2)
        with open(f"/proc/{os.getpid()}/statm") as f:
            out["process_memory_in_use_mb"] = int(f.read().split()[1]) * 4096 / 1e6
    except OSError:
        pass
    return out


def read_device_stats() -> list[dict]:
    """Per-accelerator memory stats (the TPU stand-in for the reference's
    nvidia-smi GPU utilization stream)."""
    import jax

    devices = []
    for d in jax.local_devices():
        entry = {"device_id": d.id, "kind": getattr(d, "device_kind", d.platform)}
        try:
            stats = d.memory_stats() or {}
            entry["bytes_in_use"] = stats.get("bytes_in_use")
            entry["bytes_limit"] = stats.get("bytes_limit")
            if entry.get("bytes_limit"):
                entry["memory_utilization"] = round(
                    100.0 * (entry.get("bytes_in_use") or 0) / entry["bytes_limit"], 2
                )
        except Exception:
            pass  # not all backends expose memory_stats
        devices.append(entry)
    return devices


class DevicePerfSampler:
    """Stream host + device stats every ``interval_s`` to a MetricsLogger."""

    def __init__(self, logger: Optional[MetricsLogger] = None, interval_s: float = 10.0,
                 include_devices: bool = True):
        self.logger = logger or MetricsLogger(stdout=False)
        self.interval_s = interval_s
        self.include_devices = include_devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def sample_once(self) -> dict:
        sample = {"perf_ts": time.time(), **read_host_stats()}
        if self.include_devices:
            sample["devices"] = read_device_stats()
        self.logger.log(sample)
        self.samples += 1
        return sample

    def start(self) -> "DevicePerfSampler":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception:  # the sampler must never kill training
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class RuntimeLogDaemon:
    """Tail ``log_path``; every sweep, ship complete new lines to ``sink``
    (batched, offset-tracked — reference MLOpsRuntimeLogProcessor.log_upload)."""

    def __init__(self, log_path: str, sink: Optional[Callable[[list[str]], None]] = None,
                 spool_path: Optional[str] = None, interval_s: float = 2.0,
                 batch_lines: int = 1000):
        self.log_path = Path(log_path)
        self.interval_s = interval_s
        self.batch_lines = batch_lines
        self._offset = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if sink is None:
            spool = Path(spool_path or (str(log_path) + ".uploaded"))

            def sink(lines: list[str]) -> None:
                with open(spool, "a") as f:
                    f.writelines(l + "\n" for l in lines)

        self.sink = sink
        self.shipped = 0

    def sweep_once(self, final: bool = False) -> int:
        if not self.log_path.exists():
            return 0
        # truncation/rotation: a shrunken file means a new log generation —
        # restart from 0 or shipping silently stops forever
        if self.log_path.stat().st_size < self._offset:
            self._offset = 0
        with open(self.log_path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read()
        if not chunk:
            return 0
        # only complete lines ship; a trailing partial waits for the next
        # sweep — EXCEPT on the final drain, where it would be lost forever
        # (a crash's last line is usually the diagnostic one)
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0 and not final:
            return 0
        complete = chunk if final else chunk[: last_nl + 1]
        self._offset += len(complete)
        lines = complete.decode(errors="replace").splitlines()
        for i in range(0, len(lines), self.batch_lines):
            self.sink(lines[i : i + self.batch_lines])
        self.shipped += len(lines)
        return len(lines)

    def start(self) -> "RuntimeLogDaemon":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sweep_once()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.sweep_once(final=True)  # final drain ships trailing partials too
