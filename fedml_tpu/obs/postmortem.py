"""Causal postmortem over flight-recorder bundles (ISSUE 16 tentpole, part c).

One crashed run leaves several black boxes behind: the killed server's
``hard_kill`` bundle, the recovered server's ``finish`` bundle, the fleet's
(or each client's) bundle, every ``accounting_violation`` / ``slo_breach``
dump.  Each ring only knows its own process.  :func:`stitch_bundles` joins
them — by upload idempotence key, session epoch, and wall-clock — into one
causal picture that answers the questions a human asks first after a
failure:

- **What was in flight at the kill?**  The ``hard_kill`` trigger context
  carries the dispatch ledger snapshot; the timeline shows which of those
  slots later refolded under the next epoch, which came back as
  deterministic stale rejections, and which were re-issued by the watchdog.
- **Which uploads were lost, and why?**  Every upload key a sender recorded
  (fleet ``reply`` / client ``upload_sent``) is matched against the server's
  ``upload`` notes (fold / buffer / refold / dedup / stale).  Keys the
  server never saw are attributed: in the killed server's dispatch ledger,
  sent into the kill→recovery gap, sent under a session epoch a kill
  terminated (in transit or unjournaled when the process died), a
  final-round straggler the closing round outran, eaten by an injected
  silent chaos fault (drop / corrupt / partition_lost), or — the red flag
  the whole exercise exists to catch — unattributed.
- **Which SLO broke first?**  Breach notes across all bundles, ordered.

The output of :func:`stitch_bundles` is a plain JSON-able dict;
:func:`render_postmortem` formats it for terminals.  ``fedml-tpu obs
postmortem <dir>`` wires both to the CLI.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Union

from . import flight

__all__ = ["stitch_bundles", "render_postmortem"]

#: upload-note paths meaning "the bytes reached the aggregator"
_ARRIVED_PATHS = ("fold", "buffer", "refold")

#: chaos faults that silently eat a frame (must mirror
#: ``comm.chaos.SILENT_LOSS_FAULTS``; duplicated here so the postmortem can
#: read bundles without importing the comm stack)
_SILENT_FAULTS = ("drop", "corrupt", "partition_lost")


def _load(source: Union[str, list]) -> list[dict]:
    """Bundles from a directory (recursive), one file path, or a pre-read
    list of bundle dicts.  Unreadable/corrupt bundles are skipped — a
    postmortem must work on whatever survived."""
    if isinstance(source, list) and source and isinstance(source[0], dict):
        return list(source)
    paths = ([source] if isinstance(source, str) and os.path.isfile(source)
             else flight.list_bundles(str(source)))
    bundles = []
    for p in paths:
        try:
            bundles.append(flight.read_bundle(p))
        except (OSError, ValueError):
            continue
    return bundles


def _epoch_of(rec: dict) -> Optional[int]:
    try:
        return int(rec.get("epoch"))
    except (TypeError, ValueError):
        return None


def _src(bundle: dict) -> str:
    m = bundle.get("meta", {})
    return f"{m.get('name', '?')}[{m.get('reason', '?')}]"


def stitch_bundles(source: Union[str, list],
                   window_s: float = 0.0) -> dict:
    """Join every readable bundle under ``source`` into one causal summary.

    ``window_s`` > 0 trims the merged timeline to the trailing window before
    the newest event (the ledger always uses every event)."""
    bundles = _load(source)
    timeline: list[dict] = []
    sent: dict[str, dict] = {}          # upload key -> sender-side record
    outcome: dict[str, dict] = {}       # upload key -> server-side record
    kills: list[dict] = []
    recoveries: list[dict] = []
    breaches: list[dict] = []
    triggers: list[dict] = []
    chaos: dict[str, dict[str, int]] = {}
    dispatches: list[dict] = []
    drops: list[dict] = []
    accounting: Optional[dict] = None

    for b in bundles:
        src = _src(b)
        reason = b.get("meta", {}).get("reason")
        ctx = b.get("context") or {}
        if reason == "hard_kill":
            kills.append({"src": src, "ts": b.get("meta", {}).get("ts"),
                          "context": ctx})
        if reason in ("soak_finish", "accounting_violation"):
            accounting = dict(ctx)
        for e in b.get("events", []):
            kind = e.get("kind")
            if kind == "chaos":
                # post-hoc notes: end-of-run timestamps — ledger only
                leg = str(e.get("leg", "?"))
                fault = str(e.get("fault", "?"))
                chaos.setdefault(leg, {})
                chaos[leg][fault] = chaos[leg].get(fault, 0) + 1
                continue
            timeline.append({**e, "src": src})
            if kind in ("reply", "upload_sent"):
                key = e.get("key")
                if key is not None:
                    sent.setdefault(str(key), {**e, "src": src})
            elif kind == "upload":
                key = e.get("key")
                if key is not None:
                    outcome[str(key)] = {**e, "src": src}
            elif kind == "epoch" and e.get("event") == "recovery":
                recoveries.append({**e, "src": src})
            elif kind == "slo_breach":
                breaches.append({**e, "src": src})
            elif kind == "trigger":
                triggers.append({**e, "src": src})
            elif kind == "dispatch":
                dispatches.append(e)
            elif kind == "drop":
                drops.append({**e, "src": src})

    timeline.sort(key=lambda e: e.get("ts", 0.0))
    if window_s and window_s > 0 and timeline:
        cut = timeline[-1].get("ts", 0.0) - window_s
        timeline = [e for e in timeline if e.get("ts", 0.0) >= cut]
    breaches.sort(key=lambda e: e.get("ts", 0.0))
    recoveries.sort(key=lambda e: e.get("ts", 0.0))

    # -- kill → recovery gaps: an upload sent into a gap reached nobody ------
    kill_triggers = sorted(
        (t for t in triggers if t.get("reason") == "hard_kill"),
        key=lambda t: t.get("ts", 0.0))
    gaps: list[tuple[float, float]] = []
    for kt in kill_triggers:
        t0 = float(kt.get("ts", 0.0))
        t1 = min((float(r.get("ts", 0.0)) for r in recoveries
                  if float(r.get("ts", 0.0)) >= t0), default=float("inf"))
        gaps.append((t0, t1))

    # -- upload ledger --------------------------------------------------------
    arrived = {p: 0 for p in _ARRIVED_PATHS}
    deduped = stale = 0
    for rec in outcome.values():
        path = rec.get("path")
        if path in arrived:
            arrived[path] += 1
        elif path == "dedup":
            deduped += 1
        elif path == "stale":
            stale += 1
    # dedup/stale notes name keys whose FIRST copy may live only in the
    # server's journaled key table (pre-crash folds): count them as seen
    lost_keys = [k for k in sent if k not in outcome]
    # the dispatch ledger a killed server dumped in its trigger context:
    # those (client, version) slots were awaiting an answer when the process
    # died — an upload matching one of them vanished WITH the server
    kill_ledger: set[tuple] = set()
    kill_epochs: set[int] = set()
    for k in kills:
        ctx = k.get("context") or {}
        for table in ("outstanding", "prev_epoch_inflight"):
            for cid, ver in (ctx.get(table) or {}).items():
                kill_ledger.add((int(cid), int(ver)))
        try:
            kill_epochs.add(int(ctx.get("epoch")))
        except (TypeError, ValueError):
            pass
    # the run's end: after the final virtual-round close the server ignores
    # stragglers by design (`_finished` latches before the finish broadcast
    # reaches anyone still training).  The final round's own version matters
    # too: a reply for that version which never arrived can only be a
    # straggler the closing round outran — the round reached quorum on other
    # clients' arrivals while this one was still in transit, so its sent ts
    # lands a few ms BEFORE the close event (wall clock alone misses it)
    vr_events = [e for e in timeline if e.get("kind") == "virtual_round"]
    end_ts = max((float(e.get("ts", 0.0)) for e in vr_events),
                 default=float("inf"))
    final_version: Optional[int] = None
    if vr_events:
        last_vr = max(vr_events, key=lambda e: float(e.get("ts", 0.0)))
        try:
            final_version = int(last_vr.get("version"))
        except (TypeError, ValueError):
            final_version = None
    # only UPLOAD-leg silent faults eat a sent key; dispatch-leg faults mean
    # the client never got work, so no reply existed to lose
    silent_budget = sum(n for f, n in chaos.get("upload", {}).items()
                        if f in _SILENT_FAULTS)
    lost: list[dict] = []
    for k in sorted(lost_keys, key=lambda k: sent[k].get("ts", 0.0)):
        rec = sent[k]
        ts = float(rec.get("ts", 0.0))
        client = rec.get("client", rec.get("rank"))
        version = rec.get("version", rec.get("round_idx"))
        try:
            slot = (int(client), int(version))
        except (TypeError, ValueError):
            slot = None
        if slot in kill_ledger:
            attribution = "in_flight_at_kill"
        elif any(g[0] <= ts <= g[1] for g in gaps):
            attribution = "in_kill_gap"
        elif _epoch_of(rec) in kill_epochs:
            # sent under a session epoch a kill terminated and never seen by
            # the server: either still in transit when the process died (the
            # dispatch ledger misses superseded versions — a v reply in
            # flight after the client was re-dispatched v+1), or folded into
            # state the kill destroyed before a journal snapshot.  Both are
            # the kill's doing — the journal fence makes everything
            # unjournaled in a killed epoch an expected casualty
            attribution = "in_killed_epoch"
        elif ts >= end_ts or (final_version is not None
                              and slot is not None
                              and slot[1] >= final_version):
            attribution = "post_finish"
        elif silent_budget > 0:
            silent_budget -= 1
            attribution = "chaos_silent_loss"
        else:
            attribution = "unattributed"
        lost.append({"key": k, "client": client, "version": version,
                     "epoch": rec.get("epoch"), "ts": ts,
                     "attribution": attribution})
    unattributed = sum(1 for r in lost if r["attribution"] == "unattributed")

    # -- dispatch ledger: dispatches that never produced a reply --------------
    replied = {(r.get("client"), r.get("version"))
               for r in sent.values() if r.get("kind") == "reply"}
    unanswered = [d for d in dispatches
                  if (d.get("client"), d.get("version")) not in replied]

    return {
        "bundles": [{"path": b.get("path"), **{k: b.get("meta", {}).get(k)
                     for k in ("name", "reason", "pid", "seq", "ts",
                               "n_events")}} for b in bundles],
        "timeline": timeline,
        "kills": kills,
        "recoveries": recoveries,
        "slo_breaches": breaches,
        "first_breach": breaches[0] if breaches else None,
        "uploads": {
            "sent": len(sent),
            "arrived": arrived,
            "deduped": deduped,
            "rejected_stale": stale,
            "lost": lost,
            "unattributed_lost": unattributed,
        },
        "chaos": chaos,
        "drops_at_sender": len(drops),
        "dispatches": {"total": len(dispatches),
                       "unanswered": len(unanswered)},
        "accounting": accounting,
        "unaccounted": (accounting or {}).get("unaccounted"),
    }


def _fmt_event(e: dict, t0: float) -> str:
    ts = e.get("ts", 0.0) - t0
    kind = e.get("kind", "?")
    skip = {"ts", "kind", "src", "delta"}
    fields = " ".join(f"{k}={e[k]}" for k in sorted(e)
                      if k not in skip and not isinstance(e[k], (dict, list)))
    if kind == "metrics_delta":
        fields = f"{len(e.get('delta') or {})} series moved"
    return f"  +{ts:9.3f}s  {e.get('src', '?'):<24} {kind:<14} {fields}"


def render_postmortem(stitched: dict, limit: int = 40) -> str:
    """Terminal rendering of a stitched postmortem (most recent ``limit``
    timeline events; ``limit <= 0`` renders the whole timeline)."""
    out: list[str] = []
    bundles = stitched.get("bundles", [])
    out.append(f"flight postmortem: {len(bundles)} bundle(s)")
    for b in bundles:
        out.append(f"  {b.get('name')}.{b.get('pid')}.{b.get('seq', 0):04d} "
                   f"reason={b.get('reason')} events={b.get('n_events')}")
    timeline = stitched.get("timeline", [])
    if timeline:
        t0 = timeline[0].get("ts", 0.0)
        shown = timeline if limit <= 0 else timeline[-limit:]
        out.append("")
        out.append(f"timeline ({len(shown)}/{len(timeline)} events, "
                   f"t0={t0:.3f}):")
        out.extend(_fmt_event(e, t0) for e in shown)

    kills = stitched.get("kills", [])
    if kills:
        out.append("")
        out.append("kills:")
        for k in kills:
            ctx = k.get("context") or {}
            inflight = ctx.get("outstanding") or ctx.get("awaiting") or {}
            n = len(inflight)
            out.append(f"  {k.get('src')}: {n} in flight at the kill "
                       f"(epoch {ctx.get('epoch')}, "
                       f"version {ctx.get('server_version', ctx.get('round_idx'))})")
    for r in stitched.get("recoveries", []):
        out.append(f"  recovered: {r.get('src')} step={r.get('step')} "
                   f"version={r.get('version', r.get('round_idx'))} "
                   f"epoch={r.get('epoch')}")

    up = stitched.get("uploads", {})
    out.append("")
    arrived = up.get("arrived", {})
    out.append(f"upload ledger: {up.get('sent', 0)} sent — "
               f"{sum(arrived.values())} arrived "
               f"({', '.join(f'{k}={v}' for k, v in sorted(arrived.items()))}), "
               f"{up.get('deduped', 0)} deduped, "
               f"{up.get('rejected_stale', 0)} stale-rejected, "
               f"{len(up.get('lost', []))} lost")
    for rec in up.get("lost", []):
        out.append(f"  lost {rec['key']} (client {rec['client']}, "
                   f"version {rec['version']}, epoch {rec['epoch']}) "
                   f"-> {rec['attribution']}")
    chaos = stitched.get("chaos", {})
    if chaos:
        parts = [f"{leg}: " + ", ".join(f"{f}={n}" for f, n in sorted(v.items()))
                 for leg, v in sorted(chaos.items())]
        out.append(f"chaos injected — {'; '.join(parts)}")
    if stitched.get("drops_at_sender"):
        out.append(f"sender-side drops (never sent): "
                   f"{stitched['drops_at_sender']}")
    disp = stitched.get("dispatches", {})
    if disp.get("total"):
        out.append(f"dispatch ledger: {disp['total']} dispatches, "
                   f"{disp['unanswered']} never answered "
                   f"(redispatched, throttled, or in flight at a kill)")

    fb = stitched.get("first_breach")
    if fb is not None:
        out.append("")
        out.append(f"FIRST SLO BREACH: {fb.get('slo')} — "
                   f"{fb.get('metric')} {fb.get('stat')} {fb.get('op')} "
                   f"{fb.get('threshold')} (value {fb.get('value')}) "
                   f"at ts={fb.get('ts')}")
    elif stitched.get("slo_breaches") is not None:
        out.append("slo: no breaches recorded")

    acc = stitched.get("accounting")
    if acc is not None:
        out.append("")
        verdict = ("OK — every loss accounted"
                   if not acc.get("unaccounted") else
                   f"VIOLATION — {acc.get('unaccounted')} loss(es) unaccounted")
        out.append(f"accounting: {verdict}")
        fields = " ".join(f"{k}={v}" for k, v in sorted(acc.items())
                          if not isinstance(v, (dict, list)))
        out.append(f"  {fields}")
    unattributed = up.get("unattributed_lost", 0)
    if unattributed:
        out.append(f"WARNING: {unattributed} lost upload(s) have no cause — "
                   f"not in a kill gap, beyond the injected chaos budget")
    return "\n".join(out)
