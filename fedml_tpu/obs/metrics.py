"""Metrics / events / spans with pluggable sinks.

TPU-native replacement for ``core/mlops`` (SURVEY.md §2.12/§5): the reference
ships metrics over MQTT to a SaaS backend (``MLOpsMetrics``,
``mlops_profiler_event.py:9``); here the same call shapes write to pluggable
sinks — stdout, JSONL file, or an in-memory buffer (tests) — and spans use
``jax.profiler`` trace annotations so they show up in TPU profiles.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from contextlib import contextmanager
from typing import Any, Optional

import jax

log = logging.getLogger("fedml_tpu")


class MetricsLogger:
    """``mlops.log(...)`` equivalent (``core/mlops/__init__.py:172``)."""

    def __init__(self, jsonl_path: Optional[str] = None, stdout: bool = True):
        self.jsonl_path = jsonl_path
        self.stdout = stdout
        self.records: list[dict] = []
        self._fh = open(jsonl_path, "a") if jsonl_path else None

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        rec = {k: (float(v) if hasattr(v, "__float__") else v) for k, v in metrics.items()}
        if step is not None:
            rec["step"] = step
        rec["ts"] = time.time()
        self.records.append(rec)
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        if self.stdout:
            items = " ".join(
                f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in rec.items() if k != "ts"
            )
            log.info("metrics %s", items)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


class EventTracer:
    """Span events (``MLOpsProfilerEvent`` ``mlops_profiler_event.py:9``):
    ``started/ended`` pairs, mirrored into jax.profiler TraceAnnotation so
    spans land in XLA device profiles."""

    def __init__(self, logger: Optional[MetricsLogger] = None):
        self.logger = logger
        self.events: list[dict] = []

    def log_event_started(self, name: str, value: Any = None) -> None:
        self.events.append({"event": name, "phase": "started", "value": value, "ts": time.time()})

    def log_event_ended(self, name: str, value: Any = None) -> None:
        self.events.append({"event": name, "phase": "ended", "value": value, "ts": time.time()})

    @contextmanager
    def span(self, name: str, value: Any = None):
        self.log_event_started(name, value)
        with jax.profiler.TraceAnnotation(name):
            try:
                yield
            finally:
                self.log_event_ended(name, value)
