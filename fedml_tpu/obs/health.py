"""Per-client health ledger — what telemetry learns feeds back into selection.

The communication-perspective FL surveys (PAPERS.md, arxiv 2405.20431) name
client heterogeneity and straggler variance as the dominant cross-silo
bottleneck; PR 1 measured it (per-client RTT histogram, straggler-timeout
quorum) but the server kept sampling degraded ranks anyway.  This ledger
folds the three signals the server already observes into one health score
per client:

- **EWMA round trip** — the same broadcast-to-reply RTT the
  ``fedml_crosssilo_client_round_trip_seconds`` histogram observes, smoothed
  per client (``ewma_alpha``);
- **deadline breaches** — selected-but-missing when a straggler timeout
  fires and the round proceeds on quorum (``_on_straggler_timeout``);
- **comm failures** — per-client broadcast send errors, plus process-wide
  transport drop/retry pressure via the comm layer's event sinks.

Scores live in ``[0, 1]`` (1 = healthy), decay back toward healthy on every
successful round trip (``recovery``), and are exported as
``fedml_client_health_*`` gauges.  ``FedMLAggregator.client_selection``
consults ``partition()`` behind ``extra.health_aware_selection`` to
deprioritize degraded ranks: healthy clients are sampled first, degraded
ones fill remaining slots best-score-first — a rank is deprioritized, never
permanently evicted, so a recovered client re-enters the pool.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Optional

from . import registry as obsreg

__all__ = ["ClientHealthLedger", "health_summary_from_registry"]

HEALTH_SCORE = obsreg.REGISTRY.gauge(
    "fedml_client_health_score",
    "Per-client health in [0,1] (1 = healthy): EWMA RTT vs the fleet, "
    "deadline breaches, comm failures.  Feeds health-aware selection.",
    labels=("client",),
)
HEALTH_EWMA_RTT = obsreg.REGISTRY.gauge(
    "fedml_client_health_ewma_rtt_seconds",
    "EWMA of the broadcast-to-reply round trip, by client rank.",
    labels=("client",),
)
HEALTH_BREACHES = obsreg.REGISTRY.gauge(
    "fedml_client_health_deadline_breaches",
    "Decayed count of straggler-deadline breaches, by client rank.",
    labels=("client",),
)
HEALTH_COMM_FAILURES = obsreg.REGISTRY.gauge(
    "fedml_client_health_comm_failures",
    "Decayed count of per-client transport failures, by client rank.",
    labels=("client",),
)


class ClientHealthLedger:
    """Thread-safe per-client health state + the selection-facing queries.

    The score is multiplicative so each signal degrades independently:
    ``1/(1 + breach_weight*breaches)`` x ``1/(1 + comm_weight*failures)``
    x an RTT factor that only kicks in when a client's EWMA round trip
    exceeds ``rtt_degraded_factor`` x the fleet median (absolute RTTs vary
    by deployment; the *ratio* flags the straggler).
    """

    def __init__(self, ewma_alpha: float = 0.3, breach_weight: float = 0.5,
                 comm_weight: float = 0.25, rtt_degraded_factor: float = 3.0,
                 recovery: float = 0.25, degraded_threshold: float = 0.5):
        self.ewma_alpha = float(ewma_alpha)
        self.breach_weight = float(breach_weight)
        self.comm_weight = float(comm_weight)
        self.rtt_degraded_factor = float(rtt_degraded_factor)
        self.recovery = float(recovery)
        self.degraded_threshold = float(degraded_threshold)
        self._lock = threading.Lock()
        self._clients: dict[int, dict] = {}
        # process-wide transport pressure (unattributable to one client:
        # drops happen before the sender is decodable)
        self.comm_drops = 0
        self.comm_retries = 0
        self._comm_sink = None

    def _entry(self, client) -> dict:
        return self._clients.setdefault(int(client), {
            "ewma_rtt_s": None, "rtts": 0, "breaches": 0.0, "comm_failures": 0.0,
        })

    # -- signal intake --------------------------------------------------------
    def observe_rtt(self, client, rtt_s: float) -> None:
        """A completed round trip: update the EWMA and decay the failure
        counts — successful replies are the evidence of recovery."""
        with self._lock:
            e = self._entry(client)
            prev = e["ewma_rtt_s"]
            e["ewma_rtt_s"] = (float(rtt_s) if prev is None
                               else self.ewma_alpha * float(rtt_s)
                               + (1.0 - self.ewma_alpha) * prev)
            e["rtts"] += 1
            e["breaches"] *= (1.0 - self.recovery)
            e["comm_failures"] *= (1.0 - self.recovery)
        self._export(int(client))

    def record_deadline_breach(self, client) -> None:
        with self._lock:
            self._entry(client)["breaches"] += 1.0
        self._export(int(client))

    def record_comm_failure(self, client, n: float = 1.0) -> None:
        with self._lock:
            self._entry(client)["comm_failures"] += float(n)
        self._export(int(client))

    def attach_comm(self) -> "ClientHealthLedger":
        """Subscribe to the comm layer's process-wide drop/retry events
        (``comm.base.add_comm_event_sink``); idempotent.

        Events that name a sender (``client=``, e.g. an evicted chunk
        stream or a corrupt async upload) additionally accrue per-client
        failure pressure — the receive-loop counterpart of the send-side
        ``record_comm_failure`` the broadcast path already feeds, so async
        arrivals degrade a flaky client's score the same way synchronous
        broadcasts do.  Unattributable events only move the process-wide
        counters."""
        if self._comm_sink is None:
            from ..comm import base as comm_base

            def sink(event: str, client=None, **_info):
                with self._lock:
                    if event == "dropped":
                        self.comm_drops += 1
                    elif event == "retried":
                        self.comm_retries += 1
                if client is not None:
                    # outside self._lock: record_comm_failure locks itself
                    self.record_comm_failure(
                        client, n=1.0 if event == "dropped" else 0.25)

            self._comm_sink = comm_base.add_comm_event_sink(sink)
        return self

    def detach_comm(self) -> None:
        if self._comm_sink is not None:
            from ..comm import base as comm_base

            comm_base.remove_comm_event_sink(self._comm_sink)
            self._comm_sink = None

    # -- scoring --------------------------------------------------------------
    def _fleet_median_rtt_locked(self) -> Optional[float]:
        vals = sorted(e["ewma_rtt_s"] for e in self._clients.values()
                      if e["ewma_rtt_s"])
        return vals[len(vals) // 2] if vals else None

    def _score_locked(self, client: int) -> float:
        e = self._clients.get(client)
        if e is None:
            return 1.0  # never observed = assumed healthy
        s = 1.0 / (1.0 + self.breach_weight * e["breaches"])
        s *= 1.0 / (1.0 + self.comm_weight * e["comm_failures"])
        med = self._fleet_median_rtt_locked()
        ewma = e["ewma_rtt_s"]
        if med and ewma and ewma > self.rtt_degraded_factor * med:
            s *= (self.rtt_degraded_factor * med) / ewma
        return s

    def score(self, client) -> float:
        with self._lock:
            return self._score_locked(int(client))

    def partition(self, client_ids: Iterable) -> tuple[list, list]:
        """(healthy, degraded) split of ``client_ids`` at
        ``degraded_threshold``; degraded comes back best-score-first so the
        caller can fill remaining slots with the least-bad ranks."""
        with self._lock:
            scored = [(self._score_locked(int(c)), c) for c in client_ids]
        healthy = [c for s, c in scored if s >= self.degraded_threshold]
        degraded = [c for s, c in sorted(
            (sc for sc in scored if sc[0] < self.degraded_threshold),
            key=lambda t: t[0], reverse=True)]
        return healthy, degraded

    # -- export ---------------------------------------------------------------
    def _export(self, client: int) -> None:
        with self._lock:
            e = self._clients.get(client)
            if e is None:
                return
            score = self._score_locked(client)
            ewma = e["ewma_rtt_s"] or 0.0
            breaches, failures = e["breaches"], e["comm_failures"]
        label = str(client)
        HEALTH_SCORE.set(score, client=label)
        HEALTH_EWMA_RTT.set(ewma, client=label)
        HEALTH_BREACHES.set(breaches, client=label)
        HEALTH_COMM_FAILURES.set(failures, client=label)

    def export_state(self) -> dict:
        """JSON-able snapshot of the raw per-client signal state (EWMA RTT,
        breach/failure counts) for the server recovery journal — the inverse
        of :meth:`import_state`.  Scores are derived, so they are not stored."""
        with self._lock:
            return {str(cid): dict(e) for cid, e in sorted(self._clients.items())}

    def import_state(self, state: dict) -> None:
        """Install a journaled :meth:`export_state` snapshot (recovery path):
        a restarted server remembers which clients were degraded instead of
        re-learning it one breach at a time."""
        if not state:
            return
        with self._lock:
            for cid, e in state.items():
                entry = self._entry(int(cid))
                for k in ("ewma_rtt_s", "rtts", "breaches", "comm_failures"):
                    if k in e:
                        entry[k] = e[k]
        for cid in state:
            self._export(int(cid))

    def summary(self) -> dict:
        """{client: {score, ewma_rtt_s, rtts, breaches, comm_failures}} plus
        the process-wide comm pressure under the ``_comm`` key."""
        with self._lock:
            out = {
                cid: {
                    "score": round(self._score_locked(cid), 4),
                    "ewma_rtt_s": round(e["ewma_rtt_s"], 6) if e["ewma_rtt_s"] else None,
                    "rtts": e["rtts"],
                    "breaches": round(e["breaches"], 4),
                    "comm_failures": round(e["comm_failures"], 4),
                }
                for cid, e in sorted(self._clients.items())
            }
            out["_comm"] = {"drops": self.comm_drops, "retries": self.comm_retries}
        return out

    def records(self, trace_id: Optional[str] = None) -> list[dict]:
        """Collector-trail metric records (one per client) so the health
        trajectory persists in the same JSONL the spans land in and
        ``fedml-tpu obs report`` can render it."""
        now = time.time()
        summary = self.summary()
        out = []
        for cid, e in summary.items():
            if cid == "_comm":
                continue
            rec = {"kind": "metric", "metric": "client_health", "client": cid,
                   "ts": now, **e}
            if trace_id:
                rec["trace_id"] = trace_id
            out.append(rec)
        return out


def health_summary_from_registry() -> dict:
    """{client: score} read back from the global gauges — lets ``bench.py``
    record a health summary without holding a ledger reference."""
    fam = HEALTH_SCORE._snapshot()
    return {s["labels"]["client"]: round(s["value"], 4) for s in fam["samples"]}
