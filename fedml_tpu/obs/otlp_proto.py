"""Minimal OTLP protobuf wire-format writer — stdlib only (ISSUE 16).

Some collectors reject OTLP/HTTP JSON outright (``415 Unsupported Media
Type``) or mangle it (strict proto3-JSON parsers balk at our payloads'
int64-as-string fields); the protocol's mandatory encoding is binary
protobuf.  Pulling in ``protobuf``/``opentelemetry-proto`` would break the
repo's no-new-deps rule, so this module hand-encodes the exact two request
shapes :mod:`fedml_tpu.obs.otlp` already builds — the proto3-JSON dicts
from ``spans_to_otlp`` / ``metrics_snapshot_to_otlp`` /
``trail_metrics_to_otlp`` — into ``ExportTraceServiceRequest`` /
``ExportMetricsServiceRequest`` wire bytes.

Field numbers are transcribed from opentelemetry-proto v1 (``trace.proto``,
``metrics.proto``, ``common.proto``, ``resource.proto``); a golden-bytes
test pins the output against a hand-decoded fixture so a transcription
slip cannot land silently.

Encoding rules (what a conformant decoder expects):

- scalar fields at their proto3 default (0 / "" / false) are omitted,
  EXCEPT oneof members (``AnyValue`` variants, data-point ``as_double`` /
  ``as_int``) and ``optional``-marked fields (``HistogramDataPoint.sum``),
  which are emitted whenever the JSON payload carries them;
- 64-bit timestamp fields arrive as decimal strings (proto3-JSON int64)
  and leave as fixed64;
- hex trace/span ids become raw bytes;
- ``bucket_counts`` / ``explicit_bounds`` use packed encoding.
"""

from __future__ import annotations

import struct

__all__ = ["encode_trace_request", "encode_metrics_request", "encode_request"]

_MASK64 = (1 << 64) - 1


# ---------------------------------------------------------------------------
# wire primitives


def _varint(n: int) -> bytes:
    n &= _MASK64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _str_field(field: int, s, *, always: bool = False) -> bytes:
    data = str(s).encode("utf-8") if s is not None else b""
    if not data and not always:
        return b""
    return _len_field(field, data)


def _varint_field(field: int, n: int, *, always: bool = False) -> bytes:
    n = int(n)
    if not n and not always:
        return b""
    return _tag(field, 0) + _varint(n)


def _fixed64_field(field: int, n: int, *, always: bool = False) -> bytes:
    n = int(n) & _MASK64
    if not n and not always:
        return b""
    return _tag(field, 1) + struct.pack("<Q", n)


def _double_field(field: int, v: float, *, always: bool = False) -> bytes:
    v = float(v)
    if v == 0.0 and not always:
        return b""
    return _tag(field, 1) + struct.pack("<d", v)


def _i64(v) -> int:
    """proto3-JSON int64 fields arrive as decimal strings (or ints)."""
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


def _id_bytes(hex_id) -> bytes:
    s = str(hex_id or "")
    try:
        return bytes.fromhex(s)
    except ValueError:
        return b""


# ---------------------------------------------------------------------------
# common.proto / resource.proto


def _any_value(av: dict) -> bytes:
    # oneof: the set member is serialized even at its default value
    if "stringValue" in av:
        return _str_field(1, av["stringValue"], always=True)
    if "boolValue" in av:
        return _varint_field(2, 1 if av["boolValue"] else 0, always=True)
    if "intValue" in av:
        return _varint_field(3, _i64(av["intValue"]), always=True)
    if "doubleValue" in av:
        return _double_field(4, av["doubleValue"], always=True)
    if "arrayValue" in av:
        inner = b"".join(_len_field(1, _any_value(v))
                         for v in av["arrayValue"].get("values", ()))
        return _len_field(5, inner)
    if "kvlistValue" in av:
        inner = b"".join(_len_field(1, _key_value(kv))
                         for kv in av["kvlistValue"].get("values", ()))
        return _len_field(6, inner)
    if "bytesValue" in av:
        import base64
        return _len_field(7, base64.b64decode(av["bytesValue"]))
    return b""


def _key_value(kv: dict) -> bytes:
    return _str_field(1, kv.get("key", "")) + _len_field(2, _any_value(kv.get("value", {})))


def _attributes(field: int, attrs) -> bytes:
    return b"".join(_len_field(field, _key_value(kv)) for kv in (attrs or ()))


def _resource(res: dict) -> bytes:
    return _attributes(1, res.get("attributes"))


def _scope(scope: dict) -> bytes:
    return _str_field(1, scope.get("name", ""))


# ---------------------------------------------------------------------------
# trace.proto


def _span(span: dict) -> bytes:
    out = [
        _len_field(1, _id_bytes(span.get("traceId"))),
        _len_field(2, _id_bytes(span.get("spanId"))),
    ]
    parent = _id_bytes(span.get("parentSpanId"))
    if parent:
        out.append(_len_field(4, parent))
    out.append(_str_field(5, span.get("name", "")))
    out.append(_varint_field(6, int(span.get("kind", 0))))
    out.append(_fixed64_field(7, _i64(span.get("startTimeUnixNano"))))
    out.append(_fixed64_field(8, _i64(span.get("endTimeUnixNano"))))
    out.append(_attributes(9, span.get("attributes")))
    return b"".join(out)


def _scope_spans(ss: dict) -> bytes:
    out = [_len_field(1, _scope(ss.get("scope", {})))]
    out += [_len_field(2, _span(s)) for s in ss.get("spans", ())]
    return b"".join(out)


def _resource_spans(rs: dict) -> bytes:
    out = [_len_field(1, _resource(rs.get("resource", {})))]
    out += [_len_field(2, _scope_spans(ss)) for ss in rs.get("scopeSpans", ())]
    return b"".join(out)


def encode_trace_request(payload: dict) -> bytes:
    """``spans_to_otlp`` JSON body -> ``ExportTraceServiceRequest`` bytes."""
    return b"".join(_len_field(1, _resource_spans(rs))
                    for rs in payload.get("resourceSpans", ()))


# ---------------------------------------------------------------------------
# metrics.proto


def _number_data_point(dp: dict) -> bytes:
    out = [
        _fixed64_field(2, _i64(dp.get("startTimeUnixNano"))),
        _fixed64_field(3, _i64(dp.get("timeUnixNano"))),
    ]
    if "asDouble" in dp:  # oneof value
        out.append(_double_field(4, dp["asDouble"], always=True))
    elif "asInt" in dp:
        out.append(_tag(6, 1) + struct.pack("<q", _i64(dp["asInt"])))
    out.append(_attributes(7, dp.get("attributes")))
    return b"".join(out)


def _histogram_data_point(dp: dict) -> bytes:
    out = [
        _fixed64_field(2, _i64(dp.get("startTimeUnixNano"))),
        _fixed64_field(3, _i64(dp.get("timeUnixNano"))),
        _fixed64_field(4, _i64(dp.get("count"))),
    ]
    if "sum" in dp:  # optional field: present in JSON -> emitted
        out.append(_double_field(5, dp["sum"], always=True))
    counts = dp.get("bucketCounts") or ()
    if counts:
        packed = b"".join(struct.pack("<Q", _i64(c) & _MASK64) for c in counts)
        out.append(_len_field(6, packed))
    bounds = dp.get("explicitBounds") or ()
    if bounds:
        packed = b"".join(struct.pack("<d", float(b)) for b in bounds)
        out.append(_len_field(7, packed))
    out.append(_attributes(9, dp.get("attributes")))
    return b"".join(out)


def _metric(m: dict) -> bytes:
    out = [_str_field(1, m.get("name", "")),
           _str_field(2, m.get("description", "")),
           _str_field(3, m.get("unit", ""))]
    if "gauge" in m:
        inner = b"".join(_len_field(1, _number_data_point(dp))
                         for dp in m["gauge"].get("dataPoints", ()))
        out.append(_len_field(5, inner))
    elif "sum" in m:
        s = m["sum"]
        inner = b"".join(_len_field(1, _number_data_point(dp))
                         for dp in s.get("dataPoints", ()))
        inner += _varint_field(2, int(s.get("aggregationTemporality", 0)))
        inner += _varint_field(3, 1 if s.get("isMonotonic") else 0)
        out.append(_len_field(7, inner))
    elif "histogram" in m:
        h = m["histogram"]
        inner = b"".join(_len_field(1, _histogram_data_point(dp))
                         for dp in h.get("dataPoints", ()))
        inner += _varint_field(2, int(h.get("aggregationTemporality", 0)))
        out.append(_len_field(9, inner))
    return b"".join(out)


def _scope_metrics(sm: dict) -> bytes:
    out = [_len_field(1, _scope(sm.get("scope", {})))]
    out += [_len_field(2, _metric(m)) for m in sm.get("metrics", ())]
    return b"".join(out)


def _resource_metrics(rm: dict) -> bytes:
    out = [_len_field(1, _resource(rm.get("resource", {})))]
    out += [_len_field(2, _scope_metrics(sm)) for sm in rm.get("scopeMetrics", ())]
    return b"".join(out)


def encode_metrics_request(payload: dict) -> bytes:
    """``metrics_snapshot_to_otlp`` JSON body ->
    ``ExportMetricsServiceRequest`` bytes."""
    return b"".join(_len_field(1, _resource_metrics(rm))
                    for rm in payload.get("resourceMetrics", ()))


def encode_request(payload: dict) -> bytes:
    """Dispatch on the payload's top-level key — the two request shapes are
    disjoint, so the transport can stay signal-agnostic."""
    if "resourceSpans" in payload:
        return encode_trace_request(payload)
    if "resourceMetrics" in payload:
        return encode_metrics_request(payload)
    raise ValueError("not an OTLP export payload: "
                     f"keys={sorted(payload)[:4]}")
