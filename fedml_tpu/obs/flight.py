"""Flight recorder — bounded per-process black-box capture (ISSUE 16).

Every subsystem the repo grew (journals, chaos injection, SIGKILL soaks,
multi-tenant gang scheduling) made failures *survivable*; none made them
*self-explaining* — diagnosis still meant hand-trawling per-process JSONL
trails.  This module is the black box: a bounded ring buffer of recent
spans, registry metric deltas, comm events (drops/retries/chunk
reassembly), chaos injections, and journal/epoch transitions, dumped as an
atomic bundle when something goes wrong.

Shape, deliberately minimal:

- :meth:`FlightRecorder.note` appends one dict to a ``deque(maxlen=N)``
  under a lock — O(1), allocation-bounded, safe from any thread, and it
  NEVER raises into the caller (telemetry must not take down a receive
  loop).
- ``span_sink`` plugs straight into ``obs.trace.traced(sink=...)``;
  ``attach_comm`` subscribes to the comm layer's process-wide event sinks
  (the same hook the client-health ledger uses), so transport drops and
  chunk-stream evictions land in the ring without new plumbing.
- ``record_metric_deltas`` scalarizes a ``MetricsRegistry.snapshot()`` and
  rings only what CHANGED since the last capture — a cheap round-boundary
  call that turns the registry into a time series inside the black box.
- **Triggers**: unhandled exception (``sys.excepthook`` +
  ``threading.excepthook`` chained), SIGTERM (main thread, chained),
  accounting-identity violation / SLO breach / hard kill / finish (explicit
  :meth:`trigger` calls wired into the servers, clients, soak harnesses,
  control plane, and serving worker).
- **Bundles** are atomic: the journal/AOT-store envelope pattern (MAGIC +
  one sorted-keys JSON meta line + payload, ``tempfile.mkstemp`` + fsync +
  ``os.replace``) — a reader sees an old bundle or a complete new one,
  never a torn one.  When LOCKSAN is on, the current lock-sanitizer report
  rides in the bundle.

Gating is absolute: :func:`recorder_from_config` returns ``None`` unless
``extra.flight_recorder`` is set — no ring, no taps, no signal handlers,
default path bit-identical.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Optional

from ..core.flags import cfg_extra
from . import registry as obsreg

log = logging.getLogger("fedml_tpu.obs.flight")

__all__ = [
    "FlightRecorder", "recorder_from_config", "read_bundle", "list_bundles",
    "FLIGHT_DUMPS",
]

#: on-disk bundle envelope: MAGIC + one sorted-keys JSON meta line + the
#: JSON body.  Bump the magic when the envelope changes — old bundles are
#: then rejected as foreign, never misread.
_MAGIC = b"FMLFLT1\n"

FLIGHT_DUMPS = obsreg.REGISTRY.counter(
    "fedml_flight_dumps_total",
    "Black-box bundles dumped by the flight recorder, by trigger reason.",
    labels=("reason",),
)
FLIGHT_EVENTS = obsreg.REGISTRY.counter(
    "fedml_flight_events_total",
    "Events appended to flight-recorder rings (evictions not subtracted).",
)


def _scalarize(snapshot: list[dict]) -> dict[str, float]:
    """Flatten a registry snapshot to ``{"family{k=v,...}": value}`` —
    counters/gauges by value, histograms by ``_count`` and ``_sum`` (the
    delta-friendly scalars)."""
    out: dict[str, float] = {}
    for fam in snapshot:
        name = fam["name"]
        for s in fam.get("samples", ()):
            labels = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
            key = f"{name}{{{labels}}}" if labels else name
            if fam.get("kind") == "histogram":
                out[key + "_count"] = float(s["count"])
                out[key + "_sum"] = float(s["sum"])
            else:
                out[key] = float(s["value"])
    return out


class FlightRecorder:
    """One process-local black box: bounded ring + atomic dump on trigger."""

    def __init__(self, out_dir: str, *, name: str = "proc",
                 capacity: int = 4096, window_s: float = 60.0,
                 registry: Optional[obsreg.MetricsRegistry] = None,
                 meta: Optional[dict] = None):
        self.out_dir = os.path.abspath(str(out_dir))
        os.makedirs(self.out_dir, exist_ok=True)
        self.name = str(name)
        self.capacity = max(16, int(capacity))
        self.window_s = float(window_s)
        self.registry = registry or obsreg.REGISTRY
        self.meta = dict(meta or {})
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._metric_last: Optional[dict[str, float]] = None
        self._comm_sink = None
        self._prev_excepthook = None
        self._prev_thread_hook = None
        self._prev_sigterm = None
        self._closed = False

    # -- intake ---------------------------------------------------------------
    def note(self, kind: str, **fields: Any) -> None:
        """Append one event to the ring.  Never raises; non-serializable
        field values are stringified at dump time, not here (hot path)."""
        try:
            ev = {"ts": round(time.time(), 6), "kind": str(kind)}
            ev.update(fields)
            with self._lock:
                self._ring.append(ev)
            FLIGHT_EVENTS.inc()
        except Exception:
            pass

    def span_sink(self, record: dict) -> None:
        """``obs.trace.traced(sink=recorder.span_sink)`` tap: finished spans
        land in the ring as ``kind: span`` events."""
        try:
            self.note("span", **{k: v for k, v in record.items() if k != "kind"})
        except Exception:
            pass

    def record_metric_deltas(self) -> int:
        """Scalarize the registry snapshot and ring only what changed since
        the last capture.  Returns the number of changed series (0 on the
        first call, which just sets the baseline)."""
        try:
            current = _scalarize(self.registry.snapshot())
        except Exception:
            return 0
        with self._lock:
            last, self._metric_last = self._metric_last, current
        if last is None:
            return 0
        delta = {k: round(v - last.get(k, 0.0), 9)
                 for k, v in current.items() if v != last.get(k, 0.0)}
        if delta:
            self.note("metrics_delta", delta=delta)
        return len(delta)

    def attach_comm(self) -> "FlightRecorder":
        """Subscribe to the comm layer's process-wide drop/retry/chunk
        events (``comm.base.add_comm_event_sink``); idempotent."""
        if self._comm_sink is None:
            from ..comm import base as comm_base

            def sink(event: str, **info):
                self.note("comm", event=event,
                          **{k: v for k, v in info.items() if v is not None})

            self._comm_sink = comm_base.add_comm_event_sink(sink)
        return self

    def detach_comm(self) -> None:
        if self._comm_sink is not None:
            from ..comm import base as comm_base

            comm_base.remove_comm_event_sink(self._comm_sink)
            self._comm_sink = None

    # -- triggers -------------------------------------------------------------
    def install_signal_handlers(self) -> "FlightRecorder":
        """Chain SIGTERM (main thread only — ``signal.signal`` refuses
        elsewhere) and the process/thread excepthooks so a terminating or
        crashing process leaves a bundle behind.  Idempotent."""
        if self._prev_excepthook is None:
            prev = sys.excepthook

            def hook(exc_type, exc, tb):
                self.trigger("unhandled_exception",
                             exc_type=getattr(exc_type, "__name__", str(exc_type)),
                             exc=str(exc))
                prev(exc_type, exc, tb)

            self._prev_excepthook = prev
            sys.excepthook = hook
        if self._prev_thread_hook is None and hasattr(threading, "excepthook"):
            prev_t = threading.excepthook

            def thook(args):
                self.trigger(
                    "unhandled_exception",
                    thread=getattr(args.thread, "name", None),
                    exc_type=getattr(args.exc_type, "__name__", str(args.exc_type)),
                    exc=str(args.exc_value))
                prev_t(args)

            self._prev_thread_hook = prev_t
            threading.excepthook = thook
        if (self._prev_sigterm is None
                and threading.current_thread() is threading.main_thread()):
            try:
                prev_s = signal.getsignal(signal.SIGTERM)

                def on_term(signum, frame):
                    self.trigger("sigterm")
                    if callable(prev_s):
                        prev_s(signum, frame)
                    elif prev_s == signal.SIG_DFL:
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        os.kill(os.getpid(), signal.SIGTERM)

                signal.signal(signal.SIGTERM, on_term)
                self._prev_sigterm = prev_s
            except (ValueError, OSError):
                pass
        return self

    def uninstall_signal_handlers(self) -> None:
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_thread_hook is not None:
            threading.excepthook = self._prev_thread_hook
            self._prev_thread_hook = None
        if self._prev_sigterm is not None:
            with contextlib.suppress(ValueError, OSError):
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    def trigger(self, reason: str, **context: Any) -> Optional[str]:
        """Note the trigger, dump a bundle, return its path (``None`` when
        the dump itself failed — triggers must never raise)."""
        try:
            self.note("trigger", reason=reason)
            return self.dump(reason, context=context)
        except Exception as e:
            log.warning("flight: dump for %r failed (%s: %s)",
                        reason, type(e).__name__, e)
            return None

    # -- the bundle -----------------------------------------------------------
    def events(self, window_s: Optional[float] = None) -> list[dict]:
        """The ring's events within the last ``window_s`` seconds (the
        recorder's configured window by default; <= 0 = everything)."""
        win = self.window_s if window_s is None else float(window_s)
        with self._lock:
            ring = list(self._ring)
        if win > 0:
            cutoff = time.time() - win
            ring = [e for e in ring if e.get("ts", 0.0) >= cutoff]
        return ring

    def dump(self, reason: str, context: Optional[dict] = None) -> str:
        """Write one atomic black-box bundle; returns its path."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        events = self.events()
        try:
            metrics = _scalarize(self.registry.snapshot())
        except Exception:
            metrics = {}
        locksan = None
        try:
            from ..analysis import sanitizer

            san = sanitizer.active()
            if san is not None:
                locksan = san.report()
        except Exception:
            locksan = None
        body = {
            "events": events,
            "metrics": metrics,
            "context": context or {},
            "recorder": dict(self.meta),
        }
        if locksan is not None:
            body["locksan"] = locksan
        meta = {
            "format": "fedml-flight-v1",
            "name": self.name,
            "pid": os.getpid(),
            "seq": seq,
            "reason": str(reason),
            "ts": round(time.time(), 6),
            "n_events": len(events),
        }
        payload = json.dumps(body, sort_keys=True, default=str).encode()
        blob = _MAGIC + json.dumps(meta, sort_keys=True).encode() + b"\n" + payload
        fname = f"{self.name}.{os.getpid()}.{seq:04d}.{reason}.flight"
        fname = "".join(c if c.isalnum() or c in "._-" else "_" for c in fname)
        path = os.path.join(self.out_dir, fname)
        fd, tmp = tempfile.mkstemp(dir=self.out_dir, prefix=".tmp_", suffix=".flight")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers see a complete bundle or none
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise
        FLIGHT_DUMPS.inc(reason=str(reason))
        return path

    def close(self) -> None:
        """Detach every tap/hook; the ring stays readable (no final dump —
        finish-time dumps are the owner's explicit trigger)."""
        if self._closed:
            return
        self._closed = True
        self.detach_comm()
        self.uninstall_signal_handlers()


# ---------------------------------------------------------------------------
# bundle IO


def read_bundle(path: str) -> dict:
    """Parse one ``.flight`` bundle -> ``{"meta": {...}, "events": [...],
    "metrics": {...}, "context": {...}, ...}``.  Raises ``ValueError`` on a
    foreign or torn file (callers skip those)."""
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MAGIC):
        raise ValueError(f"{path}: not a flight bundle (bad magic)")
    rest = blob[len(_MAGIC):]
    nl = rest.find(b"\n")
    if nl < 0:
        raise ValueError(f"{path}: truncated header")
    meta = json.loads(rest[:nl].decode())
    body = json.loads(rest[nl + 1:].decode())
    body["meta"] = meta
    body["path"] = path
    return body


def list_bundles(root: str) -> list[str]:
    """Every ``.flight`` file under ``root`` (recursive), sorted."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        out.extend(os.path.join(dirpath, f) for f in files
                   if f.endswith(".flight") and not f.startswith(".tmp_"))
    return sorted(out)


def recorder_from_config(cfg, *, name: str, meta: Optional[dict] = None
                         ) -> Optional[FlightRecorder]:
    """The one gate: ``extra.flight_recorder`` unset/falsy -> ``None``
    (no ring, no taps, bit-identical default path)."""
    if cfg is None or not cfg_extra(cfg, "flight_recorder"):
        return None
    out_dir = cfg_extra(cfg, "flight_dir") or os.path.join(
        os.getcwd(), "flight_bundles")
    try:
        return FlightRecorder(
            str(out_dir), name=name,
            capacity=int(cfg_extra(cfg, "flight_capacity")),
            window_s=float(cfg_extra(cfg, "flight_window_s")),
            meta={"run_id": str(getattr(cfg, "run_id", "")), **(meta or {})})
    except OSError as e:
        log.warning("flight: recorder dir %s unusable (%s) — running without "
                    "the black box", out_dir, e)
        return None
