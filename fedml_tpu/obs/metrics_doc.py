"""Generated metrics reference (ISSUE 16).

The registry is the single source of truth for every ``fedml_*`` family —
name, kind, labels, help text, histogram buckets all live at the
declaration site.  This module imports every registering module (metric
families register at import time, as module-level constants) and renders
the registry's own snapshot as markdown, so the reference CANNOT drift
from the code: regenerate with

    python -m fedml_tpu.obs.metrics_doc > docs/METRICS.md

A family missing from the doc means its module is missing from
``_REGISTERING_MODULES`` below — the generator prints import failures to
stderr and exits nonzero rather than silently documenting a subset.
"""

from __future__ import annotations

import importlib
import sys

from . import registry as obsreg

#: every module that declares ``fedml_*`` metric families at import time.
#: Keep sorted; the lint-adjacent guarantee is the generator's stderr check,
#: not this list's completeness by inspection.
_REGISTERING_MODULES = (
    "fedml_tpu.analysis.tracesan",
    "fedml_tpu.comm.base",
    "fedml_tpu.comm.chaos",
    "fedml_tpu.comm.codecs",
    "fedml_tpu.core.aot",
    "fedml_tpu.cross_silo.async_server",
    "fedml_tpu.cross_silo.client_journal",
    "fedml_tpu.cross_silo.edge",
    "fedml_tpu.cross_silo.journal",
    "fedml_tpu.cross_silo.runtime",
    "fedml_tpu.cross_silo.server",
    "fedml_tpu.obs.flight",
    "fedml_tpu.obs.health",
    "fedml_tpu.obs.otlp",
    "fedml_tpu.obs.profiler",
    "fedml_tpu.obs.remote",
    "fedml_tpu.obs.slo",
    "fedml_tpu.obs.timeline",
    "fedml_tpu.ops.pallas.timing",
    "fedml_tpu.population.cohorts",
    "fedml_tpu.population.store",
    "fedml_tpu.sched.multi_tenant",
    "fedml_tpu.serving.batcher",
    "fedml_tpu.serving.gateway",
    "fedml_tpu.serving.publisher",
    "fedml_tpu.sim.engine",
)

#: section title per family prefix (the token after ``fedml_``); prefixes
#: not listed here land under their raw prefix
_SECTIONS = {
    "aot": "AOT program store",
    "async": "Buffered-async aggregation",
    "chaos": "Chaos injection",
    "client": "Client health + journals",
    "comm": "Communication layer",
    "convergence": "Convergence tracking",
    "crosssilo": "Cross-silo rounds",
    "fleet": "Fleet partition (per-job submeshes)",
    "flight": "Flight recorder",
    "gateway": "Tenant-routed serving gateway",
    "hier": "Hierarchical aggregation tree",
    "journal": "Server recovery journal",
    "mt": "Multi-tenant control plane",
    "obs": "Observability trail shipping",
    "otlp": "OTLP egress",
    "pallas": "Pallas kernels",
    "pop": "Population-scale store",
    "profile": "Program-time attribution",
    "program": "Compiled-program cost model",
    "runtime": "Event-driven runtime",
    "serving": "Serving fleet",
    "sim": "Simulation engine",
    "slo": "SLO watchdog",
    "timeline": "Performance timeline",
    "tracesan": "Runtime trace sanitizer",
}


def _import_all() -> list[str]:
    """Import every registering module; returns the failures (module:
    error) instead of raising, so the caller can report ALL of them."""
    failures = []
    for mod in _REGISTERING_MODULES:
        try:
            importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 — the error string IS the report
            failures.append(f"{mod}: {type(e).__name__}: {e}")
    return failures


def _prefix(name: str) -> str:
    parts = name.split("_")
    return parts[1] if len(parts) > 1 and parts[0] == "fedml" else parts[0]


def render_metrics_reference(registry: obsreg.MetricsRegistry | None = None
                             ) -> str:
    """Markdown reference for every registered family, grouped by
    subsystem prefix.  Call after :func:`_import_all` (or after the
    subsystems you care about are imported)."""
    snap = (registry or obsreg.REGISTRY).snapshot()
    by_section: dict[str, list[dict]] = {}
    for fam in snap:
        if not fam["name"].startswith("fedml_"):
            continue
        by_section.setdefault(_prefix(fam["name"]), []).append(fam)
    lines = [
        "# Metrics reference",
        "",
        "Every `fedml_*` family the framework registers, rendered from the",
        "registry's own snapshot (names, kinds, labels, and help text come",
        "from the declaration sites — this file cannot drift from the code).",
        "",
        "Regenerate: `python -m fedml_tpu.obs.metrics_doc > docs/METRICS.md`",
        "",
        "Exposition: `extra.metrics_port` serves the Prometheus text format;",
        "`extra.otlp_endpoint` ships the same families over OTLP (see",
        "`docs/FLAGS.md`).  SLO specs (`extra.slo_specs`) reference these",
        "names directly.",
        "",
    ]
    for prefix in sorted(by_section):
        lines.append(f"## {_SECTIONS.get(prefix, prefix)} (`fedml_{prefix}_*`)")
        lines.append("")
        lines.append("| metric | kind | labels | help |")
        lines.append("|---|---|---|---|")
        for fam in sorted(by_section[prefix], key=lambda f: f["name"]):
            labels = ", ".join(fam.get("labels") or ()) or "—"
            help_text = " ".join(str(fam.get("help", "")).split())
            kind = fam["kind"]
            if kind == "histogram" and fam.get("buckets"):
                b = fam["buckets"]
                kind = f"histogram ({len(b)} buckets ≤ {b[-1]:g})"
            lines.append(
                f"| `{fam['name']}` | {kind} | {labels} | {help_text} |")
        lines.append("")
    return "\n".join(lines)


def main() -> int:
    failures = _import_all()
    if failures:
        for f in failures:
            print(f"metrics_doc: import failed — {f}", file=sys.stderr)
        return 1
    print(render_metrics_reference())
    return 0


if __name__ == "__main__":
    sys.exit(main())
