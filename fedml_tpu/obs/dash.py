"""Performance dashboard over a recorded timeline (ISSUE 18).

``fedml-tpu obs dash`` renders what :mod:`obs/timeline` recorded — no
Grafana, no dependencies: a terminal view (sparklines + tables) and a
fully self-contained HTML file (inline CSS + SVG, openable from disk).

Panels, each computed once in :func:`dash_data` so the two renderers
cannot disagree:

- **round throughput** — windowed rate of the sync round histogram's
  count and the async ``fedml_async_virtual_rounds_total`` counter,
- **comm bytes by tier** — ``fedml_hier_hop_bytes_total{hop=...}`` and
  flat-path payload counters, differenced over the timeline span,
- **convergence curve** — the tee'd ``(round, test_acc)`` series plus
  first-crossing rounds-to-target,
- **per-tenant rows** — every ``job=`` label value the ScopedRegistry
  stamped, with rounds and SLO breaches per tenant,
- **SLO-breach markers** — sample pairs where any
  ``fedml_slo_breaches_total`` series increased,
- **profile attribution** — the compile/h2d/device-compute/host-gap
  split and per-category rows from ``obs/profiler``'s JSON, when given.
"""

from __future__ import annotations

import html as _html
import json
import re
import time
from typing import Optional, Sequence

from . import timeline as tl

__all__ = ["dash_data", "render_dash_text", "render_dash_html"]

_JOB_RE = re.compile(r"\{(?:[^}]*,)?job=([^,}]+)")
_SPARK_CHARS = "▁▂▃▄▅▆▇█"

#: scalar families summed into the comm-bytes panel when present (flat
#: path; the hier hop counter is matched by prefix, per hop label)
_COMM_FAMILIES = ("fedml_comm_payload_bytes_total",
                  "fedml_comm_payload_raw_bytes_total")


def _spark(values: Sequence[float]) -> str:
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(_SPARK_CHARS[min(7, int((v - lo) / (hi - lo) * 7.999))]
                   for v in vals)


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} GiB"


def _series_delta(samples: Sequence[dict], key: str) -> float:
    pts = tl.value_series(samples, key)
    return (pts[-1][1] - pts[0][1]) if len(pts) >= 2 else 0.0


def _hist_count_rate(samples: Sequence[dict], key: str) -> Optional[float]:
    win = [s for s in samples if key in s.get("hists", {})]
    if len(win) < 2:
        return None
    t0, t1 = float(win[0]["ts"]), float(win[-1]["ts"])
    if t1 <= t0:
        return None
    return (win[-1]["hists"][key]["count"] - win[0]["hists"][key]["count"]) / (t1 - t0)


def dash_data(timeline: dict, profile: Optional[dict] = None) -> dict:
    """Every panel as plain data — the single computation both renderers
    (and tests) consume.  ``timeline`` is :func:`obs.timeline.load_timeline`
    output (or a live recorder's ``{"samples","rounds","buckets"}``)."""
    samples = list(timeline.get("samples", ()))
    rounds = list(timeline.get("rounds", ()))
    span_s = (float(samples[-1]["ts"]) - float(samples[0]["ts"])
              if len(samples) >= 2 else 0.0)
    all_keys: set[str] = set()
    for s in samples:
        all_keys.update(s.get("scalars", {}))

    # throughput
    rounds_per_s = _hist_count_rate(samples, "fedml_crosssilo_round_seconds")
    versions_per_s = tl.windowed_rate(samples, "fedml_async_virtual_rounds_total")

    # comm bytes by tier
    comm: dict[str, float] = {}
    for key in sorted(all_keys):
        if key.startswith("fedml_hier_hop_bytes_total{"):
            m = re.search(r"hop=([^,}]+)", key)
            delta = _series_delta(samples, key)
            if m and delta:
                comm[m.group(1)] = comm.get(m.group(1), 0.0) + delta
        elif key.split("{", 1)[0] in _COMM_FAMILIES:
            delta = _series_delta(samples, key)
            if delta:
                name = "flat" if "raw" not in key else "flat_raw"
                comm[name] = comm.get(name, 0.0) + delta

    # convergence
    curve = [(r.get("round_idx", r.get("server_version")), r.get("test_acc"))
             for r in rounds]
    curve = [(int(i), float(a)) for i, a in curve if i is not None and a is not None]
    targets = {k: v for k, v in tl.rounds_to_target(rounds).items()
               if v is not None}

    # per-tenant rows
    jobs: dict[str, dict] = {}
    for key in sorted(all_keys):
        m = _JOB_RE.search(key)
        if not m or not m.group(1):
            continue
        job = jobs.setdefault(m.group(1), {"rounds": None, "breaches": 0.0})
        if key.startswith("fedml_mt_job_rounds{"):
            pts = tl.value_series(samples, key)
            if pts:
                job["rounds"] = pts[-1][1]
        elif key.startswith("fedml_slo_breaches_total{"):
            pts = tl.value_series(samples, key)
            if pts:
                job["breaches"] += pts[-1][1]

    # SLO-breach markers: any breach counter increasing between samples
    markers = []
    breach_keys = [k for k in all_keys
                   if k.startswith("fedml_slo_breaches_total")]
    for key in sorted(breach_keys):
        pts = tl.value_series(samples, key)
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            if v1 > v0:
                markers.append({"ts": t1, "series": key, "inc": v1 - v0})
    markers.sort(key=lambda m: m["ts"])

    return {
        "n_samples": len(samples),
        "n_rounds": len(rounds),
        "span_s": round(span_s, 3),
        "skipped_segments": int(timeline.get("skipped", 0)),
        "throughput": {"rounds_per_s": rounds_per_s,
                       "versions_per_s": versions_per_s},
        "comm_bytes": comm,
        "convergence": {"curve": curve, "rounds_to_target": targets},
        "tenants": jobs,
        "slo_markers": markers,
        "profile": profile,
    }


# ---------------------------------------------------------------------------
# terminal rendering


def _num(v, digits: int = 3) -> str:
    return "-" if v is None else f"{float(v):.{digits}f}"


def render_dash_text(timeline: dict, profile: Optional[dict] = None) -> str:
    d = dash_data(timeline, profile)
    lines = ["== performance timeline =="]
    lines.append(f"samples: {d['n_samples']}  rounds: {d['n_rounds']}  "
                 f"span: {d['span_s']}s  skipped segments: "
                 f"{d['skipped_segments']}")
    t = d["throughput"]
    lines.append(f"throughput: rounds/s {_num(t['rounds_per_s'])}  "
                 f"versions/s {_num(t['versions_per_s'])}")
    if d["comm_bytes"]:
        lines.append("")
        lines.append("comm bytes by tier:")
        for hop, b in sorted(d["comm_bytes"].items()):
            lines.append(f"  {hop:<12} {_fmt_bytes(b)}")
    curve = d["convergence"]["curve"]
    if curve:
        lines.append("")
        lines.append(f"convergence ({len(curve)} evals): "
                     f"{_spark([a for _, a in curve])}  "
                     f"last acc {curve[-1][1]:.4f} @ round {curve[-1][0]}")
        for target, rnd in sorted(d["convergence"]["rounds_to_target"].items()):
            lines.append(f"  target {target}: round {rnd:g}")
    if d["tenants"]:
        lines.append("")
        lines.append("tenants:")
        for job, row in sorted(d["tenants"].items()):
            lines.append(f"  job {job:<10} rounds {_num(row['rounds'], 0)}  "
                         f"slo breaches {row['breaches']:g}")
    if d["slo_markers"]:
        lines.append("")
        lines.append(f"slo breaches ({len(d['slo_markers'])}):")
        for m in d["slo_markers"][:10]:
            lines.append(f"  +{m['inc']:g} {m['series']}")
    p = d["profile"]
    if p:
        lines.append("")
        lines.append("profile attribution:")
        for k, v in sorted((p.get("buckets") or {}).items()):
            lines.append(f"  {k:<18} {v:.4f}")
        for label in ("mfu_cost_model", "mfu_trace", "sim_mfu_gauge"):
            if p.get(label) is not None:
                lines.append(f"  {label:<18} {p[label]:.4f}")
        for row in (p.get("by_category") or [])[:8]:
            lines.append(f"  {row['key']:<18} {row['ms']:>9.2f} ms  "
                         f"{row['tflops']:>7.2f} TFLOP/s  "
                         f"{row['gbps']:>7.1f} GB/s")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self-contained HTML


_CSS = """
body{font:14px/1.45 system-ui,sans-serif;margin:2em;background:#fafafa;color:#222}
h1{font-size:1.3em}h2{font-size:1.05em;margin-top:1.6em}
table{border-collapse:collapse;margin:.5em 0}
td,th{border:1px solid #ccc;padding:.25em .6em;text-align:right}
th{background:#eee}td:first-child,th:first-child{text-align:left}
svg{background:#fff;border:1px solid #ccc}
.mark{color:#b00;font-weight:bold}
"""


def _svg_curve(points: Sequence[tuple[float, float]], *, w: int = 560,
               h: int = 160, markers: Sequence[float] = ()) -> str:
    if not points:
        return ""
    xs = [float(p[0]) for p in points]
    ys = [float(p[1]) for p in points]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    pad = 8

    def px(x):
        return pad + (x - x0) / xr * (w - 2 * pad)

    def py(y):
        return h - pad - (y - y0) / yr * (h - 2 * pad)

    pts = " ".join(f"{px(x):.1f},{py(y):.1f}" for x, y in points)
    marks = "".join(
        f'<line x1="{px(m):.1f}" y1="0" x2="{px(m):.1f}" y2="{h}" '
        f'stroke="#b00" stroke-dasharray="3,3"/>'
        for m in markers if x0 <= m <= x1)
    return (f'<svg width="{w}" height="{h}" viewBox="0 0 {w} {h}">'
            f'{marks}<polyline points="{pts}" fill="none" stroke="#07c" '
            f'stroke-width="1.5"/></svg>'
            f'<div>y: [{y0:.4g}, {y1:.4g}]  x: [{x0:.4g}, {x1:.4g}]</div>')


def _table(headers: Sequence[str], rows: Sequence[Sequence]) -> str:
    head = "".join(f"<th>{_html.escape(str(hh))}</th>" for hh in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def render_dash_html(timeline: dict, profile: Optional[dict] = None,
                     title: str = "fedml-tpu performance timeline") -> str:
    d = dash_data(timeline, profile)
    out = [f"<!doctype html><html><head><meta charset='utf-8'>"
           f"<title>{_html.escape(title)}</title><style>{_CSS}</style></head>"
           f"<body><h1>{_html.escape(title)}</h1>"]
    out.append(
        f"<p>{d['n_samples']} samples · {d['n_rounds']} rounds · "
        f"{d['span_s']}s span · generated "
        f"{time.strftime('%Y-%m-%d %H:%M:%S')}</p>")
    t = d["throughput"]
    out.append("<h2>Throughput</h2>")
    out.append(_table(["series", "per second"], [
        ["rounds/s (sync)", _num(t["rounds_per_s"])],
        ["versions/s (async)", _num(t["versions_per_s"])]]))
    if d["comm_bytes"]:
        out.append("<h2>Comm bytes by tier</h2>")
        out.append(_table(["tier", "bytes"], [
            [hop, _fmt_bytes(b)] for hop, b in sorted(d["comm_bytes"].items())]))
    curve = d["convergence"]["curve"]
    if curve:
        out.append("<h2>Convergence</h2>")
        marker_rounds = [v for v in d["convergence"]["rounds_to_target"].values()]
        out.append(_svg_curve(curve, markers=marker_rounds))
        if d["convergence"]["rounds_to_target"]:
            out.append(_table(["accuracy target", "first round"], [
                [k, f"{v:g}"] for k, v in
                sorted(d["convergence"]["rounds_to_target"].items())]))
    if d["tenants"]:
        out.append("<h2>Tenants</h2>")
        out.append(_table(["job", "rounds", "SLO breaches"], [
            [job, _num(row["rounds"], 0), f"{row['breaches']:g}"]
            for job, row in sorted(d["tenants"].items())]))
    if d["slo_markers"]:
        out.append("<h2>SLO breaches</h2>")
        out.append(_table(["ts", "series", "increase"], [
            [f"{m['ts']:.3f}", m["series"], f"{m['inc']:g}"]
            for m in d["slo_markers"]]))
    p = d["profile"]
    if p:
        out.append("<h2>Profile attribution</h2>")
        out.append(_table(["bucket", "seconds"], [
            [k, f"{v:.4f}"] for k, v in sorted((p.get("buckets") or {}).items())]))
        mfu_rows = [[label, f"{p[label]:.4f}"]
                    for label in ("mfu_cost_model", "mfu_trace", "sim_mfu_gauge")
                    if p.get(label) is not None]
        if mfu_rows:
            out.append(_table(["MFU cross-check", "value"], mfu_rows))
        if p.get("by_category"):
            out.append(_table(["hlo category", "ms", "n", "TFLOP/s", "GB/s"], [
                [r["key"], r["ms"], r["n"], r["tflops"], r["gbps"]]
                for r in p["by_category"]]))
    out.append("<details><summary>raw panel data</summary><pre>"
               + _html.escape(json.dumps(
                   {k: v for k, v in d.items() if k != "profile"},
                   indent=1, default=str))
               + "</pre></details>")
    out.append("</body></html>")
    return "".join(out)
