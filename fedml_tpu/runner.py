"""FedMLRunner — platform dispatch.

Reference: ``python/fedml/runner.py:19`` picks a platform runner from
``args.training_type``/``args.backend``.  Same dispatch here; the simulation
path constructs the MeshSimulator directly (no actor hierarchy to build).
"""

from __future__ import annotations

from typing import Optional

from . import constants as C
from .arguments import Config


def _check_unimplemented_flags(cfg: Config) -> None:
    """Security/privacy flags must never be silent no-ops: until the trust
    stack handles a flag, enabling it is an error (silent absence of DP noise
    or defenses is worse than a crash)."""
    pending = [
        name
        for name in ("enable_attack", "enable_defense", "enable_dp", "enable_secagg", "enable_fhe", "enable_contribution")
        if getattr(cfg, name, False) and name not in _IMPLEMENTED_TRUST_FLAGS
    ]
    if pending:
        raise NotImplementedError(
            f"trust features {pending} are enabled in the config but not yet "
            "implemented in fedml_tpu; refusing to run without them"
        )


# updated as the trust stack lands
_IMPLEMENTED_TRUST_FLAGS: set = {
    "enable_attack",
    "enable_defense",
    "enable_dp",
    "enable_contribution",
}


class FedMLRunner:
    def __init__(
        self,
        cfg: Config,
        dataset=None,
        model=None,
        client_trainer=None,
        server_aggregator=None,
    ):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        self.client_trainer = client_trainer
        self.server_aggregator = server_aggregator
        _check_unimplemented_flags(cfg)
        if cfg.training_type == C.TRAINING_PLATFORM_SIMULATION:
            self.runner = self._init_simulation_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_CROSS_SILO:
            self.runner = self._init_cross_silo_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_CENTRALIZED:
            self.runner = self._init_centralized_runner()
        else:
            raise ValueError(f"unsupported training_type {cfg.training_type!r}")

    def _load_data_model(self):
        if self.dataset is None:
            from .data import loader

            self.dataset = loader.load(self.cfg)
        if self.model is None:
            from .models import model_hub

            self.model = model_hub.create(self.cfg, self.dataset.class_num)
        return self.dataset, self.model

    def _init_simulation_runner(self):
        dataset, model = self._load_data_model()
        from .sim.engine import MeshSimulator

        return MeshSimulator(self.cfg, dataset, model, algorithm=self.client_trainer)

    def _init_cross_silo_runner(self):
        dataset, model = self._load_data_model()
        try:
            from .cross_silo import create_cross_silo_runner
        except ImportError as e:
            raise NotImplementedError(
                "cross_silo platform is not yet available in this build"
            ) from e
        return create_cross_silo_runner(self.cfg, dataset, model)

    def _init_centralized_runner(self):
        dataset, model = self._load_data_model()
        from .sim.centralized import CentralizedTrainer

        return CentralizedTrainer(self.cfg, dataset, model)

    def run(self):
        return self.runner.run()
