"""FedMLRunner — platform dispatch.

Reference: ``python/fedml/runner.py:19`` picks a platform runner from
``args.training_type``/``args.backend``.  Same dispatch here; the simulation
path constructs the MeshSimulator directly (no actor hierarchy to build).
"""

from __future__ import annotations

from typing import Optional

from . import constants as C
from .core.flags import cfg_extra
from .arguments import Config


def _check_unimplemented_flags(cfg: Config) -> None:
    """Security/privacy flags must never be silent no-ops: until the trust
    stack handles a flag, enabling it is an error (silent absence of DP noise
    or defenses is worse than a crash)."""
    pending = [
        name
        for name in ("enable_attack", "enable_defense", "enable_dp", "enable_secagg", "enable_fhe", "enable_contribution")
        if getattr(cfg, name, False) and name not in _IMPLEMENTED_TRUST_FLAGS
    ]
    if pending:
        raise NotImplementedError(
            f"trust features {pending} are enabled in the config but not yet "
            "implemented in fedml_tpu; refusing to run without them"
        )


# updated as the trust stack lands
_IMPLEMENTED_TRUST_FLAGS: set = {
    "enable_attack",
    "enable_defense",
    "enable_dp",
    "enable_contribution",
    "enable_secagg",  # LightSecAgg masked aggregation (cross-silo platform)
    "enable_fhe",  # RLWE homomorphic aggregation (cross-silo platform)
}


class FedMLRunner:
    def __init__(
        self,
        cfg: Config,
        dataset=None,
        model=None,
        client_trainer=None,
        server_aggregator=None,
    ):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        self.client_trainer = client_trainer
        self.server_aggregator = server_aggregator
        _check_unimplemented_flags(cfg)
        if cfg.training_type == C.TRAINING_PLATFORM_SIMULATION:
            self.runner = self._init_simulation_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_CROSS_SILO:
            self.runner = self._init_cross_silo_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_CROSS_DEVICE:
            self.runner = self._init_cross_device_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_CROSS_CLOUD:
            self.runner = self._init_cross_cloud_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_SERVING:
            self.runner = self._init_serving_runner()
        elif cfg.training_type == C.TRAINING_PLATFORM_CENTRALIZED:
            self.runner = self._init_centralized_runner()
        else:
            raise ValueError(f"unsupported training_type {cfg.training_type!r}")

    def _load_data_model(self):
        if self.dataset is None:
            from .data import loader

            self.dataset = loader.load(self.cfg)
        if self.model is None:
            from .models import model_hub

            self.model = model_hub.create(self.cfg, self.dataset.class_num)
        return self.dataset, self.model

    # simulators that bypass the MeshSimulator (and its trust pipeline /
    # custom-trainer support)
    _SPECIAL_SIM_OPTIMIZERS = {
        C.FEDERATED_OPTIMIZER_DECENTRALIZED_FL,
        C.FEDERATED_OPTIMIZER_HIERARCHICAL_FL,
        C.FEDERATED_OPTIMIZER_ASYNC_FEDAVG,
        C.FEDERATED_OPTIMIZER_SPLIT_NN,
        C.FEDERATED_OPTIMIZER_FEDGKT,
        C.FEDERATED_OPTIMIZER_VERTICAL_FL,
        C.FEDERATED_OPTIMIZER_FEDGAN,
        C.FEDERATED_OPTIMIZER_FEDNAS,
        C.FEDERATED_OPTIMIZER_FEDSEG,
        C.FEDERATED_OPTIMIZER_TURBO_AGGREGATE,
        C.FEDERATED_OPTIMIZER_FEDLLM,
        *C.FEDERATED_OPTIMIZER_MYAVG_ALIASES,
    }
    # these build their own model pair internally; model_hub model is unused
    _OWN_MODEL_OPTIMIZERS = {
        C.FEDERATED_OPTIMIZER_SPLIT_NN,
        C.FEDERATED_OPTIMIZER_FEDGKT,
        C.FEDERATED_OPTIMIZER_VERTICAL_FL,
        C.FEDERATED_OPTIMIZER_FEDGAN,
        C.FEDERATED_OPTIMIZER_FEDNAS,
        C.FEDERATED_OPTIMIZER_FEDSEG,
        C.FEDERATED_OPTIMIZER_FEDLLM,
    }

    def _init_simulation_runner(self):
        for flag, feature in (("enable_secagg", "LightSecAgg"), ("enable_fhe", "FHE aggregation")):
            if getattr(self.cfg, flag, False):
                raise NotImplementedError(
                    f"{flag} is a cross-silo protocol feature ({feature} over "
                    "the wire); the single-process simulator has no "
                    "adversarial server to hide updates from — set "
                    "training_type='cross_silo'"
                )
        opt = self.cfg.federated_optimizer
        if opt in self._SPECIAL_SIM_OPTIMIZERS:
            # trust flags must never be silent no-ops (see
            # _check_unimplemented_flags): these simulators don't wire the
            # trust pipeline yet, so refuse rather than ignore.  MyAvg is the
            # exception — it routes attack/defense/DP through the engine's
            # trust hooks and enforces its own finer-grained policy
            # (sim/myavg.py refuses secagg/fhe/contribution and
            # aggregation-replacing defenses itself).
            if opt not in C.FEDERATED_OPTIMIZER_MYAVG_ALIASES:
                active = [
                    f for f in _IMPLEMENTED_TRUST_FLAGS if getattr(self.cfg, f, False)
                ]
                if active:
                    raise NotImplementedError(
                        f"trust features {active} are not yet wired into the "
                        f"{opt!r} simulator (supported on the FedAvg-family mesh "
                        "engine); refusing to run without them"
                    )
            if self.client_trainer is not None or self.server_aggregator is not None:
                raise ValueError(
                    f"custom client_trainer/server_aggregator are not used by "
                    f"the {opt!r} simulator; remove them or use a FedAvg-family optimizer"
                )
        if self.dataset is None:
            from .data import loader

            self.dataset = loader.load(self.cfg)
        dataset = self.dataset
        if self.model is None and opt not in self._OWN_MODEL_OPTIMIZERS:
            from .models import model_hub

            self.model = model_hub.create(self.cfg, dataset.class_num)
        model = self.model
        if opt == C.FEDERATED_OPTIMIZER_DECENTRALIZED_FL:
            from .sim.decentralized import DecentralizedSimulator

            return DecentralizedSimulator(self.cfg, dataset, model)
        if opt == C.FEDERATED_OPTIMIZER_HIERARCHICAL_FL:
            from .sim.hierarchical import HierarchicalSimulator

            return HierarchicalSimulator(self.cfg, dataset, model)
        if opt == C.FEDERATED_OPTIMIZER_ASYNC_FEDAVG:
            from .sim.async_fl import AsyncSimulator

            return AsyncSimulator(self.cfg, dataset, model)
        if opt == C.FEDERATED_OPTIMIZER_SPLIT_NN:
            from .sim.split_learning import SplitNNSimulator

            return SplitNNSimulator(self.cfg, dataset)
        if opt == C.FEDERATED_OPTIMIZER_FEDGKT:
            from .sim.split_learning import FedGKTSimulator

            return FedGKTSimulator(self.cfg, dataset)
        if opt == C.FEDERATED_OPTIMIZER_VERTICAL_FL:
            from .sim.vertical import VFLSimulator

            return VFLSimulator(self.cfg, dataset)
        if opt == C.FEDERATED_OPTIMIZER_FEDGAN:
            from .sim.fedgan import FedGANSimulator

            return FedGANSimulator(self.cfg, dataset)
        if opt == C.FEDERATED_OPTIMIZER_FEDNAS:
            from .sim.fednas import FedNASSimulator

            return FedNASSimulator(self.cfg, dataset)
        if opt == C.FEDERATED_OPTIMIZER_FEDSEG:
            from .sim.fedseg import FedSegSimulator

            return FedSegSimulator(self.cfg, dataset)
        if opt == C.FEDERATED_OPTIMIZER_TURBO_AGGREGATE:
            from .sim.turboaggregate import TurboAggregateSimulator

            return TurboAggregateSimulator(self.cfg, dataset, model)
        if opt in C.FEDERATED_OPTIMIZER_MYAVG_ALIASES:
            from .sim.myavg import MyAvgSimulator

            return MyAvgSimulator(self.cfg, dataset, model)
        if opt == C.FEDERATED_OPTIMIZER_FEDLLM:
            # config-driven FedLLM (reference spotlight_prj/fedllm
            # run_fedllm.py is launched from a job yaml); the transformer is
            # built internally from extra.llm_* keys / tiny defaults
            from .llm.fedllm import FedLLMSimulator

            return FedLLMSimulator(self.cfg, dataset)
        from .sim.engine import MeshSimulator

        return MeshSimulator(self.cfg, dataset, model, algorithm=self.client_trainer)

    def _init_cross_silo_runner(self):
        dataset, model = self._load_data_model()
        try:
            from .cross_silo import create_cross_silo_runner
        except ImportError as e:
            raise NotImplementedError(
                "cross_silo platform is not yet available in this build"
            ) from e
        return create_cross_silo_runner(self.cfg, dataset, model)

    def _init_cross_device_runner(self):
        dataset, model = self._load_data_model()
        from .cross_device import create_cross_device_runner

        return create_cross_device_runner(self.cfg, dataset, model)

    def _init_cross_cloud_runner(self):
        cfg = self.cfg
        llm_mode = bool(cfg_extra(cfg, "unitedllm"))
        if self.dataset is None:
            from .data import loader

            self.dataset = loader.load(cfg)
        if self.model is None and not llm_mode:
            from .models import model_hub

            self.model = model_hub.create(cfg, self.dataset.class_num)
        from .cross_cloud import create_cross_cloud_runner

        return create_cross_cloud_runner(cfg, self.dataset, self.model)

    def _init_serving_runner(self):
        """``training_type='model_serving'`` (reference ``runner.py:19`` +
        ``serving/fedml_server.py``): a federated run under an endpoint
        identity; the server registers + deploys the final model."""
        cfg = self.cfg
        for flag in ("enable_secagg", "enable_fhe"):
            if getattr(cfg, flag, False):
                # the serving managers wrap the PLAIN cross-silo builders;
                # silently dropping a privacy flag is worse than refusing
                raise NotImplementedError(
                    f"{flag} is not wired into the model_serving platform; "
                    "run the secure-aggregation job under "
                    "training_type='cross_silo' and deploy the result"
                )
        dataset, model = self._load_data_model()
        end_point = str(cfg_extra(cfg, "end_point_name", f"ep-{cfg.run_id}"))
        model_name = str(cfg_extra(cfg, "serving_model_name", cfg.model))
        version = str(cfg_extra(cfg, "model_version"))
        from .serving.federated import FedMLModelServingClient, FedMLModelServingServer

        if cfg.role == "server":
            single_process = cfg.backend in ("INPROC", "MESH", "")

            class _ServingRunner:
                def run(self_inner):
                    clients = []
                    if single_process:
                        from .comm.inproc import InProcRouter

                        InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
                        clients = [
                            FedMLModelServingClient(
                                cfg, end_point, model_name, version,
                                dataset=dataset, model=model, rank=r,
                                backend="INPROC",
                            )
                            for r in range(1, cfg.client_num_in_total + 1)
                        ]
                        for c in clients:
                            c.run_in_thread()
                    server = FedMLModelServingServer(
                        cfg, end_point, model_name, version, dataset=dataset, model=model,
                        backend="INPROC" if single_process else None,
                    )
                    try:
                        history, _card = server.run()
                    finally:
                        for c in clients:
                            c.finish()
                    return history

            return _ServingRunner()

        class _ServingClientRunner:
            def run(self_inner):
                client = FedMLModelServingClient(
                    cfg, end_point, model_name, version, dataset=dataset, model=model,
                    rank=int(cfg.rank),
                )
                thread = client.run_in_thread()
                client.client.done.wait()
                thread.join(timeout=5.0)
                return None

        return _ServingClientRunner()

    def _init_centralized_runner(self):
        dataset, model = self._load_data_model()
        from .sim.centralized import CentralizedTrainer

        return CentralizedTrainer(self.cfg, dataset, model)

    def run(self):
        return self.runner.run()
