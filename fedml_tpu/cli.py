"""CLI — ``python -m fedml_tpu.cli <command>``.

Parity with the reference CLI verbs (``python/fedml/cli/cli.py:11-80``):
``run`` (a training recipe), ``launch`` (a job.yaml through the scheduler),
``build`` (package a workspace), ``agent`` (start a worker), ``jobs``/``logs``
(job DB), ``env``, ``version``.  Cloud-account verbs (``login`` to the SaaS)
have no meaning in a self-hosted TPU build; ``login`` here registers the
local spool directory.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

DEFAULT_SPOOL = os.path.expanduser("~/.fedml_tpu/spool")


def cmd_run(args) -> int:
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = fedml_tpu.init(argv=["--cf", args.config] + (["--rank", str(args.rank)] if args.rank is not None else []) + (["--role", args.role] if args.role else []))
    history = FedMLRunner(cfg).run()
    if history:
        print(json.dumps(history[-1]))
    return 0


def cmd_launch(args) -> int:
    from fedml_tpu.sched.launch import FedMLLaunchManager

    mgr = FedMLLaunchManager(args.spool)
    run_id = mgr.launch_job(args.job_yaml)
    print(run_id)
    return 0


def cmd_build(args) -> int:
    from fedml_tpu.sched.launch import FedMLLaunchManager, JobSpec

    mgr = FedMLLaunchManager(args.spool)
    spec = JobSpec.from_yaml(args.job_yaml)
    pkg = mgr.build_package(spec, base_dir=str(Path(args.job_yaml).parent))
    print(pkg)
    return 0


def cmd_agent(args) -> int:
    from fedml_tpu.sched.agent import FedMLAgent

    agent = FedMLAgent(args.spool)
    print(f"agent watching {args.spool}", file=sys.stderr)
    try:
        agent.run_forever(poll_s=args.poll)
    except KeyboardInterrupt:
        agent.stop()
    return 0


def cmd_jobs(args) -> int:
    from fedml_tpu.sched.agent import JobDB

    db = JobDB(str(Path(args.spool) / "jobs.sqlite"))
    for row in db.all_jobs():
        print(json.dumps(row))
    return 0


def cmd_logs(args) -> int:
    from fedml_tpu.sched.agent import FedMLAgent

    print(FedMLAgent(args.spool).logs(args.run_id))
    return 0


def cmd_env(args) -> int:
    import jax

    import fedml_tpu

    info = {
        "fedml_tpu": fedml_tpu.__version__,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_version(args) -> int:
    import fedml_tpu

    print(fedml_tpu.__version__)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fedml-tpu")
    parser.add_argument("--spool", default=DEFAULT_SPOOL, help="local scheduler spool dir")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("run", help="run a training recipe yaml")
    p.add_argument("--cf", dest="config", required=True)
    p.add_argument("--rank", type=int, default=None)
    p.add_argument("--role", default=None)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("launch", help="package + submit a job.yaml")
    p.add_argument("job_yaml")
    p.set_defaults(fn=cmd_launch)

    p = sub.add_parser("build", help="build a run package without submitting")
    p.add_argument("job_yaml")
    p.set_defaults(fn=cmd_build)

    p = sub.add_parser("agent", help="start a worker agent on the spool")
    p.add_argument("--poll", type=float, default=0.5)
    p.set_defaults(fn=cmd_agent)

    p = sub.add_parser("jobs", help="list job statuses")
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("logs", help="print a run's logs")
    p.add_argument("run_id")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("env", help="print environment info")
    p.set_defaults(fn=cmd_env)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
