"""CLI — ``python -m fedml_tpu.cli <command>``.

Parity with the reference CLI verbs (``python/fedml/cli/cli.py:11-80``):
``login``/``logout``, ``launch``, ``cluster``, ``run``, ``device``,
``model``, ``build``, ``logs``, ``train``, ``federate``, ``storage``,
``diagnosis``, ``version`` — plus ``agent``/``jobs``/``env`` from the local
scheduler.  The reference's account verbs talk to its SaaS; the self-hosted
translation keeps the same verb surface against local state: credentials in
``~/.fedml_tpu/credentials.json``, model cards + endpoints in the spool
directory's sqlite/json stores, storage as a local object dir, diagnosis as
an environment self-check.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

DEFAULT_SPOOL = os.path.expanduser("~/.fedml_tpu/spool")


def cmd_run(args) -> int:
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = fedml_tpu.init(argv=["--cf", args.config] + (["--rank", str(args.rank)] if args.rank is not None else []) + (["--role", args.role] if args.role else []))
    history = FedMLRunner(cfg).run()
    if history:
        print(json.dumps(history[-1]))
    return 0


def cmd_launch(args) -> int:
    from fedml_tpu.sched.launch import FedMLLaunchManager

    mgr = FedMLLaunchManager(args.spool)
    run_id = mgr.launch_job(args.job_yaml)
    print(run_id)
    return 0


def cmd_build(args) -> int:
    from fedml_tpu.sched.launch import FedMLLaunchManager, JobSpec

    mgr = FedMLLaunchManager(args.spool)
    spec = JobSpec.from_yaml(args.job_yaml)
    pkg = mgr.build_package(spec, base_dir=str(Path(args.job_yaml).parent))
    print(pkg)
    return 0


def cmd_agent(args) -> int:
    from fedml_tpu.sched.agent import FedMLAgent

    capacity = {"num_devices": args.num_devices}
    if args.device_type:
        capacity["device_type"] = args.device_type
    if args.mem_gb:
        capacity["mem_gb"] = args.mem_gb
    agent = FedMLAgent(args.spool, agent_id=args.agent_id, capacity=capacity)
    print(f"agent watching {args.spool}", file=sys.stderr)
    try:
        agent.run_forever(poll_s=args.poll)
    except KeyboardInterrupt:
        agent.stop()
    return 0


def cmd_jobs(args) -> int:
    from fedml_tpu.sched.agent import JobDB

    db = JobDB(str(Path(args.spool) / "jobs.sqlite"))
    for row in db.all_jobs():
        print(json.dumps(row))
    return 0


def cmd_logs(args) -> int:
    from fedml_tpu.sched.agent import FedMLAgent

    print(FedMLAgent(args.spool).logs(args.run_id))
    return 0


def cmd_env(args) -> int:
    import jax

    import fedml_tpu

    info = {
        "fedml_tpu": fedml_tpu.__version__,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_version(args) -> int:
    import fedml_tpu

    print(fedml_tpu.__version__)
    return 0


# -- account (reference login.py/logout.py; local credentials file) ----------

def _cred_path() -> Path:
    return Path(os.path.expanduser("~/.fedml_tpu/credentials.json"))


def cmd_login(args) -> int:
    p = _cred_path()
    p.parent.mkdir(parents=True, exist_ok=True)
    # create 0600 from the first byte — chmod-after-write leaves a window
    # where the api key is world-readable under umask 022
    fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(json.dumps({"account": args.account, "api_key": args.api_key or ""}))
    # os.open's mode applies only at CREATION; tighten a pre-existing file too
    os.chmod(p, 0o600)
    print(f"logged in as {args.account}")
    return 0


def cmd_logout(args) -> int:
    p = _cred_path()
    if p.exists():
        p.unlink()
    print("logged out")
    return 0


# -- train / federate (reference train.py / federate.py job verbs) -----------

def cmd_train(args) -> int:
    """Centralized training job (reference ``fedml train``)."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = fedml_tpu.init(argv=["--cf", args.config])
    cfg.training_type = "centralized"
    history = FedMLRunner(cfg).run()
    if history:
        print(json.dumps(history[-1]))
    return 0


def cmd_federate(args) -> int:
    """Federated job (reference ``fedml federate``) — refuses a centralized
    recipe instead of silently running one."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = fedml_tpu.init(argv=["--cf", args.config])
    if cfg.training_type == "centralized":
        print("error: 'federate' needs a federated training_type "
              "(simulation/cross_silo/cross_device); use 'train' for centralized",
              file=sys.stderr)
        return 2
    history = FedMLRunner(cfg).run()
    if history:
        print(json.dumps(history[-1]))
    return 0


# -- model (reference model.py: create/list/deploy/run against the deploy
#    scheduler, local card registry in the spool) -----------------------------

def _card_registry(spool: str) -> Path:
    p = Path(spool) / "model_cards.json"
    p.parent.mkdir(parents=True, exist_ok=True)
    if not p.exists():
        p.write_text("{}")
    return p


def cmd_model(args) -> int:
    reg = _card_registry(args.spool)
    cards = json.loads(reg.read_text())
    if args.model_cmd == "create":
        cards[f"{args.name}:{args.model_version}"] = {
            "name": args.name, "version": args.model_version,
            "model": args.arch, "classes": args.classes, "params_path": args.params,
        }
        reg.write_text(json.dumps(cards, indent=2))
        print(f"registered {args.name}:{args.model_version}")
        return 0
    if args.model_cmd == "list":
        for key, card in sorted(cards.items()):
            print(json.dumps(card))
        return 0
    if args.model_cmd == "delete":
        removed = [k for k in list(cards) if k.split(":")[0] == args.name]
        for k in removed:
            del cards[k]
        reg.write_text(json.dumps(cards, indent=2))
        print(f"deleted {len(removed)} card(s)")
        return 0
    if args.model_cmd == "deploy":
        from fedml_tpu.serving.deploy import ModelCard, ModelDeployScheduler

        key = f"{args.name}:{args.model_version}"
        if key not in cards:
            print(f"error: no card {key}", file=sys.stderr)
            return 2
        sched = ModelDeployScheduler(str(Path(args.spool) / "endpoints.db"))
        sched.cards.register(ModelCard(**cards[key]))
        sched.deploy(args.endpoint, args.name, args.model_version, replicas=args.replicas)
        ok = sched.wait_ready(args.endpoint, replicas=args.replicas, timeout=args.timeout)
        ep = sched.endpoints[args.endpoint]
        print(json.dumps({"endpoint": args.endpoint, "ready": ok,
                          "ports": ep.ready_ports()}))
        if ok and args.watch:
            # foreground reconcile until interrupted — the CLI owns the
            # replica processes for the session
            sched.run_in_thread()
            try:
                import time as _t

                while True:
                    _t.sleep(1)
            except KeyboardInterrupt:
                pass
        # a one-shot CLI cannot own background processes: stop the endpoint
        # on exit either way (use --watch to keep serving)
        sched.stop()
        return 0 if ok else 1
    print(f"unknown model subcommand {args.model_cmd}", file=sys.stderr)
    return 2


# -- device / cluster (reference device.py / cluster.py; local semantics) ----

def cmd_device(args) -> int:
    import jax

    devices = [
        {"id": d.id, "kind": getattr(d, "device_kind", d.platform), "platform": d.platform}
        for d in jax.devices()
    ]
    print(json.dumps({"host_devices": devices, "process_index": jax.process_index(),
                      "process_count": jax.process_count()}, indent=2))
    return 0


def cmd_cluster(args) -> int:
    from fedml_tpu.sched.agent import JobDB

    db_path = Path(args.spool) / "jobs.sqlite"
    jobs = JobDB(str(db_path)).all_jobs() if db_path.exists() else []
    running = [j for j in jobs if j.get("status") == "RUNNING"]
    print(json.dumps({"spool": args.spool, "jobs_total": len(jobs),
                      "running": len(running)}, indent=2))
    return 0


# -- storage (reference storage.py; local object dir) ------------------------

def cmd_storage(args) -> int:
    root = (Path(args.spool) / "storage").resolve()
    root.mkdir(parents=True, exist_ok=True)

    def contained(name: str) -> Path:
        """Resolve an object name INSIDE the storage root; '..'-style
        traversal out of the object dir is refused."""
        p = (root / name).resolve()
        if not p.is_relative_to(root):
            print(f"error: object name {name!r} escapes the storage root", file=sys.stderr)
            raise SystemExit(2)
        return p

    import shutil

    if args.storage_cmd == "upload":
        src = Path(args.path)
        dest = contained(src.name)
        shutil.copyfile(src, dest)  # streaming copy — objects can be GBs
        print(str(dest))
        return 0
    if args.storage_cmd == "download":
        src = contained(args.path)
        if not src.exists():
            print(f"error: no object {args.path}", file=sys.stderr)
            return 2
        out = Path(args.output or args.path)
        shutil.copyfile(src, out)
        print(str(out))
        return 0
    if args.storage_cmd == "list":
        for p in sorted(root.iterdir()):
            print(json.dumps({"name": p.name, "bytes": p.stat().st_size}))
        return 0
    if args.storage_cmd == "delete":
        target = contained(args.path)
        if target.exists():
            target.unlink()
            print("deleted")
            return 0
        print(f"error: no object {args.path}", file=sys.stderr)
        return 2
    return 2


# -- obs (round tracing / metrics trails; ISSUE 1 observability layer) -------

def cmd_obs(args) -> int:
    """Reconstruct round timelines from collector/metrics JSONL trails
    (written by ObsCollector via extra.obs_jsonl_path, or MetricsLogger)."""
    from fedml_tpu.obs import report as obs_report

    if args.obs_cmd == "report":
        records = []
        for path in args.jsonl:
            if not Path(path).exists():
                print(f"error: no trail {path}", file=sys.stderr)
                return 2
            records.extend(obs_report.load_jsonl(path))
        if not records:
            print("error: trails contain no records", file=sys.stderr)
            return 1
        print(obs_report.render_report(records), end="")
        return 0
    if args.obs_cmd == "export":
        from fedml_tpu.obs import otlp as obs_otlp

        records = []
        for path in args.jsonl:
            if not Path(path).exists():
                print(f"error: no trail {path}", file=sys.stderr)
                return 2
            records.extend(obs_report.load_jsonl(path))
        if not records:
            print("error: trails contain no records", file=sys.stderr)
            return 1
        summary = obs_otlp.export_jsonl_trail(
            args.endpoint, records,
            batch_size=args.batch_size, timeout_s=args.timeout,
        )
        print(json.dumps(summary))
        failed = summary["spans_failed"] + summary["metric_points_failed"]
        return 0 if failed == 0 else 1
    if args.obs_cmd == "postmortem":
        from fedml_tpu.obs import postmortem as obs_postmortem

        if not Path(args.path).exists():
            print(f"error: no such path {args.path}", file=sys.stderr)
            return 2
        stitched = obs_postmortem.stitch_bundles(args.path)
        if not stitched["bundles"]:
            print(f"error: no readable flight bundles under {args.path}",
                  file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(stitched))
        else:
            print(obs_postmortem.render_postmortem(stitched, limit=args.limit))
        # the postmortem's own verdict drives the exit code so CI can gate
        # on it: an unaccounted loss or an unattributable lost upload fails
        bad = (stitched.get("unaccounted") or 0) + \
            stitched["uploads"]["unattributed_lost"]
        return 0 if bad == 0 else 1
    if args.obs_cmd == "dash":
        from fedml_tpu.obs import dash as obs_dash
        from fedml_tpu.obs import timeline as obs_timeline

        if not Path(args.path).exists():
            print(f"error: no such path {args.path}", file=sys.stderr)
            return 2
        loaded = obs_timeline.load_timeline(args.path)
        if not loaded["samples"] and not loaded["rounds"]:
            print(f"error: no readable timeline segments under {args.path}",
                  file=sys.stderr)
            return 1
        profile = None
        if args.profile:
            if not Path(args.profile).exists():
                print(f"error: no attribution json {args.profile}",
                      file=sys.stderr)
                return 2
            with open(args.profile) as f:
                profile = json.load(f)
        if args.html:
            html_doc = obs_dash.render_dash_html(loaded, profile)
            Path(args.html).write_text(html_doc)
            print(f"wrote {args.html} ({len(html_doc)} bytes)",
                  file=sys.stderr)
        print(obs_dash.render_dash_text(loaded, profile))
        return 0
    if args.obs_cmd == "serve":
        from fedml_tpu.obs.registry import REGISTRY, MetricsHTTPServer

        server = MetricsHTTPServer(REGISTRY, port=args.port).start()
        print(f"serving /metrics and /healthz on :{server.port}", file=sys.stderr)
        try:
            import time as _t

            while True:
                _t.sleep(1)
        except KeyboardInterrupt:
            server.close()
        return 0
    print(f"unknown obs subcommand {args.obs_cmd}", file=sys.stderr)
    return 2


# -- lint (analysis/: AST invariant checker, tier-1-enforced) ----------------

def cmd_lint(args) -> int:
    """Run the GL001-GL012 static invariant rules over a package tree.

    Exit 0 = clean (counting inline suppressions and the baseline),
    1 = unsuppressed findings or unparseable files.  Deliberately imports no
    jax: the bench/dryrun drivers run this in processes that must not touch
    the accelerator runtime."""
    from fedml_tpu.analysis import engine as lint_engine
    from fedml_tpu.analysis import findings as lint_findings

    pkg_dir = Path(__file__).resolve().parent
    target = Path(args.path) if args.path else pkg_dir
    if not target.exists():
        print(f"error: no such path {target}", file=sys.stderr)
        return 2
    if args.fix:
        # --fix first rewrites the mechanical legacy idioms in place, then
        # falls through to the normal lint pass so what remains (manual
        # sites, other rules) is reported against the FIXED sources
        from fedml_tpu.analysis.fix import fix_tree

        summary = fix_tree(target)
        if args.format == "json":
            print(json.dumps({"files_changed": summary.files_changed,
                              "rewrites": summary.rewrites,
                              "manual": summary.skipped}))
        else:
            print(summary.render())
    baseline = Path(args.baseline) if args.baseline else pkg_dir / "analysis" / "baseline.json"
    result = lint_engine.run_lint(target, baseline=baseline if baseline.exists() else None)
    if args.write_baseline:
        lint_findings.save_baseline(baseline, result.findings)
        print(f"baselined {len(result.findings)} finding(s) into {baseline}")
        return 0
    if args.format == "json":
        print(json.dumps({
            "ok": result.ok,
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "severity": f.severity, "message": f.message, "key": f.key}
                for f in result.findings
            ],
            "counts_by_rule": result.counts_by_rule(),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
            "parse_errors": result.errors,
        }))
    else:
        print(result.render())
    return 0 if result.ok else 1


def cmd_diagnosis(args) -> int:
    """Reference diagnosis.py checks SaaS/MQTT/S3 connectivity; here the
    self-hosted equivalents: jax backend usable, a jit executes, the spool is
    writable, and the TCP transport can bind."""
    import socket

    checks = {}
    try:
        import jax
        import jax.numpy as jnp

        checks["jax_backend"] = jax.default_backend()
        checks["jit_executes"] = bool(jax.jit(lambda x: x + 1)(jnp.ones(8))[0] == 2.0)
    except Exception as e:
        checks["jax_error"] = f"{type(e).__name__}: {e}"
    try:
        Path(args.spool).mkdir(parents=True, exist_ok=True)
        probe = Path(args.spool) / ".diag"
        probe.write_text("ok")
        probe.unlink()
        checks["spool_writable"] = True
    except Exception as e:
        checks["spool_writable"] = f"{type(e).__name__}: {e}"
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            checks["tcp_bind"] = True
    except Exception as e:
        checks["tcp_bind"] = f"{type(e).__name__}: {e}"
    ok = checks.get("jit_executes") is True and checks.get("spool_writable") is True \
        and checks.get("tcp_bind") is True
    checks["ok"] = ok
    print(json.dumps(checks, indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fedml-tpu")
    parser.add_argument("--spool", default=DEFAULT_SPOOL, help="local scheduler spool dir")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("run", help="run a training recipe yaml")
    p.add_argument("--cf", dest="config", required=True)
    p.add_argument("--rank", type=int, default=None)
    p.add_argument("--role", default=None)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("launch", help="package + submit a job.yaml")
    p.add_argument("job_yaml")
    p.set_defaults(fn=cmd_launch)

    p = sub.add_parser("build", help="build a run package without submitting")
    p.add_argument("job_yaml")
    p.set_defaults(fn=cmd_build)

    p = sub.add_parser("agent", help="start a worker agent on the spool")
    p.add_argument("--poll", type=float, default=0.5)
    p.add_argument("--agent-id", default="", help="stable agent id (default: agent_<pid>)")
    p.add_argument("--num-devices", type=int, default=1,
                   help="devices this agent offers (matched against job computing.minimum_num_gpus)")
    p.add_argument("--device-type", default="",
                   help="device type label (matched against computing.request_gpu_type)")
    p.add_argument("--mem-gb", type=float, default=0,
                   help="memory capacity in GB (0 = unlimited)")
    p.set_defaults(fn=cmd_agent)

    p = sub.add_parser("jobs", help="list job statuses")
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("logs", help="print a run's logs")
    p.add_argument("run_id")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("env", help="print environment info")
    p.set_defaults(fn=cmd_env)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(fn=cmd_version)

    p = sub.add_parser("login", help="store local account credentials")
    p.add_argument("account")
    p.add_argument("--api-key", default="")
    p.set_defaults(fn=cmd_login)

    p = sub.add_parser("logout", help="remove local account credentials")
    p.set_defaults(fn=cmd_logout)

    p = sub.add_parser("train", help="run a centralized training recipe")
    p.add_argument("--cf", dest="config", required=True)
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("federate", help="run a federated recipe (refuses centralized)")
    p.add_argument("--cf", dest="config", required=True)
    p.set_defaults(fn=cmd_federate)

    p = sub.add_parser("model", help="model card registry + deploy")
    msub = p.add_subparsers(dest="model_cmd", required=True)
    mc = msub.add_parser("create")
    mc.add_argument("--name", required=True)
    mc.add_argument("--model-version", default="v1")
    mc.add_argument("--arch", required=True, help="model_hub name, e.g. lr/resnet20")
    mc.add_argument("--classes", type=int, default=10)
    mc.add_argument("--params", required=True, help="pytree-wire params file")
    ml = msub.add_parser("list")
    md = msub.add_parser("delete")
    md.add_argument("--name", required=True)
    mdep = msub.add_parser("deploy")
    mdep.add_argument("--name", required=True)
    mdep.add_argument("--model-version", default="v1")
    mdep.add_argument("--endpoint", required=True)
    mdep.add_argument("--replicas", type=int, default=1)
    mdep.add_argument("--timeout", type=float, default=60.0)
    mdep.add_argument("--watch", action="store_true")
    p.set_defaults(fn=cmd_model)

    p = sub.add_parser("device", help="show local accelerator devices")
    p.set_defaults(fn=cmd_device)

    p = sub.add_parser("cluster", help="show local cluster/agent status")
    p.set_defaults(fn=cmd_cluster)

    p = sub.add_parser("storage", help="local object storage")
    ssub = p.add_subparsers(dest="storage_cmd", required=True)
    su = ssub.add_parser("upload")
    su.add_argument("path")
    sd = ssub.add_parser("download")
    sd.add_argument("path")
    sd.add_argument("--output", default="")
    ssub.add_parser("list")
    sdel = ssub.add_parser("delete")
    sdel.add_argument("path")
    p.set_defaults(fn=cmd_storage)

    p = sub.add_parser("obs", help="observability: round timelines, metrics endpoint")
    osub = p.add_subparsers(dest="obs_cmd", required=True)
    orep = osub.add_parser("report", help="round timeline + straggler report from JSONL trails")
    orep.add_argument("jsonl", nargs="+", help="collector/metrics JSONL trail path(s)")
    oexp = osub.add_parser(
        "export", help="backfill a JSONL trail into an OTLP/HTTP collector")
    oexp.add_argument("jsonl", nargs="+", help="collector JSONL trail path(s)")
    oexp.add_argument("--endpoint", required=True,
                      help="collector base URL (POSTs /v1/traces and /v1/metrics)")
    oexp.add_argument("--batch-size", type=int, default=512)
    oexp.add_argument("--timeout", type=float, default=10.0)
    oserve = osub.add_parser("serve", help="serve /metrics + /healthz for this process")
    oserve.add_argument("--port", type=int, default=9109)
    opm = osub.add_parser(
        "postmortem",
        help="stitch flight-recorder bundles into one causal failure timeline")
    opm.add_argument("path", help="flight bundle directory (recursive) or one .flight file")
    opm.add_argument("--json", action="store_true", dest="as_json",
                     help="emit the stitched structure as JSON instead of text")
    opm.add_argument("--limit", type=int, default=40,
                     help="timeline events to render (<=0 = all; default 40)")
    odash = osub.add_parser(
        "dash",
        help="performance dashboard from recorded timeline segments")
    odash.add_argument("path",
                       help="timeline segment directory (extra.timeline_dir)")
    odash.add_argument("--html", default="",
                       help="also write a self-contained HTML dashboard here")
    odash.add_argument("--profile", default="",
                       help="profiler attribution JSON (obs/profiler.py) to "
                            "render as an attribution table")
    p.set_defaults(fn=cmd_obs)

    p = sub.add_parser("lint", help="AST invariant checker (GL001-GL012) over fedml_tpu/")
    p.add_argument("path", nargs="?", default="",
                   help="package dir or single .py file (default: the installed fedml_tpu package)")
    p.add_argument("--baseline", default="",
                   help="suppression baseline JSON (default: fedml_tpu/analysis/baseline.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings into the baseline instead of failing")
    p.add_argument("--fix", action="store_true",
                   help="mechanically rewrite legacy extra idioms to the "
                        "registry helpers (cfg_extra / cfg_extra_present / "
                        "set_cfg_extra) before linting")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("diagnosis", help="environment/connectivity self-check")
    p.set_defaults(fn=cmd_diagnosis)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
