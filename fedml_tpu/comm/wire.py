"""Pytree wire format — defined FIRST so every backend and the future C++
client speak the same bytes (SURVEY.md §7 hard part 6).

The reference pickles torch ``state_dict``s (MPI/gRPC,
``grpc_comm_manager.py``) or uploads them to S3 (MQTT path) — Python-only and
version-fragile.  Here a pytree serializes to a self-describing, polyglot
layout:

    [4-byte LE header length][header JSON][raw little-endian buffers...]

header = {"treedef": <json pytree skeleton>, "leaves": [{dtype, shape,
nbytes}...], "version": 1}.  A non-Python client needs only a JSON parser to
read or produce it.  No pickle anywhere.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

WIRE_VERSION = 1

# JSON pytree skeleton: dict -> {"d": {k: skel}}, list/tuple -> {"l"/"t": [...]},
# leaf -> {"x": leaf_index}


def _build_skeleton(obj, leaves: list):
    if isinstance(obj, dict):
        return {"d": {str(k): _build_skeleton(v, leaves) for k, v in sorted(obj.items())}}
    if isinstance(obj, (list, tuple)):
        tag = "l" if isinstance(obj, list) else "t"
        return {tag: [_build_skeleton(v, leaves) for v in obj]}
    leaves.append(obj)
    return {"x": len(leaves) - 1}


def _restore_skeleton(skel, leaves: list):
    if "d" in skel:
        return {k: _restore_skeleton(v, leaves) for k, v in skel["d"].items()}
    if "l" in skel:
        return [_restore_skeleton(v, leaves) for v in skel["l"]]
    if "t" in skel:
        return tuple(_restore_skeleton(v, leaves) for v in skel["t"])
    return leaves[skel["x"]]


def encode_pytree(tree: Any) -> bytes:
    """Pytree of arrays/scalars -> wire bytes."""
    leaves: list = []
    skel = _build_skeleton(tree, leaves)
    arrs = [np.asarray(l) for l in leaves]
    header = {
        "version": WIRE_VERSION,
        "treedef": skel,
        "leaves": [
            {"dtype": a.dtype.str, "shape": list(a.shape), "nbytes": int(a.nbytes)}
            for a in arrs
        ],
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    parts = [struct.pack("<I", len(hbytes)), hbytes]
    for a in arrs:
        parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def decode_pytree(data: bytes) -> Any:
    """Wire bytes -> pytree of numpy arrays."""
    (hlen,) = struct.unpack_from("<I", data, 0)
    header = json.loads(data[4 : 4 + hlen].decode("utf-8"))
    if header.get("version") != WIRE_VERSION:
        raise ValueError(f"unsupported wire version {header.get('version')}")
    offset = 4 + hlen
    leaves = []
    for spec in header["leaves"]:
        dt = np.dtype(spec["dtype"])
        n = spec["nbytes"]
        arr = np.frombuffer(data, dtype=dt, count=n // dt.itemsize, offset=offset).reshape(spec["shape"])
        leaves.append(arr.copy())  # own the memory
        offset += n
    return _restore_skeleton(header["treedef"], leaves)
