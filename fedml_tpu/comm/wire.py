"""Pytree wire format — defined FIRST so every backend and the future C++
client speak the same bytes (SURVEY.md §7 hard part 6).

The reference pickles torch ``state_dict``s (MPI/gRPC,
``grpc_comm_manager.py``) or uploads them to S3 (MQTT path) — Python-only and
version-fragile.  Here a pytree serializes to a self-describing, polyglot
layout:

    [4-byte LE header length][header JSON][per-leaf segments...]

v1 header = {"treedef": <json pytree skeleton>, "leaves": [{dtype, shape,
nbytes}...], "version": 1}.  A non-Python client needs only a JSON parser to
read or produce it.  No pickle anywhere.

**Wire v2** (compressed streaming rounds) extends every leaf spec with a
``codec`` field and keeps the same envelope:

- ``raw``   — little-endian buffer, exactly the v1 layout.
- ``qsgd8`` — block-scaled stochastic int8 (the ``ops/pallas/quantize.py``
  semantics): segment = per-block f32 scales then int8 values; spec carries
  ``blocks`` and the unpadded ``length``.
- ``topk``  — sparse delta: segment = int32 indices then f32 values; spec
  carries the dense ``size`` and ``k``.

v2 frames are emitted only when the tree contains :class:`CompressedLeaf`
leaves; plain trees keep producing **bit-identical v1 bytes**.  Decode
accepts both versions.  Encoding is writev-style: ``encode_pytree_chunks``
yields bounded buffer views (no leaf is ever duplicated through ``tobytes``
and no giant intermediate blob exists beyond the single final join), and
decoding returns ``np.frombuffer`` views into the received buffer instead of
per-leaf copies.  :class:`PytreeStreamDecoder` decodes incrementally from
bounded chunks so a receiver can fold leaves into an accumulator while the
rest of the frame is still in flight.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Iterator, Optional

import numpy as np

from ..analysis import tracesan

WIRE_VERSION = 1
WIRE_VERSION_V2 = 2

#: bound on the buffer views yielded by :func:`encode_pytree_chunks` — a
#: large model streams as many bounded chunks instead of one giant blob
CHUNK_BYTES_DEFAULT = 1 << 20

#: transport chunk-frame magic (``extra.comm_chunk_bytes`` framing): a
#: message larger than the configured bound ships as N bounded frames of
#: ``MAGIC + <4-byte LE subheader len> + subheader JSON + chunk bytes`` so
#: concurrent uploads interleave at the socket level instead of one slow
#: 100MB frame head-of-line-blocking the receiver.  The magic can never
#: collide with a legacy frame: a legacy payload starts with a 4-byte
#: control-JSON length, and these 4 bytes decode to ~1.2 GB — far beyond
#: any real control section.
CHUNK_MAGIC = b"FMLCHNK1"

#: elements per qsgd8 block (matches the (8, 128) f32 tile of
#: ``ops/pallas/quantize.py``)
QSGD8_BLOCK = 1024

# JSON pytree skeleton: dict -> {"d": {k: skel}}, list/tuple -> {"l"/"t": [...]},
# leaf -> {"x": leaf_index}


def _build_skeleton(obj, leaves: list):
    if isinstance(obj, dict):
        return {"d": {str(k): _build_skeleton(v, leaves) for k, v in sorted(obj.items())}}
    if isinstance(obj, (list, tuple)):
        tag = "l" if isinstance(obj, list) else "t"
        return {tag: [_build_skeleton(v, leaves) for v in obj]}
    leaves.append(obj)
    return {"x": len(leaves) - 1}


def _restore_skeleton(skel, leaves: list):
    if "d" in skel:
        return {k: _restore_skeleton(v, leaves) for k, v in skel["d"].items()}
    if "l" in skel:
        return [_restore_skeleton(v, leaves) for v in skel["l"]]
    if "t" in skel:
        return tuple(_restore_skeleton(v, leaves) for v in skel["t"])
    return leaves[skel["x"]]


def flatten_with_skeleton(tree: Any) -> tuple:
    """(skeleton, leaves) in wire order — the leaf ordering every frame built
    from ``tree``'s structure uses (sorted dict keys, depth first)."""
    leaves: list = []
    skel = _build_skeleton(tree, leaves)
    return skel, leaves


def restore_skeleton(skel, leaves: list) -> Any:
    return _restore_skeleton(skel, leaves)


class CompressedLeaf:
    """A pre-compressed wire-v2 leaf: codec name, dense dtype/shape, codec
    metadata, and the raw segment arrays whose bytes go on the wire.

    ``qsgd8``: segments = (f32 scales ``(blocks,)``, int8 values
    ``(blocks*1024,)``), meta = {"blocks", "length"}.
    ``topk``: segments = (int32 indices ``(k,)``, f32 values ``(k,)``),
    meta = {"size", "k"}.
    """

    __slots__ = ("codec", "dtype", "shape", "meta", "segments")

    def __init__(self, codec: str, dtype, shape, meta: dict, segments):
        self.codec = str(codec)
        self.dtype = np.dtype(dtype).str
        self.shape = tuple(int(s) for s in shape)
        self.meta = dict(meta)
        self.segments = tuple(np.ascontiguousarray(s) for s in segments)

    @property
    def nbytes(self) -> int:
        return sum(int(s.nbytes) for s in self.segments)

    def spec(self) -> dict:
        d = {"codec": self.codec, "dtype": self.dtype,
             "shape": list(self.shape), "nbytes": int(self.nbytes)}
        d.update(self.meta)
        return d

    def dense(self) -> np.ndarray:
        """Decode back to the dense array (test/debug convenience)."""
        raw = b"".join(_raw_view(s) for s in self.segments)
        return _decode_leaf(self.spec(), memoryview(raw), 0)

    def __repr__(self) -> str:
        return (f"CompressedLeaf({self.codec}, dtype={self.dtype}, "
                f"shape={self.shape}, nbytes={self.nbytes})")


def _raw_view(a: np.ndarray):
    """Zero-copy read view of an array's bytes (no ``tobytes`` duplicate)."""
    a = np.ascontiguousarray(a)
    if a.nbytes == 0:
        return b""
    return memoryview(a.reshape(-1).view(np.uint8))


def _prepare_frame(tree: Any) -> tuple:
    """(header_dict, [buffer views]) for a pytree; picks v1 vs v2 by whether
    any leaf is a :class:`CompressedLeaf`.  v1 headers are constructed with
    exactly the historical key order so plain trees stay bit-identical."""
    leaves: list = []
    skel = _build_skeleton(tree, leaves)
    specs: list[dict] = []
    buffers: list = []
    compressed = False
    with tracesan.allow("wire_encode"):
        # device leaves materialize here (np.asarray is the d2h): the wire
        # boundary is THE legitimate host crossing of the upload path
        for leaf in leaves:
            if isinstance(leaf, CompressedLeaf):
                compressed = True
                specs.append(leaf.spec())
                buffers.extend(_raw_view(s) for s in leaf.segments)
            else:
                # NOTE: spec shape from np.asarray, NOT ascontiguousarray —
                # the latter promotes 0-d scalars to (1,) and would change
                # v1 bytes
                a = np.asarray(leaf)
                specs.append({"dtype": a.dtype.str, "shape": list(a.shape),
                              "nbytes": int(a.nbytes)})
                buffers.append(_raw_view(a))
    if compressed:
        for spec in specs:
            spec.setdefault("codec", "raw")
        header = {"version": WIRE_VERSION_V2, "treedef": skel, "leaves": specs}
    else:
        header = {"version": WIRE_VERSION, "treedef": skel, "leaves": specs}
    return header, buffers


def encode_pytree_chunks(tree: Any, chunk_bytes: int = CHUNK_BYTES_DEFAULT) -> Iterator:
    """Writev-style encoder: yields bounded bytes-like views (header first,
    then per-leaf segments in ≤ ``chunk_bytes`` pieces).  Nothing here copies
    a leaf — the views alias the source arrays, so the only full copy of the
    payload is whatever the transport does with the chunks."""
    header, buffers = _prepare_frame(tree)
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    yield struct.pack("<I", len(hbytes)) + hbytes
    for buf in buffers:
        n = len(buf) if isinstance(buf, (bytes, bytearray)) else buf.nbytes
        if n == 0:
            continue
        mv = buf if isinstance(buf, memoryview) else memoryview(buf)
        if n <= chunk_bytes:
            yield mv
        else:
            for s in range(0, n, chunk_bytes):
                yield mv[s:s + chunk_bytes]


def encode_pytree(tree: Any) -> bytes:
    """Pytree of arrays/scalars (and/or :class:`CompressedLeaf`) -> wire
    bytes.  One output allocation; leaves are copied exactly once, into it."""
    return b"".join(encode_pytree_chunks(tree))


def _as_bytes_view(data) -> memoryview:
    mv = data if isinstance(data, memoryview) else memoryview(data)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return mv


def decode_header(data) -> tuple:
    """Parse + validate the frame header; returns ``(header, payload_offset)``.

    Validates the version and that the declared leaf bytes exactly fill the
    buffer, so framing corruption fails HERE (the receive loop's drop path)
    rather than at first lazy leaf access."""
    mv = _as_bytes_view(data)
    if len(mv) < 4:
        raise ValueError(f"wire frame too short ({len(mv)} bytes)")
    (hlen,) = struct.unpack_from("<I", mv, 0)
    if 4 + hlen > len(mv):
        raise ValueError(f"wire header truncated ({hlen} declared, {len(mv) - 4} present)")
    header = json.loads(bytes(mv[4:4 + hlen]).decode("utf-8"))
    version = header.get("version")
    if version not in (WIRE_VERSION, WIRE_VERSION_V2):
        raise ValueError(f"unsupported wire version {version}")
    payload = sum(int(spec["nbytes"]) for spec in header["leaves"])
    if 4 + hlen + payload != len(mv):
        raise ValueError(
            f"wire payload length mismatch: header declares {payload} leaf "
            f"bytes, buffer has {len(mv) - 4 - hlen}"
        )
    return header, 4 + hlen


def _decode_leaf(spec: dict, mv: memoryview, offset: int) -> np.ndarray:
    """One leaf segment -> dense array.  ``raw`` leaves are zero-copy
    ``np.frombuffer`` views into the receive buffer; compressed codecs
    dequantize/scatter into fresh arrays."""
    codec = spec.get("codec", "raw")
    shape = tuple(spec["shape"])
    dtype = np.dtype(spec["dtype"])
    if codec == "raw":
        n = int(spec["nbytes"])
        return np.frombuffer(mv, dtype=dtype, count=n // dtype.itemsize,
                             offset=offset).reshape(shape)
    if codec == "qsgd8":
        blocks = int(spec["blocks"])
        length = int(spec["length"])
        scales = np.frombuffer(mv, dtype="<f4", count=blocks, offset=offset)
        values = np.frombuffer(mv, dtype=np.int8, count=blocks * QSGD8_BLOCK,
                               offset=offset + 4 * blocks)
        deq = values.reshape(blocks, QSGD8_BLOCK).astype(np.float32) * scales[:, None]
        return deq.reshape(-1)[:length].astype(dtype, copy=False).reshape(shape)
    if codec == "topk":
        size = int(spec["size"])
        k = int(spec["k"])
        idx = np.frombuffer(mv, dtype="<i4", count=k, offset=offset)
        vals = np.frombuffer(mv, dtype="<f4", count=k, offset=offset + 4 * k)
        out = np.zeros(size, np.float32)
        out[idx] = vals
        return out.astype(dtype, copy=False).reshape(shape)
    raise ValueError(f"unknown wire codec {codec!r}")


def iter_leaf_arrays(data, header: Optional[dict] = None,
                     offset: Optional[int] = None) -> Iterator:
    """Decode leaf-by-leaf: yields ``(index, spec, dense_array)`` in wire
    order without ever materializing the whole pytree — the streaming-
    aggregation primitive (fold each leaf, drop it, move on)."""
    mv = _as_bytes_view(data)
    if header is None:
        header, offset = decode_header(mv)
    off = int(offset)
    for i, spec in enumerate(header["leaves"]):
        yield i, spec, _decode_leaf(spec, mv, off)
        off += int(spec["nbytes"])


def decode_pytree(data, header: Optional[dict] = None,
                  offset: Optional[int] = None) -> Any:
    """Wire bytes -> pytree of numpy arrays (v1 or v2; compressed leaves come
    back dense).  ``raw`` leaves are read-only views into ``data`` — copy
    before mutating."""
    mv = _as_bytes_view(data)
    if header is None:
        header, offset = decode_header(mv)
    leaves = [arr for _, _, arr in iter_leaf_arrays(mv, header=header, offset=offset)]
    return _restore_skeleton(header["treedef"], leaves)


# ---------------------------------------------------------------------------
# transport chunk frames (socket-level interleaving of concurrent uploads)
# ---------------------------------------------------------------------------

def is_chunk_frame(data) -> bool:
    """True when ``data`` is a transport chunk frame (vs a legacy whole-
    message payload)."""
    mv = _as_bytes_view(data)
    return len(mv) >= len(CHUNK_MAGIC) and bytes(mv[: len(CHUNK_MAGIC)]) == CHUNK_MAGIC


def encode_chunk_frames(payload, *, stream_id: str, sender: int,
                        chunk_bytes: int) -> Iterator[bytes]:
    """Split one encoded message into bounded, self-describing chunk frames.

    Each frame carries ``{"stream", "sender", "seq", "chunks", "total"}`` so
    the receiver can reassemble N interleaved streams per peer (out-of-order
    delivery tolerated — gRPC unary chunks are separate RPCs)."""
    mv = _as_bytes_view(payload)
    chunk_bytes = max(1, int(chunk_bytes))
    total = len(mv)
    n_chunks = max(1, -(-total // chunk_bytes))
    for seq in range(n_chunks):
        sub = json.dumps(
            {"stream": str(stream_id), "sender": int(sender), "seq": seq,
             "chunks": n_chunks, "total": total},
            separators=(",", ":")).encode("utf-8")
        chunk = mv[seq * chunk_bytes: (seq + 1) * chunk_bytes]
        yield CHUNK_MAGIC + struct.pack("<I", len(sub)) + sub + bytes(chunk)


def parse_chunk_frame(data) -> tuple:
    """One chunk frame -> ``(subheader_dict, chunk_payload_view)``."""
    mv = _as_bytes_view(data)
    if not is_chunk_frame(mv):
        raise ValueError("not a chunk frame (bad magic)")
    off = len(CHUNK_MAGIC)
    if len(mv) < off + 4:
        raise ValueError("chunk frame truncated before subheader length")
    (slen,) = struct.unpack_from("<I", mv, off)
    if len(mv) < off + 4 + slen:
        raise ValueError("chunk frame subheader truncated")
    sub = json.loads(bytes(mv[off + 4: off + 4 + slen]).decode("utf-8"))
    for field in ("stream", "sender", "seq", "chunks", "total"):
        if field not in sub:
            raise ValueError(f"chunk subheader missing {field!r}")
    return sub, mv[off + 4 + slen:]


class PytreeStreamDecoder:
    """Incremental frame decoder: ``feed()`` bounded chunks as they arrive;
    each call returns the leaves completed by that chunk as
    ``(index, spec, array)`` tuples, and consumed bytes are released — peak
    buffered memory stays ~(largest leaf + chunk), not the whole frame.

    With ``retain_leaves=True`` (default) the decoded leaves are kept so
    ``result()`` can rebuild the full pytree; a streaming aggregator passes
    ``False`` and folds each leaf as it completes.
    """

    def __init__(self, retain_leaves: bool = True):
        self._buf = bytearray()
        self._header: Optional[dict] = None
        self._leaf_idx = 0
        self._retain = retain_leaves
        self._leaves: list = []

    @property
    def header(self) -> Optional[dict]:
        return self._header

    @property
    def complete(self) -> bool:
        return self._header is not None and self._leaf_idx >= len(self._header["leaves"])

    def feed(self, chunk) -> list:
        self._buf += bytes(chunk) if isinstance(chunk, memoryview) else chunk
        out: list = []
        if self._header is None:
            if len(self._buf) < 4:
                return out
            (hlen,) = struct.unpack_from("<I", self._buf, 0)
            if len(self._buf) < 4 + hlen:
                return out
            header = json.loads(bytes(self._buf[4:4 + hlen]).decode("utf-8"))
            if header.get("version") not in (WIRE_VERSION, WIRE_VERSION_V2):
                raise ValueError(f"unsupported wire version {header.get('version')}")
            self._header = header
            del self._buf[:4 + hlen]
        specs = self._header["leaves"]
        while self._leaf_idx < len(specs):
            spec = specs[self._leaf_idx]
            n = int(spec["nbytes"])
            if len(self._buf) < n:
                break
            # copy out of the mutable buffer: the view would be invalidated
            # by the del below (bounded memory beats zero-copy here)
            arr = _decode_leaf(spec, memoryview(bytes(self._buf[:n])), 0)
            del self._buf[:n]
            if self._retain:
                self._leaves.append(arr)
            out.append((self._leaf_idx, spec, arr))
            self._leaf_idx += 1
        if self.complete and self._buf:
            raise ValueError(f"{len(self._buf)} trailing bytes after final leaf")
        return out

    def leaves(self) -> list:
        """The decoded leaves in wire order (requires ``retain_leaves``);
        with ``header`` this is the zero-recompute input to
        :meth:`~fedml_tpu.comm.message.Message.from_stream`."""
        if not self._retain:
            raise ValueError("decoder built with retain_leaves=False")
        return self._leaves

    def result(self) -> Any:
        if not self.complete:
            raise ValueError(
                f"frame incomplete: {self._leaf_idx}/"
                f"{len(self._header['leaves']) if self._header else '?'} leaves decoded"
            )
        if not self._retain:
            raise ValueError("decoder built with retain_leaves=False")
        return _restore_skeleton(self._header["treedef"], self._leaves)
