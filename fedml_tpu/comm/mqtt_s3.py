"""MQTT + object-store backend (control/payload split).

Parity with ``core/distributed/communication/mqtt_s3/``
(``MqttS3MultiClientsCommManager`` ``mqtt_s3_multi_clients_comm_manager.py:20``):
small control JSON rides broker topics ``fedml_{run_id}_{sender}_{receiver}``
(QoS2 semantics), large tensor payloads are uploaded to an object store and
the message carries only the key (``send_message`` :248 upload decision,
``_on_message_impl`` :195 download); ONLINE/OFFLINE last-will liveness
messages on a status topic (``mqtt_manager.py:68-74``).

Both the broker and the store are small interfaces:
- ``InMemoryBroker`` / ``InMemoryObjectStore`` — hermetic fakes (and the
  default in this zero-egress build; paho-mqtt/boto3 are not installed).
- A real deployment implements the same two classes over paho/boto3 without
  touching the manager.
"""

from __future__ import annotations

import json
import queue
import threading
import uuid
from collections import defaultdict
from typing import Callable, Optional

from .base import BaseCommunicationManager, ObserverLoopMixin
from .message import Message

PAYLOAD_INLINE_LIMIT = 8 * 1024  # larger tensor payloads go to the store


class InMemoryBroker:
    """Topic pub/sub with last-will, keyed by run namespace."""

    _brokers: dict[str, "InMemoryBroker"] = {}
    _lock = threading.Lock()

    def __init__(self):
        self.subs: dict[str, list[Callable[[str, bytes], None]]] = defaultdict(list)
        self.wills: dict[str, tuple[str, bytes]] = {}

    @classmethod
    def get(cls, namespace: str) -> "InMemoryBroker":
        with cls._lock:
            if namespace not in cls._brokers:
                cls._brokers[namespace] = cls()
            return cls._brokers[namespace]

    def publish(self, topic: str, payload: bytes) -> None:
        for cb in list(self.subs.get(topic, [])):
            cb(topic, payload)

    def subscribe(self, topic: str, cb: Callable[[str, bytes], None]) -> None:
        self.subs[topic].append(cb)

    def set_will(self, client_id: str, topic: str, payload: bytes) -> None:
        self.wills[client_id] = (topic, payload)

    def disconnect_ungraceful(self, client_id: str) -> None:
        """Simulate a dropped connection: fire the last-will."""
        will = self.wills.pop(client_id, None)
        if will:
            self.publish(*will)


class InMemoryObjectStore:
    """put/get blobs by key (the S3 role)."""

    _stores: dict[str, "InMemoryObjectStore"] = {}
    _lock = threading.Lock()

    def __init__(self):
        self.blobs: dict[str, bytes] = {}

    @classmethod
    def get_store(cls, namespace: str) -> "InMemoryObjectStore":
        with cls._lock:
            if namespace not in cls._stores:
                cls._stores[namespace] = cls()
            return cls._stores[namespace]

    def put(self, key: str, data: bytes) -> str:
        self.blobs[key] = data
        return key

    def get(self, key: str) -> bytes:
        return self.blobs[key]


class MqttS3CommManager(ObserverLoopMixin, BaseCommunicationManager):
    def __init__(self, run_id: str, rank: int, broker: Optional[InMemoryBroker] = None,
                 store: Optional[InMemoryObjectStore] = None):
        self.run_id = str(run_id)
        self.rank = rank
        self.broker = broker or InMemoryBroker.get(self.run_id)
        self.store = store or InMemoryObjectStore.get_store(self.run_id)
        self._init_observer_loop()
        self.client_id = f"{self.run_id}_{rank}"
        # last-will: broker announces our death (reference OFFLINE status)
        self.broker.set_will(
            self.client_id,
            self._status_topic(),
            json.dumps({"ID": rank, "status": "OFFLINE"}).encode(),
        )
        # subscribe to every topic addressed to us: fedml_{run}_{s}_{r}
        # (in-mem broker has no wildcards; we subscribe per-sender lazily via
        # a routing topic instead)
        self.broker.subscribe(self._my_topic(), self._on_message)
        self.broker.publish(
            self._status_topic(), json.dumps({"ID": rank, "status": "ONLINE"}).encode()
        )

    def _my_topic(self) -> str:
        return f"fedml_{self.run_id}_to_{self.rank}"

    def _status_topic(self) -> str:
        return f"fedml_{self.run_id}_status"

    def subscribe_status(self, cb: Callable[[dict], None]) -> None:
        self.broker.subscribe(self._status_topic(), lambda _t, p: cb(json.loads(p.decode())))

    def _on_message(self, topic: str, payload: bytes) -> None:
        self._inbox.put(payload)

    def send_message(self, msg: Message) -> None:
        """One wire format (Message.encode); the only MQTT-specific decision
        is store-offload of large payloads: marker byte 'D' = direct bytes,
        'R' = store reference."""
        body = msg.encode()
        if len(body) > PAYLOAD_INLINE_LIMIT:
            key = f"{self.run_id}/{uuid.uuid4().hex}"
            self.store.put(key, body)
            payload = b"R" + json.dumps({"store_key": key}).encode()
        else:
            payload = b"D" + body
        topic = f"fedml_{self.run_id}_to_{msg.get_receiver_id()}"
        self.broker.publish(topic, payload)

    def _decode_bytes(self, payload: bytes) -> Message:
        marker, rest = payload[:1], payload[1:]
        if marker == b"R":
            rest = self.store.get(json.loads(rest.decode())["store_key"])
        elif marker != b"D":
            raise ValueError(f"unknown payload marker {marker!r}")
        return Message.decode(rest)
