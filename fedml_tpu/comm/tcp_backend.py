"""Plain-TCP communication backend — the polyglot transport.

Purpose (SURVEY.md §2.13, VERDICT item 5): the reference's cross-device
platform drives non-Python phone clients (C++ MobileNN,
``android/fedmlsdk/MobileNN/src/train/FedMLMNNTrainer.cpp``) over MQTT; the
TPU build's equivalent is a second-language client speaking the pytree wire
format.  gRPC C++ isn't available in the build image, so the polyglot
transport is the simplest thing both sides can speak exactly: one listening
socket per endpoint, one short-lived connection per message (the same
unary-per-message shape as the gRPC backend), frames of

    [8-byte LE frame length][Message bytes]

where Message bytes are ``comm.message.Message.encode()`` — 4-byte LE control
length + control JSON + pytree wire blob.  A C client needs only sockets and
a JSON parser (``native/`` holds the C++ implementation).
"""

from __future__ import annotations

import itertools
import logging
import queue
import socket
import struct
import threading
from typing import Optional

from . import wire
from .base import BaseCommunicationManager, ObserverLoopMixin
from .message import Message

log = logging.getLogger("fedml_tpu.comm.tcp")

FRAME_HEADER = struct.Struct("<Q")
MAX_FRAME_BYTES = 1 << 30  # 1 GB, matching the gRPC backend cap


def read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError(f"peer closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(FRAME_HEADER.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes:
    (n,) = FRAME_HEADER.unpack(read_exact(sock, FRAME_HEADER.size))
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {n} bytes exceeds {MAX_FRAME_BYTES}")
    return read_exact(sock, n)


class TCPCommManager(ObserverLoopMixin, BaseCommunicationManager):
    """Endpoint i listens on base_port + i; send opens a connection to
    base_port + receiver_id on the receiver's host (ip_config, default
    loopback)."""

    def __init__(self, host: str, port: int, rank: int,
                 ip_config: Optional[dict] = None, base_port: int = 9690,
                 chunk_bytes: int = 0):
        self._init_observer_loop()
        self.rank = rank
        self.base_port = base_port
        self.ip_config = {int(k): v for k, v in (ip_config or {}).items()}
        # extra.comm_chunk_bytes: messages above this bound ship as bounded
        # chunk frames (wire.encode_chunk_frames) so N concurrent uploads
        # interleave at the socket level; 0 = one frame per message,
        # byte-identical to the legacy protocol
        self.chunk_bytes = int(chunk_bytes or 0)
        self._stream_seq = itertools.count()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while True:
                    self._inbox.put(recv_frame(conn))
        except (ConnectionError, OSError):
            pass  # per-message connections close after one frame
        except ValueError as e:
            # oversized/corrupt frame: drop the connection but NEVER die
            # silently — the sender sees success, so this log line is the
            # only trace of the lost message
            log.error("rank %d dropping connection: %s", self.rank, e)

    def send_message(self, msg: Message) -> None:
        rid = msg.get_receiver_id()
        host = self.ip_config.get(rid, "127.0.0.1")
        payload = msg.encode()
        with socket.create_connection((host, self.base_port + rid), timeout=30.0) as s:
            if self.chunk_bytes and len(payload) > self.chunk_bytes:
                stream_id = f"{self.rank}.{next(self._stream_seq)}"
                for frame in wire.encode_chunk_frames(
                        payload, stream_id=stream_id, sender=self.rank,
                        chunk_bytes=self.chunk_bytes):
                    send_frame(s, frame)
            else:
                send_frame(s, payload)

    def send_raw(self, receiver_id: int, payload: bytes) -> None:
        """One raw frame to a peer, bypassing Message encode — the chaos
        wrapper's corrupt-frame injection point."""
        host = self.ip_config.get(int(receiver_id), "127.0.0.1")
        with socket.create_connection(
                (host, self.base_port + int(receiver_id)), timeout=30.0) as s:
            send_frame(s, payload)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        try:
            self._listener.close()
        except OSError:
            pass
