"""Real-world Ledger adapters for the blockchain comm backends.

These implement the exact two-method ``Ledger`` interface
``comm/blockchain.py``'s manager consumes (``append_tx`` / ``read_since``)
over the same services the reference uses:

- :class:`Web3ContractLedger` — an EVM contract via web3.py (reference
  ``core/distributed/communication/web3/web3_comm_manager.py``: FL messages
  as contract transactions carrying base64 payload strings).  The expected
  contract exposes ``sendMessage(uint64 recipient, string data)`` and an
  append-only ``getMessages(uint256 fromIndex)`` view returning
  ``(uint64 sender, uint64 recipient, string data)[]`` — the minimal mailbox
  the reference's flow needs.
- :class:`ThetaEdgeStoreLedger` — the Theta EdgeStore via its HTTP RPC
  (reference ``thetastore``): payloads are PUT to the store, the returned
  key is appended to a per-run index document.

Import-guarded like ``mqtt_real.py``: the build image ships neither web3.py
nor a Theta node (zero egress), so construction without an injected module /
RPC client raises a clear error; the in-memory chain stays the hermetic
default.  Injection seams (``web3_module`` / ``http_client``) let tests
drive every branch with scripted fakes.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

try:  # pragma: no cover - not installed in the hermetic build
    import web3 as _web3
except ImportError:  # pragma: no cover
    _web3 = None

# minimal mailbox ABI (see module docstring)
MAILBOX_ABI = [
    {
        "name": "sendMessage",
        "type": "function",
        "stateMutability": "nonpayable",
        "inputs": [
            {"name": "recipient", "type": "uint64"},
            {"name": "data", "type": "string"},
        ],
        "outputs": [],
    },
    {
        "name": "getMessages",
        "type": "function",
        "stateMutability": "view",
        "inputs": [{"name": "fromIndex", "type": "uint256"}],
        "outputs": [
            {
                "components": [
                    {"name": "sender", "type": "uint64"},
                    {"name": "recipient", "type": "uint64"},
                    {"name": "data", "type": "string"},
                ],
                "name": "",
                "type": "tuple[]",
            }
        ],
    },
]


class Web3ContractLedger:
    """web3.py-backed Ledger over the mailbox contract."""

    def __init__(self, rpc_url: str, contract_address: str, account: str,
                 private_key: Optional[str] = None, web3_module=None):
        web3 = web3_module if web3_module is not None else _web3
        if web3 is None:
            raise ImportError(
                "web3.py is not installed; install it for an on-chain ledger "
                "or use comm.blockchain.InMemoryLedger for hermetic runs"
            )
        self._w3 = web3.Web3(web3.Web3.HTTPProvider(rpc_url))
        self._contract = self._w3.eth.contract(address=contract_address, abi=MAILBOX_ABI)
        self._account = account
        self._private_key = private_key
        self._lock = threading.Lock()

    # -- Ledger interface ----------------------------------------------------
    def append_tx(self, sender: int, recipient: int, data_b64: str) -> int:
        """Submit sendMessage.  Sender identity on chain is the ACCOUNT, not
        the FL rank — the rank rides inside the Message control header.
        Returns a local monotonic send counter (advisory only — the manager
        ignores it; a global height would cost an O(history) RPC per send and
        still race other accounts' appends)."""
        with self._lock:
            fn = self._contract.functions.sendMessage(int(recipient), data_b64)
            if self._private_key:
                tx = fn.build_transaction({
                    "from": self._account,
                    "nonce": self._w3.eth.get_transaction_count(self._account),
                })
                signed = self._w3.eth.account.sign_transaction(tx, self._private_key)
                tx_hash = self._w3.eth.send_raw_transaction(signed.raw_transaction)
            else:  # unlocked node account (dev chains)
                tx_hash = fn.transact({"from": self._account})
            receipt = self._w3.eth.wait_for_transaction_receipt(tx_hash)
            # a reverted tx (status 0) means the message never landed on
            # chain — surfacing it here beats a receiver waiting forever
            status = receipt.get("status", 1) if hasattr(receipt, "get") else getattr(receipt, "status", 1)
            if status == 0:
                raise RuntimeError(f"sendMessage transaction reverted: {tx_hash!r}")
            self._sent = getattr(self, "_sent", -1) + 1
            return self._sent

    def read_since(self, height: int) -> list[dict]:
        rows = self._contract.functions.getMessages(int(height)).call()
        return [
            {"height": height + i, "sender": int(s), "recipient": int(r), "data": d}
            for i, (s, r, d) in enumerate(rows)
        ]


class ThetaEdgeStoreLedger:
    """Theta EdgeStore-backed Ledger: payload blobs in the store, an
    append-only JSON index document per run keyed by ``index_key``.

    ``http_client`` is any object with ``put(key, bytes) -> key`` and
    ``get(key) -> bytes`` (the EdgeStore RPC adapter); injected for tests,
    constructed from ``theta_rpc_url`` in production deployments."""

    def __init__(self, run_id: str, http_client=None, theta_rpc_url: str = ""):
        if http_client is None:
            raise ImportError(
                "no Theta EdgeStore client available; pass http_client (an "
                "object with put/get) or use comm.blockchain.InMemoryLedger "
                f"(rpc url given: {theta_rpc_url!r})"
            )
        self._store = http_client
        self._index_key = f"fedml_tpu/{run_id}/ledger_index"
        self._lock = threading.Lock()

    def _read_index(self) -> list[dict]:
        try:
            raw = self._store.get(self._index_key)
        except KeyError:
            return []
        return json.loads(raw.decode())

    # -- Ledger interface ----------------------------------------------------
    def append_tx(self, sender: int, recipient: int, data_b64: str,
                  max_retries: int = 16) -> int:
        """Append with optimistic-concurrency retry.  A put/get store has no
        compare-and-swap, so a concurrent writer can clobber the index
        read-modify-write; every blob therefore gets a UNIQUE key (no payload
        can be overwritten), and after writing the index we re-read and
        verify our entry survived — retrying the merge if a racer dropped it.
        This makes lost updates a transient (retried) condition rather than a
        silent one; deployments whose EdgeStore exposes an atomic append
        should implement this method over that primitive instead."""
        import uuid

        blob_key = f"{self._index_key}/tx-{uuid.uuid4().hex}"
        with self._lock:
            self._store.put(blob_key, data_b64.encode())
            for _ in range(max_retries):
                index = self._read_index()
                height = len(index)
                index.append({"height": height, "sender": int(sender),
                              "recipient": int(recipient), "key": blob_key})
                self._store.put(self._index_key, json.dumps(index).encode())
                written = self._read_index()
                for entry in written:
                    if entry["key"] == blob_key:
                        return entry["height"]
            raise RuntimeError(
                f"could not append to {self._index_key} after {max_retries} "
                "retries (heavy index contention)"
            )

    def read_since(self, height: int) -> list[dict]:
        index = self._read_index()
        out = []
        # heights are POSITIONAL (index order), not the stored hints — after
        # a retried merge an entry's recorded height can lag its position
        for pos in range(height, len(index)):
            entry = index[pos]
            data = self._store.get(entry["key"]).decode()
            out.append({"height": pos, "sender": entry["sender"],
                        "recipient": entry["recipient"], "data": data})
        return out
