"""Blockchain comm backends (Web3 / Theta) — messages as ledger transactions.

Parity with the reference's Web3/Theta communication managers
(``core/distributed/communication/web3/web3_comm_manager.py`` /
``thetastore``): FL messages ride a blockchain as transactions — the sender
appends a transaction addressed to a recipient, receivers poll new blocks
and pick out their traffic.  The chain itself is behind a two-method
``Ledger`` interface (append / read-since), mirroring the broker/store
pattern of the MQTT backend:

- :class:`InMemoryLedger` — hermetic chain simulation (append-only blocks
  with heights; the default in this zero-egress build, where web3.py /
  thetajs are not installed).
- A real deployment implements the same interface over web3.py contract
  calls or the Theta EdgeStore without touching the manager.

The payload is the standard Message bytes (pytree wire — no pickle on
chain), base64-wrapped the way the reference stores blobs in tx data.
"""

from __future__ import annotations

import base64
import threading
import time
from typing import Callable, Optional

from .base import BaseCommunicationManager, ObserverLoopMixin
from .message import Message


class InMemoryLedger:
    """Append-only block list shared by namespace (one 'chain' per run)."""

    _chains: dict[str, "InMemoryLedger"] = {}
    _lock = threading.Lock()

    def __init__(self):
        self._blocks: list[dict] = []
        self._block_lock = threading.Lock()

    @classmethod
    def get(cls, namespace: str) -> "InMemoryLedger":
        with cls._lock:
            if namespace not in cls._chains:
                cls._chains[namespace] = cls()
            return cls._chains[namespace]

    @classmethod
    def reset(cls, namespace: str) -> None:
        with cls._lock:
            cls._chains.pop(namespace, None)

    # -- Ledger interface ----------------------------------------------------
    def append_tx(self, sender: int, recipient: int, data_b64: str) -> int:
        """Mine one transaction into a block; returns its height."""
        with self._block_lock:
            height = len(self._blocks)
            self._blocks.append({
                "height": height, "ts": time.time(),
                "sender": sender, "recipient": recipient, "data": data_b64,
            })
            return height

    def read_since(self, height: int) -> list[dict]:
        with self._block_lock:
            return list(self._blocks[height:])


class BlockchainCommManager(ObserverLoopMixin, BaseCommunicationManager):
    """Poll-driven endpoint over a Ledger (reference Web3CommManager shape:
    send = submit transaction; receive = scan new blocks for our address)."""

    def __init__(self, run_id: str, rank: int, ledger: Optional[InMemoryLedger] = None,
                 poll_interval_s: float = 0.05):
        self._init_observer_loop()
        self.rank = rank
        self.ledger = ledger if ledger is not None else InMemoryLedger.get(str(run_id))
        self.poll_interval_s = poll_interval_s
        self._height = 0
        self._poll_stop = threading.Event()
        self._poller = threading.Thread(target=self._poll_loop, daemon=True)
        self._poller.start()

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_interval_s):
            for block in self.ledger.read_since(self._height):
                self._height = block["height"] + 1
                if block["recipient"] == self.rank:
                    self._inbox.put(base64.b64decode(block["data"]))

    def send_message(self, msg: Message) -> None:
        data = base64.b64encode(msg.encode()).decode("ascii")
        self.ledger.append_tx(self.rank, msg.get_receiver_id(), data)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        self._poll_stop.set()
