"""Real-world adapters for the MQTT+S3 backend: paho-mqtt broker client and
boto3 S3 object store.

These implement the exact broker/store interfaces ``comm/mqtt_s3.py``'s
manager consumes (``publish``/``subscribe``/``set_will`` and ``put``/``get``)
over the same libraries the reference uses
(``core/distributed/communication/mqtt_s3/mqtt_manager.py`` /
``remote_storage.py``).  Import-guarded: the build image ships neither
paho-mqtt nor boto3 (zero egress), so construction raises a clear error
naming the missing dependency instead of failing at first use; the in-memory
fakes remain the hermetic default.

Usage::

    broker = PahoMqttBroker("broker.example.com", 1883, client_id="rank0")
    store = S3ObjectStore(bucket="fedml-models")
    mgr = MqttS3CommManager(run_id, rank, broker=broker, store=store)
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

try:  # pragma: no cover - not installed in the hermetic build
    import paho.mqtt.client as _paho
except ImportError:  # pragma: no cover
    _paho = None

try:  # pragma: no cover
    import boto3 as _boto3
except ImportError:  # pragma: no cover
    _boto3 = None


class PahoMqttBroker:
    """paho-backed implementation of the InMemoryBroker interface
    (reference ``mqtt_manager.py:20`` — QoS2, last-will, loop thread)."""

    def __init__(self, host: str, port: int = 1883, client_id: str = "",
                 username: Optional[str] = None, password: Optional[str] = None,
                 keepalive: int = 180, paho_module=None):
        """``paho_module`` is an injection seam (tests drive the adapter with
        a scripted fake; production leaves it None for the real import)."""
        paho = paho_module if paho_module is not None else _paho
        if paho is None:
            raise ImportError(
                "paho-mqtt is not installed; install it for a real broker or "
                "use comm.mqtt_s3.InMemoryBroker for hermetic runs"
            )
        if hasattr(paho, "CallbackAPIVersion"):
            # paho-mqtt >= 2.0 (the pip default since 2024) requires the
            # callback API version and dropped the clean_session kwarg
            self._client = paho.Client(
                paho.CallbackAPIVersion.VERSION1, client_id=client_id
            )
        else:  # paho-mqtt 1.x
            self._client = paho.Client(client_id=client_id, clean_session=True)
        if username:
            self._client.username_pw_set(username, password or "")
        self._subs: dict[str, list[Callable[[str, bytes], None]]] = {}
        self._lock = threading.Lock()
        self._client.on_message = self._dispatch
        # clean-session reconnects start with ZERO subscriptions: re-issue
        # every subscribe on (re)connect or a broker restart silently drops
        # all FL-round traffic
        self._client.on_connect = self._on_connect
        self._host, self._port, self._keepalive = host, port, keepalive
        self._connected = False

    def _on_connect(self, client, userdata, *args, **kwargs) -> None:
        with self._lock:
            topics = list(self._subs)
        for t in topics:
            client.subscribe(t, qos=2)

    def _ensure_connected(self) -> None:
        if not self._connected:
            self._client.connect(self._host, self._port, self._keepalive)
            self._client.loop_start()
            self._connected = True

    def _dispatch(self, client, userdata, m) -> None:
        with self._lock:
            cbs = list(self._subs.get(m.topic, []))
        for cb in cbs:
            cb(m.topic, m.payload)

    # -- InMemoryBroker interface -------------------------------------------
    def publish(self, topic: str, payload: bytes) -> None:
        self._ensure_connected()
        self._client.publish(topic, payload, qos=2)

    def subscribe(self, topic: str, cb: Callable[[str, bytes], None]) -> None:
        with self._lock:
            self._subs.setdefault(topic, []).append(cb)
        self._ensure_connected()
        self._client.subscribe(topic, qos=2)

    def set_will(self, client_id: str, topic: str, payload: bytes) -> None:
        # must be set before connect (MQTT protocol); reference does the same
        self._client.will_set(topic, payload, qos=2, retain=False)

    def disconnect(self) -> None:
        if self._connected:
            self._client.loop_stop()
            self._client.disconnect()
            self._connected = False


class TcpMqttBroker:
    """The ``InMemoryBroker`` interface over a REAL MQTT 3.1.1 TCP session
    (:class:`~fedml_tpu.comm.mqtt_wire.SocketMqttClient` — stdlib sockets,
    no fakes).  Same lazy-connect + will-before-connect contract as
    :class:`PahoMqttBroker`; reconnect/re-subscribe is handled inside the
    wire client (clean-session replay)."""

    def __init__(self, host: str, port: int, client_id: str,
                 keepalive: float = 30.0):
        from .mqtt_wire import SocketMqttClient

        self._client = SocketMqttClient(host, port, client_id, keepalive=keepalive)
        self._connected = False
        self._lock = threading.Lock()

    def _ensure_connected(self) -> None:  # graftlint: disable=GL007(the lock IS the lazy-connect once-only gate: concurrent publishers must wait out the single dial rather than race two sessions under one client id)
        with self._lock:
            if not self._connected:
                self._client.connect()
                self._connected = True

    # -- InMemoryBroker interface -------------------------------------------
    def publish(self, topic: str, payload: bytes) -> None:
        self._ensure_connected()
        # At-least-once on purpose: the wire client and MiniMqttBroker DO
        # speak full QoS2 (paho at qos=2 interoperates), but with clean
        # sessions QoS2 narrows rather than closes the loss window — a drop
        # between the subscriber's PUBREC and the broker's PUBREL strands a
        # stashed message the QoS1 path would already have delivered.  The
        # FL protocol handlers are redelivery-tolerant by design (round-
        # index gates, once-flags), so duplicates are the safe failure mode.
        self._client.publish(topic, payload, qos=1)

    def subscribe(self, topic: str, cb: Callable[[str, bytes], None]) -> None:
        self._client.subscribe(topic, cb)
        self._ensure_connected()

    def set_will(self, client_id: str, topic: str, payload: bytes) -> None:
        self._client.will_set(topic, payload, qos=1)

    def disconnect(self) -> None:
        with self._lock:
            if self._connected:
                self._client.disconnect()
                self._connected = False


class S3ObjectStore:
    """boto3-backed implementation of the InMemoryObjectStore interface
    (reference ``remote_storage.py`` S3 upload/download of model payloads)."""

    def __init__(self, bucket: str, prefix: str = "fedml_tpu/", client=None):
        if client is None:
            if _boto3 is None:
                raise ImportError(
                    "boto3 is not installed; install it for S3 payloads or "
                    "use comm.mqtt_s3.InMemoryObjectStore for hermetic runs"
                )
            client = _boto3.client("s3")
        self._s3 = client
        self.bucket = bucket
        self.prefix = prefix

    # -- InMemoryObjectStore interface --------------------------------------
    def put(self, key: str, data: bytes) -> str:
        full = self.prefix + key
        self._s3.put_object(Bucket=self.bucket, Key=full, Body=data)
        return key

    def get(self, key: str) -> bytes:
        full = self.prefix + key
        return self._s3.get_object(Bucket=self.bucket, Key=full)["Body"].read()
