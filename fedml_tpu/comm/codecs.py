"""Per-leaf compression codecs for model-update payloads (wire v2).

The compression operators the simulation path already owns
(``ops/compression.py``: QSGD, top-k with error feedback, the fused int8
Pallas kernel) were wired into nothing on the distributed path — cross-silo
clients shipped full-f32 pytrees every round.  This module turns them into
wire codecs: :func:`compress_pytree` maps a pytree of (delta) arrays to a
pytree where large float leaves become :class:`~fedml_tpu.comm.wire.
CompressedLeaf` segments (``qsgd8`` via ``ops/pallas/quantize.py``'s
block-scaled stochastic int8, ``topk`` as sparse indices+values with the
``ef_top_k`` error-feedback residual carried by the caller across rounds),
and small or non-float leaves ride raw — quantizing a 64-element BatchNorm
bias into a padded 1024-element block would *expand* it.

Decompression lives in ``comm.wire`` (numpy-only, so a server can fold
arriving updates without touching jax), keeping the format polyglot.

Payload accounting lands in the process-global registry:
``fedml_comm_payload_bytes_total`` / ``fedml_comm_payload_raw_bytes_total``
(wire vs dense-equivalent bytes, by codec) and the last observed
``fedml_comm_compression_ratio``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..obs import registry as obsreg
from . import wire

PAYLOAD_BYTES = obsreg.REGISTRY.counter(
    "fedml_comm_payload_bytes_total",
    "Model-update payload bytes as encoded on the wire, by codec.",
    labels=("codec",),
)
PAYLOAD_RAW_BYTES = obsreg.REGISTRY.counter(
    "fedml_comm_payload_raw_bytes_total",
    "Dense-equivalent bytes of the same model-update payloads, by codec.",
    labels=("codec",),
)
COMPRESSION_RATIO = obsreg.REGISTRY.gauge(
    "fedml_comm_compression_ratio",
    "Last observed dense/wire payload ratio, by codec.",
    labels=("codec",),
)

#: codecs a payload leaf may carry (``raw`` is the identity)
CODECS = ("raw", "qsgd8", "topk")

#: secure-aggregation upload forms (ISSUE 15): masked field vectors on the
#: minimal ring dtype.  ``secagg_dense`` = fixed-point over the M31 field
#: (u32 wire); ``secagg_qsgd8`` = the quantize-then-mask composition (int8
#: grid in a cohort-sized ring).  Accounted through the same payload
#: counters so bytes/round trajectories cover the trusted path too.
MASKED_CODECS = ("secagg_dense", "secagg_qsgd8")

#: leaves below this element count stay raw: the qsgd8 block padding (1024
#: elements) would expand them, and their bytes are noise at model scale
DEFAULT_MIN_COMPRESS_ELEMS = 1024

#: per-tree floor for LOW-RANK exchanged trees (LoRA adapter factors): the
#: smallest leaf size at which qsgd8 cannot expand.  A leaf of n f32 elements
#: is 4n raw bytes and ceil(n/1024)*(1024 + 4) compressed bytes, so for
#: n <= 1024 compression shrinks iff n > 257 — 260 adds a small margin.
#: Trainers whose whole payload is rank-r factors (``LoRASiloTrainer``)
#: declare this as their ``comm_compress_min_elems`` so adapter leaves ride
#: the compressed wire where the model-scale default would leave them raw.
LOW_RANK_MIN_COMPRESS_ELEMS = 260


def codec_from_config(cfg) -> Optional[str]:
    """``extra.comm_compression`` -> validated codec name, or None when
    compression is off (unset / ``no`` / ``off`` / ``raw``)."""
    from ..core.flags import cfg_extra

    name = str(cfg_extra(cfg, "comm_compression") or "").strip().lower()
    if name in ("", "no", "off", "none", "raw"):
        return None
    if name not in CODECS:
        raise ValueError(f"unknown comm_compression {name!r}; known: {CODECS[1:]}")
    return name


def _compress_vec(codec: str, vec, leaf_key, residual, ratio: float):
    """One flat f32 vector -> (segments, meta, new_residual).  jax-side: the
    qsgd8 path runs the fused Pallas kernel (interpret mode off-TPU)."""
    import jax
    import jax.numpy as jnp

    if codec == "qsgd8":
        from ..ops.pallas import quantize as q

        values, scales, n = q.quantize_int8_stochastic(
            vec, leaf_key, interpret=jax.default_backend() != "tpu"
        )
        segments = (np.asarray(scales, dtype="<f4"),
                    np.asarray(values, np.int8).reshape(-1))
        return segments, {"blocks": int(scales.shape[0]), "length": int(n)}, residual
    if codec == "topk":
        # ef_top_k semantics (ops/compression.py) in sparse wire form: add
        # the carried residual, keep the k largest-|.| entries as explicit
        # (index, value) pairs, keep everything dropped as the next residual
        corrected = vec if residual is None else vec + jnp.asarray(residual, jnp.float32)
        k = max(1, int(ratio * corrected.shape[0]))
        _, idx = jax.lax.top_k(jnp.abs(corrected), k)
        vals = corrected[idx]
        new_residual = np.asarray(corrected.at[idx].set(0.0))
        segments = (np.asarray(idx, dtype="<i4"), np.asarray(vals, dtype="<f4"))
        return segments, {"size": int(corrected.shape[0]), "k": int(k)}, new_residual
    raise ValueError(f"unknown codec {codec!r}")


def compress_pytree(tree, codec: Optional[str], *, key=None, residuals=None,
                    ratio: float = 0.01,
                    min_elems: int = DEFAULT_MIN_COMPRESS_ELEMS):
    """Compress the large float leaves of ``tree`` with ``codec``.

    Returns ``(compressed_tree, new_residuals, stats)``.  ``residuals`` /
    ``new_residuals`` are leaf-aligned lists (jax flatten order) carrying the
    top-k error-feedback state across rounds; qsgd8 is unbiased and carries
    none.  ``stats`` = {"raw_bytes", "wire_bytes", "ratio"}.  ``min_elems``
    is the per-tree floor: callers whose whole tree is low-rank (LoRA
    adapters) pass :data:`LOW_RANK_MIN_COMPRESS_ELEMS` instead of the
    model-scale default.
    """
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if codec is None:
        return tree, residuals, {"raw_bytes": sum(np.asarray(l).nbytes for l in leaves),
                                 "wire_bytes": sum(np.asarray(l).nbytes for l in leaves),
                                 "ratio": 1.0}
    if key is None:
        key = jax.random.PRNGKey(0)
    new_residuals: list = [None] * len(leaves)
    out_leaves: list = []
    raw_bytes = 0
    wire_bytes = 0
    for i, leaf in enumerate(leaves):
        a = np.asarray(leaf)
        raw_bytes += a.nbytes
        if a.dtype.kind != "f" or a.size < min_elems:
            out_leaves.append(a)
            wire_bytes += a.nbytes
            continue
        vec = jnp.asarray(a.reshape(-1), jnp.float32)
        prev = residuals[i] if residuals is not None else None
        segments, meta, new_residuals[i] = _compress_vec(
            codec, vec, jax.random.fold_in(key, i), prev, ratio
        )
        cl = wire.CompressedLeaf(codec, a.dtype.str, a.shape, meta, segments)
        out_leaves.append(cl)
        wire_bytes += cl.nbytes
    PAYLOAD_BYTES.inc(wire_bytes, codec=codec)
    PAYLOAD_RAW_BYTES.inc(raw_bytes, codec=codec)
    ratio_out = raw_bytes / max(wire_bytes, 1)
    COMPRESSION_RATIO.set(ratio_out, codec=codec)
    return (jax.tree_util.tree_unflatten(treedef, out_leaves), new_residuals,
            {"raw_bytes": int(raw_bytes), "wire_bytes": int(wire_bytes),
             "ratio": float(ratio_out)})


def note_masked_payload(codec: str, wire_bytes: int, raw_bytes: int) -> None:
    """Account one secure-aggregation upload (``codec`` from
    :data:`MASKED_CODECS`): ``wire_bytes`` = the packed masked vector as
    shipped, ``raw_bytes`` = the dense f32 equivalent."""
    PAYLOAD_BYTES.inc(int(wire_bytes), codec=codec)
    PAYLOAD_RAW_BYTES.inc(int(raw_bytes), codec=codec)
    COMPRESSION_RATIO.set(raw_bytes / max(wire_bytes, 1), codec=codec)


def payload_counters() -> dict:
    """Snapshot of the payload accounting (for BENCH json / tests)."""
    out = {}
    for codec in CODECS[1:] + MASKED_CODECS:
        wire_b = PAYLOAD_BYTES.value(codec=codec)
        raw_b = PAYLOAD_RAW_BYTES.value(codec=codec)
        if wire_b or raw_b:
            out[codec] = {"wire_bytes": int(wire_b), "raw_bytes": int(raw_b),
                          "ratio": round(raw_b / max(wire_b, 1.0), 3)}
    return out
