"""Deterministic chaos injection at the comm boundary (ISSUE 10).

The only fault model the framework ever exercised was the async soak's
seeded upload drops — injected inside the test harness, invisible to the
transports.  This module makes partial failure a FIRST-CLASS, reproducible
property of any comm backend: a :class:`ChaosCommManager` wraps the real
manager (in-proc, gRPC, TCP, MQTT — anything speaking
:class:`~fedml_tpu.comm.base.BaseCommunicationManager`) and applies a
seeded per-peer fault schedule to every send:

====================  =====================================================
fault                 observable effect
====================  =====================================================
``drop``              the frame silently vanishes (sender sees success)
``delay``             delivered late (uniform in (0, chaos_delay_max_s])
``duplicate``         delivered twice (at-least-once redelivery)
``reorder``           held back, delivered AFTER the next frame to the peer
``corrupt``           ships truncated — must die in the receive loop's
                      undecodable-drop path, never in a handler
``reset``             ``ConnectionResetError`` raised at the sender
``partition``         every send in a timed window fails like ``reset``
====================  =====================================================

**Determinism is the point.**  Each decision draws from
``default_rng([seed, sender_rank, receiver, nonce])`` where ``nonce`` is the
per-receiver send ordinal — so the same seed over the same message sequence
reproduces the same fault schedule exactly (the kill-and-recover soak's
reproducibility assertion), and two endpoints with the same seed still see
independent schedules.  Every injection lands in
``fedml_chaos_injected_total{fault=...}`` and in the wrapper's local
``schedule`` list (the test-facing record).

Gated entirely on the ``extra.chaos_*`` flags: all probabilities zero and no
partition window means :func:`wrap_with_chaos` returns the inner manager
UNTOUCHED — no wrapper object, no per-send rng, wire bytes byte-identical to
the chaos-free build.

Thread model (GL008-audited): ``send_message`` may be called from the
receive loop, watchdog timers, and the caller's thread; the nonce counter
and reorder hold-back slots mutate under ``_lock``, while actual transport
sends run OUTSIDE it (a slow peer must not serialize the other threads'
fault rolls).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

import numpy as np

from ..core.flags import cfg_extra
from ..obs import registry as obsreg
from .base import BaseCommunicationManager
from .message import Message

log = logging.getLogger("fedml_tpu.comm.chaos")

__all__ = ["ChaosConfig", "ChaosCommManager", "chaos_from_config",
           "wrap_with_chaos"]

CHAOS_INJECTED = obsreg.REGISTRY.counter(
    "fedml_chaos_injected_total",
    "Faults injected by the chaos comm wrapper, by fault kind.",
    labels=("fault",),
)
CHAOS_SENDS = obsreg.REGISTRY.counter(
    "fedml_chaos_sends_total",
    "Sends that passed through the chaos wrapper (faulted or clean).",
)

#: faults whose frame reaches no handler — the sender believes it sent, the
#: receiver never dispatches it (corrupt frames die in the receive loop's
#: drop path); these are the losses the redispatch watchdog must recover
SILENT_LOSS_FAULTS = ("drop", "corrupt", "partition_lost")


class ChaosConfig:
    """Parsed ``extra.chaos_*`` flags.  ``from_config`` returns ``None``
    when every probability is zero and no partition window is set — the
    gate that keeps the default path bit-identical."""

    __slots__ = ("seed", "drop", "delay", "delay_max_s", "duplicate",
                 "reorder", "corrupt", "reset", "partition")

    def __init__(self, *, seed: int = 0, drop: float = 0.0, delay: float = 0.0,
                 delay_max_s: float = 0.05, duplicate: float = 0.0,
                 reorder: float = 0.0, corrupt: float = 0.0,
                 reset: float = 0.0,
                 partition: Optional[tuple[float, float]] = None):
        self.seed = int(seed)
        self.drop = float(drop)
        self.delay = float(delay)
        self.delay_max_s = float(delay_max_s)
        self.duplicate = float(duplicate)
        self.reorder = float(reorder)
        self.corrupt = float(corrupt)
        self.reset = float(reset)
        self.partition = partition  # (start_s, duration_s) after manager start

    @classmethod
    def from_config(cls, cfg: Any) -> Optional["ChaosConfig"]:
        if cfg is None:
            return None
        part_spec = cfg_extra(cfg, "chaos_partition")
        partition = None
        if part_spec:
            try:
                start_s, dur_s = (float(x) for x in str(part_spec).split(":"))
                partition = (start_s, dur_s)
            except ValueError:
                raise ValueError(
                    f"chaos_partition must be 'start_s:duration_s', got "
                    f"{part_spec!r}") from None
        obj = cls(
            seed=int(cfg_extra(cfg, "chaos_seed")),
            drop=float(cfg_extra(cfg, "chaos_drop_prob")),
            delay=float(cfg_extra(cfg, "chaos_delay_prob")),
            delay_max_s=float(cfg_extra(cfg, "chaos_delay_max_s")),
            duplicate=float(cfg_extra(cfg, "chaos_duplicate_prob")),
            reorder=float(cfg_extra(cfg, "chaos_reorder_prob")),
            corrupt=float(cfg_extra(cfg, "chaos_corrupt_prob")),
            reset=float(cfg_extra(cfg, "chaos_reset_prob")),
            partition=partition,
        )
        if not obj.active():
            return None
        return obj

    def active(self) -> bool:
        return bool(self.partition) or any(
            p > 0.0 for p in (self.drop, self.delay, self.duplicate,
                              self.reorder, self.corrupt, self.reset))


class ChaosCommManager(BaseCommunicationManager):
    """Seeded fault-injecting decorator over any comm backend (module doc).

    Unknown attributes delegate to the inner manager, so transport-specific
    surface (``configure_chunk_sweep``, ``router``, ports) keeps working
    through the wrapper.
    """

    def __init__(self, inner: BaseCommunicationManager, chaos: ChaosConfig,
                 rank: int):
        self.inner = inner
        self.chaos = chaos
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._nonce: dict[int, int] = {}
        self._held: dict[int, Message] = {}
        self._t0 = time.monotonic()
        #: deterministic injection record: (fault, receiver, nonce) — the
        #: reproducibility tests and the soak's accounting identity read it
        self.schedule: list[tuple[str, int, int]] = []
        self.injected: dict[str, int] = {}

    # -- bookkeeping ----------------------------------------------------------
    def _note(self, fault: str, rid: int, nonce: int) -> None:
        CHAOS_INJECTED.inc(fault=fault)
        with self._lock:
            self.schedule.append((fault, rid, nonce))
            self.injected[fault] = self.injected.get(fault, 0) + 1

    def silent_losses(self) -> int:
        """Frames no handler will ever see (drop/corrupt/partition-lost) —
        the quantity the recovery accounting identity charges against
        redispatches + rejected-stale + tracked in-flight."""
        with self._lock:
            return sum(self.injected.get(f, 0) for f in SILENT_LOSS_FAULTS)

    def _in_partition(self) -> bool:
        if not self.chaos.partition:
            return False
        start_s, dur_s = self.chaos.partition
        dt = time.monotonic() - self._t0
        return start_s <= dt < start_s + dur_s

    # -- the fault schedule ---------------------------------------------------
    def send_message(self, msg: Message) -> None:
        rid = int(msg.get_receiver_id())
        with self._lock:
            self._nonce[rid] = nonce = self._nonce.get(rid, 0) + 1
            held = self._held.pop(rid, None)
        CHAOS_SENDS.inc()
        rng = np.random.default_rng(
            [self.chaos.seed, self.rank, rid, nonce])
        # one roll per fault class, drawn in a FIXED order so the schedule
        # is a pure function of (seed, sender, receiver, nonce)
        rolls = rng.random(6)
        try:
            if self._in_partition():
                # the network is down: the held frame (already "accepted"
                # from its caller's perspective) is lost silently; the
                # current send fails loudly like a reset would
                if held is not None:
                    self._note("partition_lost", rid, nonce)
                self._note("partition", rid, nonce)
                raise ConnectionResetError(
                    f"chaos: partition window active (peer {rid})")
            if rolls[0] < self.chaos.reset:
                self._note("reset", rid, nonce)
                raise ConnectionResetError(f"chaos: connection reset (peer {rid})")
            if rolls[1] < self.chaos.drop:
                self._note("drop", rid, nonce)
                return
            if rolls[2] < self.chaos.corrupt:
                self._note("corrupt", rid, nonce)
                self._send_corrupt(msg, rid, rng)
                return
            if rolls[3] < self.chaos.duplicate:
                self._note("duplicate", rid, nonce)
                self.inner.send_message(msg)
                self.inner.send_message(msg)
                return
            if rolls[4] < self.chaos.reorder:
                self._note("reorder", rid, nonce)
                with self._lock:
                    prev = self._held.get(rid)
                    if prev is None:
                        self._held[rid] = msg
                        return
                # a hold-back slot is already occupied: deliver normally
                self.inner.send_message(msg)
                return
            if rolls[5] < self.chaos.delay:
                self._note("delay", rid, nonce)
                delay_s = float(rng.random()) * self.chaos.delay_max_s
                t = threading.Timer(delay_s, self._send_late, args=(msg,))
                t.daemon = True
                t.start()
                return
            self.inner.send_message(msg)
        finally:
            # the held-back frame goes out AFTER the current one — that IS
            # the reorder — unless the partition already claimed it
            if held is not None and not self._in_partition():
                try:
                    self.inner.send_message(held)
                except Exception:
                    log.warning("chaos: flushing held frame to %d failed", rid,
                                exc_info=True)

    def _send_corrupt(self, msg: Message, rid: int, rng) -> None:
        """Ship a TRUNCATED encoding of the frame so the receiver's decode
        dies deterministically in the receive loop's drop path (header/
        length validation) — never inside a handler.  Backends without a
        raw-bytes send degrade to a drop (same observable: no dispatch)."""
        send_raw = getattr(self.inner, "send_raw", None)
        if send_raw is None:
            return
        data = msg.encode()
        cut = max(1, int(len(data) * (0.25 + 0.5 * float(rng.random()))))
        try:
            send_raw(rid, bytes(data[:cut]))
        except Exception:
            log.warning("chaos: corrupt-frame send to %d failed", rid,
                        exc_info=True)

    def _send_late(self, msg: Message) -> None:
        try:
            self.inner.send_message(msg)
        except Exception:
            log.warning("chaos: delayed send failed", exc_info=True)

    # -- passthrough ----------------------------------------------------------
    def add_observer(self, observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer) -> None:
        self.inner.remove_observer(observer)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        # best-effort flush of reorder hold-backs so a clean shutdown does
        # not strand the last frame of a stream
        with self._lock:
            held = list(self._held.items())
            self._held.clear()
        for _rid, msg in held:
            try:
                self.inner.send_message(msg)
            except Exception:
                pass
        self.inner.stop_receive_message()

    def __getattr__(self, name: str):
        return getattr(self.inner, name)


def chaos_from_config(cfg: Any) -> Optional[ChaosConfig]:
    return ChaosConfig.from_config(cfg)


def wrap_with_chaos(inner: BaseCommunicationManager, cfg: Any,
                    rank: int) -> BaseCommunicationManager:
    """The one gate: no ``chaos_*`` flag set → ``inner`` returned untouched
    (no wrapper, byte-identical traffic); any fault enabled → the seeded
    wrapper."""
    chaos = chaos_from_config(cfg)
    if chaos is None:
        return inner
    log.info("chaos: wrapping %s (rank %d, seed %d)",
             type(inner).__name__, rank, chaos.seed)
    return ChaosCommManager(inner, chaos, rank)
