"""Message — the unit of cross-process FL communication.

Parity with the reference ``Message`` (``core/distributed/communication/
message.py:5``): a typed dict with MSG_ARG_KEY_TYPE/SENDER/RECEIVER plus
arbitrary params.  Tensor payloads ride the pytree wire format
(``comm.wire``) instead of pickle, so the bytes are language-neutral.
"""

from __future__ import annotations

import json
from typing import Any

from . import wire

MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"

#: optional distributed-tracing header ({"trace_id", "span_id"}) — rides the
#: JSON control section so every transport propagates it unchanged
MSG_ARG_KEY_TRACE = "trace"

# payload keys matching the reference vocabulary
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_CLIENT_STATUS = "client_status"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"


class Message:
    def __init__(self, msg_type: int = 0, sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender_id,
            MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # reference API shape
    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    add = add_params

    def get(self, key: str, default=None) -> Any:
        return self.msg_params.get(key, default)

    def get_type(self) -> int:
        return self.msg_params[MSG_ARG_KEY_TYPE]

    def get_sender_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_RECEIVER]

    # -- tracing header ------------------------------------------------------
    def set_trace(self, header: dict) -> None:
        """Attach a trace-propagation header (see ``obs.trace.inject``)."""
        self.msg_params[MSG_ARG_KEY_TRACE] = dict(header)

    def get_trace(self):
        return self.msg_params.get(MSG_ARG_KEY_TRACE)

    # -- wire ---------------------------------------------------------------
    def encode(self) -> bytes:
        """Control fields as JSON; array-valued params via the pytree wire."""
        control = {}
        tensors = {}
        for k, v in self.msg_params.items():
            if _is_arraylike(v):
                tensors[k] = v
            else:
                control[k] = v
        blob = wire.encode_pytree(tensors)
        cbytes = json.dumps(control, separators=(",", ":")).encode("utf-8")
        return len(cbytes).to_bytes(4, "little") + cbytes + blob

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        clen = int.from_bytes(data[:4], "little")
        control = json.loads(data[4 : 4 + clen].decode("utf-8"))
        tensors = wire.decode_pytree(data[4 + clen :])
        msg = cls()
        msg.msg_params = {**control, **tensors}
        return msg

    def __repr__(self) -> str:
        keys = [k for k in self.msg_params if k not in (MSG_ARG_KEY_TYPE, MSG_ARG_KEY_SENDER, MSG_ARG_KEY_RECEIVER)]
        return (
            f"Message(type={self.get_type()}, {self.get_sender_id()}->"
            f"{self.get_receiver_id()}, params={keys})"
        )


def _is_arraylike(v) -> bool:
    import numpy as np

    if isinstance(v, np.ndarray):
        return True
    # jax arrays / pytrees of arrays
    if isinstance(v, dict):
        return bool(v) and all(_is_arraylike(x) for x in v.values())
    if isinstance(v, (list, tuple)):
        return bool(v) and all(_is_arraylike(x) for x in v)
    return hasattr(v, "__array_interface__") or type(v).__module__.startswith("jax")
