"""Message — the unit of cross-process FL communication.

Parity with the reference ``Message`` (``core/distributed/communication/
message.py:5``): a typed dict with MSG_ARG_KEY_TYPE/SENDER/RECEIVER plus
arbitrary params.  Tensor payloads ride the pytree wire format
(``comm.wire``) instead of pickle, so the bytes are language-neutral.
"""

from __future__ import annotations

import json
from typing import Any

from . import wire

MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"

#: optional distributed-tracing header ({"trace_id", "span_id"}) — rides the
#: JSON control section so every transport propagates it unchanged
MSG_ARG_KEY_TRACE = "trace"

# payload keys matching the reference vocabulary
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_CLIENT_STATUS = "client_status"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"


class Message:
    def __init__(self, msg_type: int = 0, sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender_id,
            MSG_ARG_KEY_RECEIVER: receiver_id,
        }
        # undecoded tensor section of a received frame: (header, offset, blob)
        # until first tensor access (lazy decode keeps the receive loop off
        # the dequantize path and lets a streaming consumer fold leaf-by-leaf)
        self._tensor_stream = None
        #: wire size of the frame this message was decoded from (0 if local)
        self.wire_nbytes: int = 0

    # reference API shape
    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    add = add_params

    def get(self, key: str, default=None) -> Any:
        if key not in self.msg_params and self._tensor_stream is not None:
            self._materialize_tensors()
        return self.msg_params.get(key, default)

    def all_params(self) -> dict:
        """The full params dict; forces tensor materialization on a received
        message (use :meth:`get` for single keys — control keys stay lazy)."""
        if self._tensor_stream is not None:
            self._materialize_tensors()
        return self.msg_params

    def get_type(self) -> int:
        return self.msg_params[MSG_ARG_KEY_TYPE]

    def get_sender_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_RECEIVER]

    # -- tracing header ------------------------------------------------------
    def set_trace(self, header: dict) -> None:
        """Attach a trace-propagation header (see ``obs.trace.inject``)."""
        self.msg_params[MSG_ARG_KEY_TRACE] = dict(header)

    def get_trace(self):
        return self.msg_params.get(MSG_ARG_KEY_TRACE)

    # -- wire ---------------------------------------------------------------
    def encode(self) -> bytes:
        """Control fields as JSON; array-valued params via the pytree wire.
        Single output allocation: the tensor chunks are zero-copy views
        joined once, never duplicated through an intermediate blob."""
        control = {}
        tensors = {}
        for k, v in self.msg_params.items():
            if _is_arraylike(v):
                tensors[k] = v
            else:
                control[k] = v
        cbytes = json.dumps(control, separators=(",", ":")).encode("utf-8")
        parts = [len(cbytes).to_bytes(4, "little"), cbytes]
        parts.extend(wire.encode_pytree_chunks(tensors))
        return b"".join(parts)

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        clen = int.from_bytes(data[:4], "little")
        control = json.loads(bytes(data[4 : 4 + clen]).decode("utf-8"))
        msg = cls()
        msg.msg_params = dict(control)
        # the tensor header is parsed + length-validated NOW (framing
        # corruption must fail in the receive loop's drop path), but leaf
        # decode is deferred to first access / the streaming consumer
        blob = memoryview(data)[4 + clen :]
        header, offset = wire.decode_header(blob)
        msg._tensor_stream = (header, offset, blob)
        msg.wire_nbytes = len(data)
        return msg

    def tensor_stream(self):
        """``(wire_header, payload_offset, blob)`` while the tensor section
        is still undecoded (for chunk-by-chunk streaming consumers), else
        None.  Control params (JSON section) never trigger materialization."""
        return self._tensor_stream

    def _materialize_tensors(self) -> None:
        header, offset, blob = self._tensor_stream
        self._tensor_stream = None
        tensors = wire.decode_pytree(blob, header=header, offset=offset)
        if isinstance(tensors, dict):
            self.msg_params.update(tensors)

    def __repr__(self) -> str:
        if self._tensor_stream is not None:
            self._materialize_tensors()
        keys = [k for k in self.msg_params if k not in (MSG_ARG_KEY_TYPE, MSG_ARG_KEY_SENDER, MSG_ARG_KEY_RECEIVER)]
        return (
            f"Message(type={self.get_type()}, {self.get_sender_id()}->"
            f"{self.get_receiver_id()}, params={keys})"
        )


def _is_arraylike(v) -> bool:
    import numpy as np

    if isinstance(v, (np.ndarray, wire.CompressedLeaf)):
        return True
    # jax arrays / pytrees of arrays
    if isinstance(v, dict):
        return bool(v) and all(_is_arraylike(x) for x in v.values())
    if isinstance(v, (list, tuple)):
        return bool(v) and all(_is_arraylike(x) for x in v)
    return hasattr(v, "__array_interface__") or type(v).__module__.startswith("jax")
