"""Message — the unit of cross-process FL communication.

Parity with the reference ``Message`` (``core/distributed/communication/
message.py:5``): a typed dict with MSG_ARG_KEY_TYPE/SENDER/RECEIVER plus
arbitrary params.  Tensor payloads ride the pytree wire format
(``comm.wire``) instead of pickle, so the bytes are language-neutral.
"""

from __future__ import annotations

import json
import time
from typing import Any, Optional

from . import wire

MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"

#: optional distributed-tracing header ({"trace_id", "span_id"}) — rides the
#: JSON control section so every transport propagates it unchanged
MSG_ARG_KEY_TRACE = "trace"

# payload keys matching the reference vocabulary
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_CLIENT_STATUS = "client_status"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"


class Message:
    def __init__(self, msg_type: int = 0, sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender_id,
            MSG_ARG_KEY_RECEIVER: receiver_id,
        }
        # undecoded tensor section of a received frame: (header, offset, blob)
        # until first tensor access (lazy decode keeps the receive loop off
        # the dequantize path and lets a streaming consumer fold leaf-by-leaf)
        self._tensor_stream = None
        # tensor section already decoded leaf-by-leaf during chunked arrival:
        # (wire_header, [leaf arrays in wire order]) — the chunk assembler's
        # output form; restored into msg_params on first tensor access
        self._tensor_leaves = None
        #: wire size of the frame this message was decoded from (0 if local)
        self.wire_nbytes: int = 0
        #: time.monotonic() of the first received byte (chunked) or of the
        #: receive-loop dequeue (whole frame); None for locally built messages
        self.recv_monotonic: Optional[float] = None

    # reference API shape
    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    add = add_params

    def get(self, key: str, default=None) -> Any:
        if key not in self.msg_params and self._has_lazy_tensors():
            self._materialize_tensors()
        return self.msg_params.get(key, default)

    def get_control(self, key: str, default=None) -> Any:
        """``get`` restricted to the JSON control section: NEVER triggers
        tensor materialization, so a streaming consumer can read optional
        control keys (delta flag, version) that may be absent without
        collapsing the lazy frame it is about to fold."""
        return self.msg_params.get(key, default)

    def all_params(self) -> dict:
        """The full params dict; forces tensor materialization on a received
        message (use :meth:`get` for single keys — control keys stay lazy)."""
        if self._has_lazy_tensors():
            self._materialize_tensors()
        return self.msg_params

    def get_type(self) -> int:
        return self.msg_params[MSG_ARG_KEY_TYPE]

    def get_sender_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_RECEIVER]

    # -- tracing header ------------------------------------------------------
    def set_trace(self, header: dict) -> None:
        """Attach a trace-propagation header (see ``obs.trace.inject``)."""
        self.msg_params[MSG_ARG_KEY_TRACE] = dict(header)

    def get_trace(self):
        return self.msg_params.get(MSG_ARG_KEY_TRACE)

    # -- wire ---------------------------------------------------------------
    def encode(self) -> bytes:
        """Control fields as JSON; array-valued params via the pytree wire.
        Single output allocation: the tensor chunks are zero-copy views
        joined once, never duplicated through an intermediate blob."""
        control = {}
        tensors = {}
        for k, v in self.msg_params.items():
            if _is_arraylike(v):
                tensors[k] = v
            else:
                control[k] = v
        cbytes = json.dumps(control, separators=(",", ":")).encode("utf-8")
        parts = [len(cbytes).to_bytes(4, "little"), cbytes]
        parts.extend(wire.encode_pytree_chunks(tensors))
        return b"".join(parts)

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        clen = int.from_bytes(data[:4], "little")
        control = json.loads(bytes(data[4 : 4 + clen]).decode("utf-8"))
        msg = cls()
        msg.msg_params = dict(control)
        # the tensor header is parsed + length-validated NOW (framing
        # corruption must fail in the receive loop's drop path), but leaf
        # decode is deferred to first access / the streaming consumer
        blob = memoryview(data)[4 + clen :]
        header, offset = wire.decode_header(blob)
        msg._tensor_stream = (header, offset, blob)
        msg.wire_nbytes = len(data)
        return msg

    @classmethod
    def from_stream(cls, control: dict, header: dict, leaves: list,
                    wire_nbytes: int = 0) -> "Message":
        """A message whose tensor section was already decoded incrementally
        (chunked arrival): control params + per-leaf arrays in wire order.
        Restoration into the params dict stays lazy, exactly like
        :meth:`decode`, and :meth:`tensor_frame` serves streaming folds."""
        msg = cls()
        msg.msg_params = dict(control)
        msg._tensor_leaves = (header, list(leaves))
        msg.wire_nbytes = int(wire_nbytes)
        return msg

    def tensor_stream(self):
        """``(wire_header, payload_offset, blob)`` while the tensor section
        is still undecoded (for chunk-by-chunk streaming consumers), else
        None.  Control params (JSON section) never trigger materialization."""
        return self._tensor_stream

    def tensor_frame(self):
        """``(wire_header, iterator of (index, spec, array))`` over the
        still-unmaterialized tensor section — the one streaming-fold surface
        covering both received forms (lazy blob and chunk-decoded leaves);
        None once the tensors have been restored into the params dict."""
        if self._tensor_stream is not None:
            header, offset, blob = self._tensor_stream
            return header, wire.iter_leaf_arrays(blob, header=header, offset=offset)
        if self._tensor_leaves is not None:
            header, leaves = self._tensor_leaves
            specs = header["leaves"]
            return header, ((i, specs[i], leaf) for i, leaf in enumerate(leaves))
        return None

    def _has_lazy_tensors(self) -> bool:
        return self._tensor_stream is not None or self._tensor_leaves is not None

    def _materialize_tensors(self) -> None:
        if self._tensor_stream is not None:
            header, offset, blob = self._tensor_stream
            self._tensor_stream = None
            tensors = wire.decode_pytree(blob, header=header, offset=offset)
        else:
            header, leaves = self._tensor_leaves
            self._tensor_leaves = None
            tensors = wire.restore_skeleton(header["treedef"], leaves)
        if isinstance(tensors, dict):
            self.msg_params.update(tensors)

    def __repr__(self) -> str:
        if self._has_lazy_tensors():
            self._materialize_tensors()
        keys = [k for k in self.msg_params if k not in (MSG_ARG_KEY_TYPE, MSG_ARG_KEY_SENDER, MSG_ARG_KEY_RECEIVER)]
        return (
            f"Message(type={self.get_type()}, {self.get_sender_id()}->"
            f"{self.get_receiver_id()}, params={keys})"
        )


class MessageStreamDecoder:
    """Incremental ``Message`` decoder for chunked arrival: feed bounded
    byte chunks of one encoded message as they land; the control JSON is
    parsed as soon as its bytes are in, then tensor leaves decode through a
    :class:`~fedml_tpu.comm.wire.PytreeStreamDecoder` (consumed chunk bytes
    released, so peak buffered memory is ~(largest leaf + chunk)).  Returns
    the completed :class:`Message` from the final ``feed``."""

    def __init__(self):
        self._buf: Optional[bytearray] = bytearray()
        self._control: Optional[dict] = None
        self._decoder = wire.PytreeStreamDecoder(retain_leaves=True)
        self._nbytes = 0

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def feed(self, chunk) -> Optional["Message"]:
        data = bytes(chunk) if isinstance(chunk, memoryview) else chunk
        self._nbytes += len(data)
        if self._control is None:
            self._buf += data
            if len(self._buf) < 4:
                return None
            clen = int.from_bytes(self._buf[:4], "little")
            if len(self._buf) < 4 + clen:
                return None
            self._control = json.loads(bytes(self._buf[4: 4 + clen]).decode("utf-8"))
            rest = bytes(self._buf[4 + clen:])
            self._buf = None  # released: the wire decoder owns buffering now
            if rest:
                self._decoder.feed(rest)
        else:
            self._decoder.feed(data)
        if not self._decoder.complete:
            return None
        return Message.from_stream(
            self._control, self._decoder.header, self._decoder.leaves(),
            wire_nbytes=self._nbytes,
        )


class ChunkAssembler:
    """Per-peer reassembly of transport chunk frames into ``Message``s.

    Streams are keyed ``(sender, stream_id)`` so chunks from N concurrent
    uploads interleave freely; within a stream, out-of-order chunks wait in
    a small reorder buffer and in-order chunks feed the stream's
    :class:`MessageStreamDecoder` immediately — tensor leaves decode while
    the rest of the upload is still in flight.  Streams idle longer than
    ``stream_timeout_s`` are evicted (``sweep``) so a sender that dies
    mid-upload cannot leak buffered chunks forever.

    Thread model (GL008-audited): one assembler belongs to ONE receive
    loop — ``feed`` and ``sweep`` are both called only from that thread
    (``ObserverLoopMixin.handle_receive_message``), so ``_streams`` needs
    no lock.  Sharing an assembler across loops would need one."""

    def __init__(self, stream_timeout_s: float = 120.0):
        self.stream_timeout_s = float(stream_timeout_s)
        self._streams: dict[tuple, dict] = {}

    def pending_streams(self) -> int:
        return len(self._streams)

    def feed(self, data) -> tuple:
        """One chunk frame in; ``(message_or_None, error_reason_or_None,
        sender_or_None)`` out.  A completed stream returns its Message with
        ``recv_monotonic`` stamped at the stream's FIRST chunk (so fold-lag
        measures first-byte-to-folded, the head-of-line quantity)."""
        try:
            sub, payload = wire.parse_chunk_frame(data)
        except (ValueError, KeyError, TypeError):
            return None, "chunk_corrupt", None
        sender = int(sub["sender"])
        key = (sender, str(sub["stream"]))
        now = time.monotonic()
        st = self._streams.get(key)
        if st is None:
            st = self._streams[key] = {
                "dec": MessageStreamDecoder(), "next": 0, "pending": {},
                "last": now, "first": now,
            }
        st["last"] = now
        st["pending"][int(sub["seq"])] = bytes(payload)
        try:
            while st["next"] in st["pending"]:
                msg = st["dec"].feed(st["pending"].pop(st["next"]))
                st["next"] += 1
                if msg is not None:
                    del self._streams[key]
                    msg.recv_monotonic = st["first"]
                    return msg, None, sender
        except (ValueError, KeyError):
            # corrupt mid-stream: drop the whole stream, attribute the loss
            del self._streams[key]
            return None, "chunk_decode", sender
        if st["next"] >= int(sub["chunks"]) and not st["pending"]:
            # every declared chunk consumed yet the message never completed:
            # bytes went missing in flight — fail NOW, not at the idle sweep
            del self._streams[key]
            return None, "chunk_incomplete", sender
        return None, None, sender

    def sweep(self) -> list:
        """Evict streams idle past the timeout; returns ``[(sender,
        stream_id), ...]`` so the receive loop can meter the drops."""
        now = time.monotonic()
        evicted = []
        for key, st in list(self._streams.items()):
            if now - st["last"] > self.stream_timeout_s:
                del self._streams[key]
                evicted.append(key)
        return evicted


def _is_arraylike(v) -> bool:
    import numpy as np

    if isinstance(v, (np.ndarray, wire.CompressedLeaf)):
        return True
    # jax arrays / pytrees of arrays
    if isinstance(v, dict):
        return bool(v) and all(_is_arraylike(x) for x in v.values())
    if isinstance(v, (list, tuple)):
        return bool(v) and all(_is_arraylike(x) for x in v)
    return hasattr(v, "__array_interface__") or type(v).__module__.startswith("jax")
