"""Minimal MQTT 3.1.1 broker and client on stdlib sockets.

The reference proves its MQTT backend against a real broker in CI
(``tests/cross-silo/run_cross_silo.sh:1-27`` connects
``mqtt_s3_multi_clients_comm_manager.py:20`` to a public broker).  This build
has zero egress and no paho-mqtt wheel, so the same proof is made in-repo:

- :class:`MiniMqttBroker` — a real MQTT 3.1.1 broker over TCP: CONNECT (with
  last-will + session takeover), SUBSCRIBE/UNSUBSCRIBE with ``+``/``#``
  wildcards, PUBLISH QoS 0/1/2 (PUBACK; full PUBREC/PUBREL/PUBCOMP
  exactly-once on both legs — the reference publishes everything at QoS2),
  PINGREQ/PINGRESP, graceful vs abrupt disconnect semantics (the will fires
  only on abrupt loss).
- :class:`SocketMqttClient` — a real client with automatic reconnect and
  re-subscribe, keepalive pings, QoS-1/2 publishes acknowledged end-to-end.

Every byte crosses a real socket in real MQTT framing, so the serialization,
reconnect, and resubscribe behavior the round-3 verdict flagged as unproven
is exercised for real (``comm/mqtt_real.py``'s paho adapter keeps the same
interface for deployments where paho IS installed).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("fedml_tpu.mqtt")

CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
PUBREC, PUBREL, PUBCOMP = 5, 6, 7
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------
def _enc_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        d = n % 128
        n //= 128
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _enc_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _enc_varint(len(body)) + body


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> tuple[int, int, bytes]:
    head = _read_exact(sock, 1)[0]
    ptype, flags = head >> 4, head & 0x0F
    length, mult = 0, 1
    for _ in range(4):
        d = _read_exact(sock, 1)[0]
        length += (d & 0x7F) * mult
        if not d & 0x80:
            break
        mult *= 128
    else:
        raise ValueError("malformed remaining length")
    body = _read_exact(sock, length) if length else b""
    return ptype, flags, body


def _take_str(body: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from(">H", body, off)
    off += 2
    return body[off:off + n].decode(), off + n


def topic_matches(filt: str, topic: str) -> bool:
    """MQTT 3.1.1 topic-filter matching (``+`` one level, ``#`` tail)."""
    fp, tp = filt.split("/"), topic.split("/")
    for i, f in enumerate(fp):
        if f == "#":
            return True
        if i >= len(tp):
            return False
        if f != "+" and f != tp[i]:
            return False
    return len(fp) == len(tp)


# ---------------------------------------------------------------------------
# broker
# ---------------------------------------------------------------------------
class _BrokerSession:
    def __init__(self, broker: "MiniMqttBroker", sock: socket.socket):
        self.broker = broker
        self.sock = sock
        self.client_id = ""
        self.subs: list[tuple[str, int]] = []
        self.will: Optional[tuple[str, bytes, int]] = None
        self.alive = True
        self._wlock = threading.Lock()
        self._next_pid = 1
        # QoS2 exactly-once state: inbound PUBLISHes stashed until PUBREL
        # (pid -> (topic, payload))
        self._qos2_in: dict[int, tuple[str, bytes]] = {}

    def send(self, data: bytes) -> None:  # graftlint: disable=GL007(_wlock exists precisely to serialize whole MQTT frames onto one socket; holding it across sendall IS the framing invariant)
        with self._wlock:
            self.sock.sendall(data)

    def close(self, fire_will: bool) -> None:
        if not self.alive:
            return
        self.alive = False
        will = self.will if fire_will else None
        self.will = None
        try:
            # shutdown BEFORE close: close() alone doesn't send FIN while the
            # session's reader thread is still blocked in recv() on the same
            # socket (the open file description stays referenced), so the
            # peer would never observe the loss
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.broker._drop(self)
        if will:
            topic, payload, qos = will
            self.broker._route(topic, payload, qos)

    # -- packet loop --------------------------------------------------------
    def run(self) -> None:
        try:
            ptype, _flags, body = _read_packet(self.sock)
            if ptype != CONNECT:
                raise ValueError("first packet must be CONNECT")
            self._handle_connect(body)
            while self.alive:
                ptype, flags, body = _read_packet(self.sock)
                if ptype == PUBLISH:
                    self._handle_publish(flags, body)
                elif ptype == PUBACK:
                    pass  # at-least-once: no broker-side redelivery queue
                elif ptype == PUBREL:
                    self._handle_pubrel(body)
                elif ptype == PUBREC:
                    (pid,) = struct.unpack_from(">H", body, 0)
                    self.send(_packet(PUBREL, 0x02, struct.pack(">H", pid)))
                elif ptype == PUBCOMP:
                    pass  # outbound QoS2 handshake complete
                elif ptype == SUBSCRIBE:
                    self._handle_subscribe(body)
                elif ptype == UNSUBSCRIBE:
                    self._handle_unsubscribe(body)
                elif ptype == PINGREQ:
                    self.send(_packet(PINGRESP, 0, b""))
                elif ptype == DISCONNECT:
                    self.close(fire_will=False)  # graceful: discard the will
                    return
                else:
                    raise ValueError(f"unsupported packet type {ptype}")
        except (ConnectionError, OSError, ValueError):
            self.close(fire_will=True)  # abrupt: the will fires

    def _handle_connect(self, body: bytes) -> None:
        proto, off = _take_str(body, 0)
        level = body[off]
        flags = body[off + 1]
        off += 4  # level + connect flags + keepalive(2)
        if proto != "MQTT" or level != 4:
            raise ValueError(f"unsupported protocol {proto!r} level {level}")
        self.client_id, off = _take_str(body, off)
        if flags & 0x04:  # will flag
            wt, off = _take_str(body, off)
            (n,) = struct.unpack_from(">H", body, off)
            off += 2
            wp = body[off:off + n]
            off += n
            self.will = (wt, wp, (flags >> 3) & 0x03)
        self.broker._register(self)
        self.send(_packet(CONNACK, 0, b"\x00\x00"))

    def _handle_publish(self, flags: int, body: bytes) -> None:
        qos = (flags >> 1) & 0x03
        topic, off = _take_str(body, 0)
        if qos == 2:
            # exactly-once inbound: stash until PUBREL; a redelivered
            # PUBLISH with the same pid just refreshes the stash (no double
            # route), and PUBREC is re-sent idempotently
            (pid,) = struct.unpack_from(">H", body, off)
            off += 2
            self._qos2_in[pid] = (topic, body[off:])
            self.send(_packet(PUBREC, 0, struct.pack(">H", pid)))
            return
        if qos == 1:
            (pid,) = struct.unpack_from(">H", body, off)
            off += 2
            self.send(_packet(PUBACK, 0, struct.pack(">H", pid)))
        self.broker._route(topic, body[off:], qos)

    def _handle_pubrel(self, body: bytes) -> None:
        (pid,) = struct.unpack_from(">H", body, 0)
        stashed = self._qos2_in.pop(pid, None)
        if stashed is not None:  # duplicate PUBREL after release: no re-route
            self.broker._route(stashed[0], stashed[1], 2)
        self.send(_packet(PUBCOMP, 0, struct.pack(">H", pid)))

    def _handle_subscribe(self, body: bytes) -> None:
        (pid,) = struct.unpack_from(">H", body, 0)
        off = 2
        granted = bytearray()
        while off < len(body):
            filt, off = _take_str(body, off)
            qos = min(body[off] & 0x03, 2)
            off += 1
            with self.broker._lock:
                self.subs = [s for s in self.subs if s[0] != filt] + [(filt, qos)]
            granted.append(qos)
        self.send(_packet(SUBACK, 0, struct.pack(">H", pid) + bytes(granted)))

    def _handle_unsubscribe(self, body: bytes) -> None:
        (pid,) = struct.unpack_from(">H", body, 0)
        off = 2
        while off < len(body):
            filt, off = _take_str(body, off)
            with self.broker._lock:
                self.subs = [s for s in self.subs if s[0] != filt]
        self.send(_packet(UNSUBACK, 0, struct.pack(">H", pid)))

    def deliver(self, topic: str, payload: bytes, qos: int) -> None:
        flags = qos << 1
        body = _enc_str(topic)
        if qos:
            with self._wlock:
                pid = self._next_pid
                self._next_pid = pid % 65535 + 1
            body += struct.pack(">H", pid)
        try:
            self.send(_packet(PUBLISH, flags, body + payload))
        except OSError:
            self.close(fire_will=True)


class MiniMqttBroker:
    """In-repo MQTT 3.1.1 broker (see module docstring).  ``start()`` returns
    the bound port (0 -> ephemeral); one daemon thread per connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._srv: Optional[socket.socket] = None
        self._sessions: list[_BrokerSession] = []
        self._lock = threading.Lock()
        self._accepting = False

    def start(self) -> int:  # graftlint: disable=GL008(_srv/_accepting are written before the accept thread exists (Thread.start is the publish barrier); stop() only flips the latch and close()s the socket to wake accept — never rebinds)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, self.port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._accepting = True
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.port

    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sess = _BrokerSession(self, sock)
            threading.Thread(target=sess.run, daemon=True).start()

    def stop(self) -> None:
        self._accepting = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            sessions = list(self._sessions)
        for s in sessions:
            s.close(fire_will=False)

    # -- session management --------------------------------------------------
    def _register(self, sess: _BrokerSession) -> None:
        with self._lock:
            old = [s for s in self._sessions if s.client_id == sess.client_id]
            self._sessions.append(sess)
        for s in old:  # MQTT-3.1.4-2 session takeover: old connection closes
            s.close(fire_will=True)

    def _drop(self, sess: _BrokerSession) -> None:
        with self._lock:
            if sess in self._sessions:
                self._sessions.remove(sess)

    def _route(self, topic: str, payload: bytes, qos: int) -> None:
        with self._lock:
            targets = []
            for s in self._sessions:
                for filt, sub_qos in s.subs:
                    if topic_matches(filt, topic):
                        targets.append((s, min(qos, sub_qos)))
                        break  # one delivery per session
        for s, q in targets:
            s.deliver(topic, payload, q)

    def kick(self, client_id: str) -> None:
        """Force-close a client's socket WITHOUT a DISCONNECT — the test
        lever for abrupt-loss behavior (will fires, client must reconnect)."""
        with self._lock:
            victims = [s for s in self._sessions if s.client_id == client_id]
        for s in victims:
            s.close(fire_will=True)

    def session_count(self) -> int:
        with self._lock:
            return len(self._sessions)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class SocketMqttClient:
    """MQTT 3.1.1 client with auto-reconnect + re-subscribe.

    Mirrors the paho surface the backend adapter needs: ``connect``,
    ``subscribe(topic, cb)``, ``publish(topic, payload, qos)`` (QoS-1 blocks
    for the PUBACK, retrying once through a reconnect), ``will_set`` before
    connect, ``disconnect``.  A reconnect replays every subscription —
    clean-session semantics, same as ``PahoMqttBroker._on_connect``.
    """

    def __init__(self, host: str, port: int, client_id: str,
                 keepalive: float = 30.0, reconnect_delay: float = 0.1):
        self.host, self.port, self.client_id = host, port, client_id
        self.keepalive = keepalive
        self.reconnect_delay = reconnect_delay
        self._will: Optional[tuple[str, bytes, int]] = None
        self._subs: dict[str, Callable[[str, bytes], None]] = {}
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._slock = threading.Lock()
        self._next_pid = 1
        self._acks: dict[int, threading.Event] = {}
        # QoS2 state: outbound pid -> stage event pair; inbound stash until
        # the broker's PUBREL releases it (exactly-once dispatch)
        self._qos2_recs: dict[int, threading.Event] = {}
        self._qos2_comps: dict[int, threading.Event] = {}
        self._qos2_in: dict[int, tuple[str, bytes]] = {}
        self._connected = threading.Event()
        self._stopping = False
        # connection generation: each connect() bumps it, and reader/ping
        # threads exit when their generation is stale — a re-connect after
        # disconnect() must not revive the OLD threads (they would clobber
        # _connected and dial a competing session under the same client id)
        self._gen = 0
        self.reconnects = 0

    # -- lifecycle -----------------------------------------------------------
    def will_set(self, topic: str, payload: bytes, qos: int = 1) -> None:  # graftlint: disable=GL008(MQTT protocol: the will must be set before connect(); no reader/ping thread exists until connect starts them)
        self._will = (topic, payload, qos)

    def connect(self) -> None:  # graftlint: disable=GL008(the generation protocol is the synchronization: _gen/_stopping are written in the documented order below, and stale reader/ping threads self-retire on the next guard check — a lock here would have to be held across blocking socket reads to add anything)
        # a client may be re-connected after disconnect() (the adapter's
        # lazy-connect contract).  Order matters: retire the old generation
        # BEFORE clearing the stop flag — the other way round, a parked old
        # reader could pass both loop guards in the window between the two
        # writes and attach to the new socket (two readers on one socket
        # interleave partial reads and corrupt the framing).
        self._gen += 1
        gen = self._gen
        self._stopping = False
        self._do_connect()
        threading.Thread(target=self._reader_loop, args=(gen,), daemon=True).start()
        threading.Thread(target=self._ping_loop, args=(gen,), daemon=True).start()

    def _do_connect(self) -> None:  # graftlint: disable=GL008(runs on the caller thread at connect() or on the one live reader during its own reconnect — the generation guard admits exactly one dialer, so _sock/_qos2_in have a single writer; readers of _sock gate on the _connected Event)
        # clean-session connect: the broker forgets the QoS2 handshake, so a
        # PUBLISH stashed between PUBREC and PUBREL will never see its PUBREL
        # — drop the stash or it is stranded (never dispatched, never freed).
        # Outbound _acks/_qos2_recs/_qos2_comps are owned by their publish()
        # threads, which time out and retire their own entries.
        self._qos2_in.clear()
        sock = socket.create_connection((self.host, self.port), timeout=10)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        flags = 0x02  # clean session
        body = _enc_str("MQTT") + bytes([4])
        will_part = b""
        if self._will:
            wt, wp, wq = self._will
            flags |= 0x04 | (wq << 3)
            will_part = _enc_str(wt) + struct.pack(">H", len(wp)) + wp
        body += bytes([flags]) + struct.pack(">H", int(self.keepalive))
        body += _enc_str(self.client_id) + will_part
        sock.sendall(_packet(CONNECT, 0, body))
        sock.settimeout(10)
        ptype, _f, ack = _read_packet(sock)
        if ptype != CONNACK or ack[1] != 0:
            raise ConnectionError(f"CONNACK refused: type={ptype} rc={ack!r}")
        sock.settimeout(None)
        self._sock = sock
        self._connected.set()
        # clean-session reconnect: replay every subscription or all FL-round
        # traffic silently stops (the exact trap PahoMqttBroker guards)
        with self._slock:
            topics = list(self._subs)
        for t in topics:
            self._send_subscribe(t)

    def disconnect(self) -> None:
        self._stopping = True
        self._connected.clear()
        sock = self._sock
        if sock is not None:
            try:
                with self._wlock:
                    sock.sendall(_packet(DISCONNECT, 0, b""))  # graftlint: disable=GL007(_wlock serializes whole frames on the socket; the DISCONNECT frame must not interleave a concurrent publish)
            except OSError:
                pass
            try:
                sock.shutdown(socket.SHUT_RDWR)  # wake the blocked reader
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._sock = None

    # -- io loops ------------------------------------------------------------
    def _reader_loop(self, gen: int) -> None:  # graftlint: disable=GL008(ack/qos2 Event tables: publish() threads insert before send and wait on the Event; this loop only pops — CPython dict set/pop are atomic and the Event is the cross-thread handshake)
        while not self._stopping and gen == self._gen:
            sock = self._sock
            if sock is None or not self._connected.is_set():
                time.sleep(0.01)
                continue
            try:
                ptype, flags, body = _read_packet(sock)
            except (ConnectionError, OSError, ValueError):
                if self._stopping or gen != self._gen:
                    return  # retired generation: a newer connect() owns state
                self._connected.clear()
                self._reconnect(gen)
                continue
            if ptype == PUBLISH:
                self._handle_publish(flags, body)
            elif ptype == PUBACK:
                (pid,) = struct.unpack_from(">H", body, 0)
                ev = self._acks.pop(pid, None)
                if ev:
                    ev.set()
            elif ptype == PUBREC:
                (pid,) = struct.unpack_from(">H", body, 0)
                ev = self._qos2_recs.pop(pid, None)
                if ev:
                    ev.set()  # publish() sends the PUBREL (its thread owns retry)
            elif ptype == PUBCOMP:
                (pid,) = struct.unpack_from(">H", body, 0)
                ev = self._qos2_comps.pop(pid, None)
                if ev:
                    ev.set()
            elif ptype == PUBREL:
                (pid,) = struct.unpack_from(">H", body, 0)
                stashed = self._qos2_in.pop(pid, None)
                try:
                    self._send(_packet(PUBCOMP, 0, struct.pack(">H", pid)))
                except OSError:
                    pass
                if stashed is not None:  # duplicate PUBREL: no re-dispatch
                    self._dispatch(*stashed)
            elif ptype in (SUBACK, UNSUBACK, PINGRESP):
                pass
            else:
                log.warning("client %s: unexpected packet type %d", self.client_id, ptype)

    def _reconnect(self, gen: int) -> None:
        while not self._stopping and gen == self._gen:
            time.sleep(self.reconnect_delay)
            # re-check AFTER the sleep: a disconnect()+connect() during the
            # delay owns the state now — dialing here would open a second
            # session under the same client id and get both kicked
            if self._stopping or gen != self._gen:
                return
            try:
                self._do_connect()
                self.reconnects += 1
                return
            except OSError as e:
                log.debug("client %s reconnect failed: %s", self.client_id, e)

    def _ping_loop(self, gen: int) -> None:
        interval = max(self.keepalive / 2.0, 0.5)
        while not self._stopping and gen == self._gen:
            time.sleep(interval)
            if self._connected.is_set() and gen == self._gen:
                try:
                    self._send(_packet(PINGREQ, 0, b""))
                except OSError:
                    pass  # the reader loop owns reconnection

    def _handle_publish(self, flags: int, body: bytes) -> None:
        qos = (flags >> 1) & 0x03
        topic, off = _take_str(body, 0)
        if qos == 2:
            # exactly-once inbound: stash until the broker's PUBREL releases
            (pid,) = struct.unpack_from(">H", body, off)
            off += 2
            self._qos2_in[pid] = (topic, body[off:])
            try:
                self._send(_packet(PUBREC, 0, struct.pack(">H", pid)))
            except OSError:
                pass
            return
        if qos == 1:
            (pid,) = struct.unpack_from(">H", body, off)
            off += 2
            try:
                self._send(_packet(PUBACK, 0, struct.pack(">H", pid)))
            except OSError:
                pass
        self._dispatch(topic, body[off:])

    def _dispatch(self, topic: str, payload: bytes) -> None:
        with self._slock:
            cbs = [cb for t, cb in self._subs.items() if topic_matches(t, topic)]
        for cb in cbs:
            try:
                cb(topic, payload)
            except Exception:  # a handler crash must not kill the reader
                log.exception("client %s: on_message handler failed", self.client_id)

    def _send(self, data: bytes) -> None:  # graftlint: disable=GL007(_wlock exists precisely to serialize whole MQTT frames onto one socket; holding it across sendall IS the framing invariant)
        sock = self._sock
        if sock is None:
            raise OSError("not connected")
        with self._wlock:
            sock.sendall(data)

    # -- API -----------------------------------------------------------------
    def subscribe(self, topic: str, cb: Callable[[str, bytes], None]) -> None:
        with self._slock:
            self._subs[topic] = cb
        if self._connected.is_set():
            self._send_subscribe(topic)

    def _send_subscribe(self, topic: str) -> None:
        with self._wlock:
            pid = self._next_pid
            self._next_pid = pid % 65535 + 1
        body = struct.pack(">H", pid) + _enc_str(topic) + bytes([2])
        self._send(_packet(SUBSCRIBE, 0x02, body))

    def publish(self, topic: str, payload: bytes, qos: int = 1,
                timeout: float = 10.0) -> None:
        # ONE packet id for all attempts: MQTT DUP redelivery must reuse the
        # pid — the receiver's exactly-once dedup (and the broker's QoS2
        # stash) key on it, so a fresh pid per retry would deliver twice
        with self._wlock:
            pid = self._next_pid
            self._next_pid = pid % 65535 + 1
        body = _enc_str(topic) + struct.pack(">H", pid) + payload
        rec_seen = False  # QoS2 stage: once PUBREC arrived, retries resend
        #                   PUBREL only — re-publishing after the broker
        #                   routed would not be deduped by a clean session
        for attempt in (0, 1):
            if not self._connected.wait(timeout):
                raise TimeoutError(f"client {self.client_id}: not connected")
            dup = 0x08 if attempt else 0
            if qos == 0:
                try:
                    self._send(_packet(PUBLISH, 0, _enc_str(topic) + payload))
                    return
                except OSError:
                    continue  # reader loop reconnects; one retry
            if qos == 1:
                ev = threading.Event()
                self._acks[pid] = ev
                try:
                    self._send(_packet(PUBLISH, dup | 0x02, body))
                    if ev.wait(timeout):
                        return
                except OSError:
                    pass  # fall through to the retry (reader loop reconnects)
                finally:
                    # always retire the pending entry: a stranded Event would
                    # leak per failed publish, and after the pid wrap a fresh
                    # PUBACK could route to a stale entry
                    self._acks.pop(pid, None)
                continue
            # QoS2 exactly-once: PUBLISH -> PUBREC -> PUBREL -> PUBCOMP
            rec, comp = threading.Event(), threading.Event()
            self._qos2_recs[pid] = rec
            self._qos2_comps[pid] = comp
            try:
                if not rec_seen:
                    self._send(_packet(PUBLISH, dup | 0x04, body))
                    if not rec.wait(timeout):
                        continue  # no PUBREC: redeliver (same pid, DUP set)
                    rec_seen = True
                self._send(_packet(PUBREL, 0x02, struct.pack(">H", pid)))
                if comp.wait(timeout):
                    return
            except OSError:
                pass
            finally:
                self._qos2_recs.pop(pid, None)
                self._qos2_comps.pop(pid, None)
        raise TimeoutError(
            f"client {self.client_id}: qos{qos} handshake incomplete for {topic}"
        )
