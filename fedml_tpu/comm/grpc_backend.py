"""gRPC communication backend.

Parity with ``core/distributed/communication/grpc/`` (``GRPCCommManager``
``grpc_comm_manager.py:30``, servicer ``grpc_server.py:10``): a unary
``SendMessage`` RPC carrying one serialized Message; an ip_config map routes
receiver_id -> host; 1 GB max message.

Differences by design: the payload is the language-neutral pytree wire format
(not pickle), and the service is registered with a generic handler over raw
bytes — no protoc-generated stubs to keep in sync (the .proto contract is
just "unary bytes in, empty bytes out" at
``/fedml_tpu.CommService/SendMessage``).
"""

from __future__ import annotations

import itertools
import queue
from concurrent import futures
from typing import Optional

import grpc

from . import wire
from .base import BaseCommunicationManager, ObserverLoopMixin
from .message import Message

SERVICE_METHOD = "/fedml_tpu.CommService/SendMessage"
MAX_MESSAGE_BYTES = 1024 * 1024 * 1024  # reference: 1 GB
_GRPC_OPTS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]


def _identity(b: bytes) -> bytes:
    return b


class _Servicer(grpc.GenericRpcHandler):
    def __init__(self, inbox: queue.Queue):
        self.inbox = inbox

    def service(self, handler_call_details):
        if handler_call_details.method != SERVICE_METHOD:
            return None

        def handler(request: bytes, context) -> bytes:
            self.inbox.put(request)
            return b""

        return grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=_identity, response_serializer=_identity
        )


class GRPCCommManager(ObserverLoopMixin, BaseCommunicationManager):
    """One endpoint = one gRPC server (receiving) + per-peer channels (sending).

    ``ip_config``: {endpoint_id: "host"} (reference CSV ip_config semantics;
    keys may be str from YAML — normalized to int); ``base_port``: endpoint i
    listens on base_port + i (reference does the same arithmetic).
    """

    def __init__(self, host: str, port: int, rank: int,
                 ip_config: Optional[dict] = None, base_port: int = 8890,
                 chunk_bytes: int = 0):
        self.rank = rank
        # YAML/JSON mapping keys arrive as strings; normalize so lookups hit
        self.ip_config = {int(k): v for k, v in (ip_config or {}).items()}
        self.base_port = base_port
        # extra.comm_chunk_bytes: large messages ship as bounded chunk-frame
        # RPCs (each its own unary call, so N uploads interleave through the
        # server's thread pool); 0 = one RPC per message, the legacy bytes
        self.chunk_bytes = int(chunk_bytes or 0)
        self._stream_seq = itertools.count()
        self._init_observer_loop()
        self._channels: dict[int, grpc.Channel] = {}
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8), options=_GRPC_OPTS
        )
        self._server.add_generic_rpc_handlers((_Servicer(self._inbox),))
        self._bound_port = self._server.add_insecure_port(f"{host}:{port}")
        if self._bound_port == 0:
            raise OSError(
                f"gRPC endpoint {rank} failed to bind {host}:{port} "
                "(port in use?); refusing to start a deaf endpoint"
            )
        self._server.start()

    def _target_for(self, receiver_id: int) -> str:
        host = self.ip_config.get(int(receiver_id), "127.0.0.1")
        return f"{host}:{self.base_port + int(receiver_id)}"

    def send_message(self, msg: Message) -> None:
        rid = msg.get_receiver_id()
        if rid not in self._channels:
            self._channels[rid] = grpc.insecure_channel(self._target_for(rid), options=_GRPC_OPTS)
        stub = self._channels[rid].unary_unary(
            SERVICE_METHOD, request_serializer=_identity, response_deserializer=_identity
        )
        payload = msg.encode()
        if self.chunk_bytes and len(payload) > self.chunk_bytes:
            stream_id = f"{self.rank}.{next(self._stream_seq)}"
            for frame in wire.encode_chunk_frames(
                    payload, stream_id=stream_id, sender=self.rank,
                    chunk_bytes=self.chunk_bytes):
                stub(frame, timeout=60.0)
        else:
            stub(payload, timeout=60.0)

    def send_raw(self, receiver_id: int, payload: bytes) -> None:
        """One raw unary call to a peer, bypassing Message encode — the
        chaos wrapper's corrupt-frame injection point."""
        rid = int(receiver_id)
        if rid not in self._channels:
            self._channels[rid] = grpc.insecure_channel(
                self._target_for(rid), options=_GRPC_OPTS)
        stub = self._channels[rid].unary_unary(
            SERVICE_METHOD, request_serializer=_identity,
            response_deserializer=_identity)
        stub(payload, timeout=60.0)

    def stop_receive_message(self) -> None:
        super().stop_receive_message()
        self._server.stop(grace=0.2)
        for ch in self._channels.values():
            ch.close()
