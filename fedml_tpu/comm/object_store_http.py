"""Minimal HTTP object store (the in-repo S3 role) + its client.

The reference offloads large payloads to real S3
(``core/distributed/communication/mqtt_s3/remote_storage.py``); this build
has zero egress, so the same control/payload split is proven against an
in-repo HTTP store speaking real sockets: PUT stores bytes, GET returns
them — the minimal surface ``MqttS3CommManager`` needs from its store.
boto3-backed :class:`~fedml_tpu.comm.mqtt_real.S3ObjectStore` keeps the
same interface for real deployments.
"""

from __future__ import annotations

import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class MiniObjectStoreServer:
    """Threaded HTTP store: ``PUT /key`` -> 200, ``GET /key`` -> bytes/404."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._blobs: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None

    def start(self) -> int:
        blobs, lock = self._blobs, self._lock

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence per-request stderr noise
                pass

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(n)
                with lock:
                    blobs[self.path.lstrip("/")] = data
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                with lock:
                    data = blobs.get(self.path.lstrip("/"))
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


class HttpObjectStore:
    """Client side of :class:`MiniObjectStoreServer` — the
    ``InMemoryObjectStore`` interface (``put``/``get``) over real HTTP."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def put(self, key: str, data: bytes) -> str:
        req = urllib.request.Request(
            f"{self.base_url}/{key}", data=data, method="PUT",
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            if r.status != 200:
                raise RuntimeError(f"object store PUT {key} -> {r.status}")
        return key

    def get(self, key: str) -> bytes:
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/{key}", timeout=self.timeout
            ) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # keep the InMemoryObjectStore contract: callers handling a
                # missing-payload race catch KeyError, not HTTPError
                raise KeyError(key) from e
            raise
