"""In-process loopback transport — the test fake the reference never had.

The reference tests multi-node by oversubscribed processes over real brokers
(SURVEY.md §4); its comm managers have no mock transport.  This backend gives
every endpoint a queue inside one process, routed through a shared
``InProcRouter`` keyed by run_id — so the full cross-silo protocol (server +
N clients, real Message encode/decode) runs hermetically in a unit test,
including injected failures (drop/delay/disconnect) for straggler-handling
tests (SURVEY.md §7 hard part 4).

Messages ARE round-tripped through the wire format on every send, so the
fake exercises exactly the bytes a remote backend would.
"""

from __future__ import annotations

import itertools
import queue
import threading
from collections import defaultdict
from typing import Callable, Optional

from . import wire
from .base import BaseCommunicationManager, ObserverLoopMixin
from .message import Message


class InProcRouter:
    """Shared message fabric for one run_id (the 'broker')."""

    _routers: dict[str, "InProcRouter"] = {}
    _lock = threading.Lock()

    def __init__(self):
        self.queues: dict[int, queue.Queue] = defaultdict(queue.Queue)
        self.drop_rule: Optional[Callable[[Message], bool]] = None
        self.delay_rule: Optional[Callable[[Message], float]] = None
        self._stream_seq = itertools.count()

    @classmethod
    def get(cls, run_id: str) -> "InProcRouter":
        with cls._lock:
            if run_id not in cls._routers:
                cls._routers[run_id] = cls()
            return cls._routers[run_id]

    @classmethod
    def reset(cls, run_id: str) -> None:
        with cls._lock:
            cls._routers.pop(run_id, None)

    def route(self, msg: Message, chunk_bytes: int = 0) -> None:
        """Deliver one message.  ``chunk_bytes`` > 0 and an encoded frame
        past the bound ships as transport chunk frames (ISSUE 11 satellite:
        the in-proc fabric exercises BOTH legs — server->client broadcast
        and client uploads — through the same chunk-frame envelope the
        gRPC/TCP senders already produce); 0 = one whole frame per message,
        byte-identical to the pre-chunk protocol."""
        if self.drop_rule is not None and self.drop_rule(msg):
            return
        data = msg.encode()  # force the wire round-trip
        if chunk_bytes and len(data) > chunk_bytes:
            stream_id = f"{msg.get_sender_id()}.{next(self._stream_seq)}"
            frames = list(wire.encode_chunk_frames(
                data, stream_id=stream_id, sender=msg.get_sender_id(),
                chunk_bytes=chunk_bytes))
        else:
            frames = [data]
        target = self.queues[msg.get_receiver_id()]

        def deliver() -> None:
            for frame in frames:
                target.put(frame)

        delay = self.delay_rule(msg) if self.delay_rule is not None else 0.0
        if delay > 0:
            t = threading.Timer(delay, deliver)
            t.daemon = True
            t.start()
        else:
            deliver()


class InProcCommManager(ObserverLoopMixin, BaseCommunicationManager):
    def __init__(self, run_id: str, rank: int, chunk_bytes: int = 0):
        self.run_id = str(run_id)
        self.rank = rank
        # extra.comm_chunk_bytes (ISSUE 11 satellite): the in-proc fabric
        # honors the same chunk bound as the gRPC/TCP backends so broadcast
        # AND upload legs reassemble through the receive loop's assembler
        self.chunk_bytes = int(chunk_bytes or 0)
        self.router = InProcRouter.get(self.run_id)
        self._init_observer_loop(inbox=self.router.queues[rank])

    def send_message(self, msg: Message) -> None:
        if self.chunk_bytes:
            self.router.route(msg, chunk_bytes=self.chunk_bytes)
        else:
            # positional call, exactly the pre-chunk signature: route() taps
            # (tests, tooling) that wrap the unchunked fabric keep working
            self.router.route(msg)

    def send_raw(self, receiver_id: int, payload: bytes) -> None:
        """Deliver raw frame bytes to a peer's inbox, bypassing the Message
        round trip — the chaos wrapper's corrupt-frame injection point (a
        real transport would deliver torn bytes exactly like this)."""
        self.router.queues[receiver_id].put(payload)
