"""Abstract communication backend + observer interface.

Parity with ``core/distributed/communication/base_com_manager.py`` and
``observer.py``: a backend moves ``Message``s between numbered endpoints and
notifies registered observers on receive.
"""

from __future__ import annotations

import queue
from abc import ABC, abstractmethod

from .message import Message


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type: int, msg: Message) -> None: ...


class ObserverLoopMixin:
    """Shared observer registry + poll/decode/dispatch receive loop.

    Backends set ``self._inbox`` (a queue of raw payloads) and may override
    ``_decode_bytes``; everything else is identical across transports.
    """

    _observers: list
    _inbox: "queue.Queue"
    _running: bool = False

    def _init_observer_loop(self, inbox: "queue.Queue" = None) -> None:
        self._observers = []
        self._inbox = inbox if inbox is not None else queue.Queue()
        self._running = False

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _decode_bytes(self, data: bytes) -> Message:
        return Message.decode(data)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                data = self._inbox.get(timeout=0.05)
            except queue.Empty:
                continue
            msg = self._decode_bytes(data)
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._running = False


class BaseCommunicationManager(ABC):
    @abstractmethod
    def send_message(self, msg: Message) -> None: ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching received messages to observers, until
        stop_receive_message is called."""

    @abstractmethod
    def stop_receive_message(self) -> None: ...
