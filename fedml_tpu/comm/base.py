"""Abstract communication backend + observer interface.

Parity with ``core/distributed/communication/base_com_manager.py`` and
``observer.py``: a backend moves ``Message``s between numbered endpoints and
notifies registered observers on receive.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from .message import Message


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type: int, msg: Message) -> None: ...


class BaseCommunicationManager(ABC):
    @abstractmethod
    def send_message(self, msg: Message) -> None: ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching received messages to observers, until
        stop_receive_message is called."""

    @abstractmethod
    def stop_receive_message(self) -> None: ...
