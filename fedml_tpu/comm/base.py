"""Abstract communication backend + observer interface.

Parity with ``core/distributed/communication/base_com_manager.py`` and
``observer.py``: a backend moves ``Message``s between numbered endpoints and
notifies registered observers on receive.

The receive loop is also the transport-agnostic metering point: every
backend funnels raw payloads through it, so messages/bytes received, decode
drops, and transient-decode retries are counted here in the process-global
:mod:`~fedml_tpu.obs.registry` regardless of transport.
"""

from __future__ import annotations

import logging
import queue
import time
from abc import ABC, abstractmethod

from ..obs import registry as obsreg
from . import wire
from .message import ChunkAssembler, Message

log = logging.getLogger(__name__)

# transport-agnostic comm metrics (send-side counterparts live in
# comm_manager.FedMLCommManager.send_message, the one choke point every
# protocol send passes through)
MSG_RECEIVED = obsreg.REGISTRY.counter(
    "fedml_comm_messages_received_total",
    "Messages decoded and dispatched to observers, by protocol message type.",
    labels=("type",),
)
BYTES_RECEIVED = obsreg.REGISTRY.counter(
    "fedml_comm_bytes_received_total",
    "Wire bytes of successfully decoded messages.",
)
MSG_DROPPED = obsreg.REGISTRY.counter(
    "fedml_comm_messages_dropped_total",
    "Messages dropped in the receive loop, by reason.",
    labels=("reason",),
)
DECODE_RETRIES = obsreg.REGISTRY.counter(
    "fedml_comm_decode_retries_total",
    "Transient decode failures deferred for retry (not yet dropped).",
)
HANDLER_ERRORS = obsreg.REGISTRY.counter(
    "fedml_comm_handler_errors_total",
    "Observer/handler exceptions contained by the receive loop.",
)
MSG_SENT = obsreg.REGISTRY.counter(
    "fedml_comm_messages_sent_total",
    "Messages handed to a transport send, by protocol message type.",
    labels=("type",),
)
SEND_LATENCY = obsreg.REGISTRY.histogram(
    "fedml_comm_send_latency_seconds",
    "Transport send() wall time, by protocol message type.",
    labels=("type",),
)
CHUNK_FRAMES = obsreg.REGISTRY.counter(
    "fedml_comm_chunk_frames_received_total",
    "Transport chunk frames fed to the per-peer stream assembler.",
)

#: transient decode failures are retried this many times with capped
#: exponential backoff + deterministic jitter (see :func:`backoff_delay`)
DECODE_RETRY_LIMIT = 3
DECODE_RETRY_BACKOFF_S = 0.2   # base of the exponential schedule
DECODE_RETRY_CAP_S = 2.0       # ceiling of the exponential schedule

#: a chunked upload whose sender dies mid-stream is evicted (and metered as
#: a drop attributed to that sender) after this long without a new chunk —
#: the DEFAULT; ``extra.comm_chunk_idle_sweep_s`` overrides per run (the
#: FedMLCommManager threads it through ``configure_chunk_sweep``)
CHUNK_STREAM_TIMEOUT_S = 120.0


#: purpose constants namespacing the :func:`backoff_delay` jitter streams.
#: Two retry schedules that happen to share a numeric ``seed`` (a decode
#: retry's default 0 and a client whose derived seed lands on 0, say) would
#: otherwise draw IDENTICAL jitter at every attempt and re-fire in lockstep —
#: exactly the correlated-retry stampede the jitter exists to prevent.  Each
#: call site folds its purpose constant into the rng seed so colocated
#: schedules decorrelate while every single schedule stays reproducible.
BACKOFF_PURPOSE_DECODE_RETRY = 0x44454352    # "DECR": receive-loop decode retry
BACKOFF_PURPOSE_RECONNECT = 0x52434E54       # "RCNT": client upload reconnect
BACKOFF_PURPOSE_STATUS_PROBE = 0x53545052    # "STPR": server status re-probe


def backoff_delay(attempt: int, *, base: float = DECODE_RETRY_BACKOFF_S,
                  cap: float = DECODE_RETRY_CAP_S, seed: int = 0,
                  purpose: int = 0) -> float:
    """Capped exponential backoff with DETERMINISTIC jitter.

    ``base * 2**attempt`` clipped at ``cap``, scaled by a jitter factor in
    ``[0.5, 1.0)`` drawn from ``default_rng([purpose, seed, attempt])`` — so
    N peers retrying the same flaky dependency de-synchronize (different
    seeds), colocated retry loops with coinciding seeds de-synchronize too
    (different ``purpose`` constants — see the ``BACKOFF_PURPOSE_*`` block
    above), while any single schedule is exactly reproducible (same purpose,
    seed, and attempt → same delay, the property the chaos soak's
    determinism assertions rely on).  Replaces the old linear
    ``base * (attempt+1)`` schedule, whose waits grew too slowly to ride out
    a multi-second object-store brownout within DECODE_RETRY_LIMIT
    attempts."""
    import numpy as np

    raw = min(float(cap), float(base) * (2.0 ** int(attempt)))
    frac = float(np.random.default_rng(
        [int(purpose), int(seed), int(attempt)]).random())
    return raw * (0.5 + 0.5 * frac)

#: process-wide comm event sinks ``fn(event, **info)`` for the drop/retry
#: signals the counters above aggregate — the client health ledger
#: (obs/health.py) subscribes so transport pressure folds into health
#: scores.  Events carry ``client=<sender>`` whenever the failing payload
#: is attributable (chunk subheaders name their sender), so per-client
#: pressure accrues for async arrivals the same way the synchronous
#: broadcast-failure path attributes it.  Sink failures are swallowed:
#: telemetry must never take down the receive loop.
_event_sinks: list = []


def add_comm_event_sink(fn):
    _event_sinks.append(fn)
    return fn


def remove_comm_event_sink(fn) -> None:
    try:
        _event_sinks.remove(fn)
    except ValueError:
        pass


def _emit_comm_event(event: str, **info) -> None:
    for fn in list(_event_sinks):
        try:
            fn(event, **info)
        except Exception:
            pass


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type: int, msg: Message) -> None: ...


class ObserverLoopMixin:
    """Shared observer registry + poll/decode/dispatch receive loop.

    Backends set ``self._inbox`` (a queue of raw payloads) and may override
    ``_decode_bytes``; everything else is identical across transports.
    """

    _observers: list
    _inbox: "queue.Queue"
    _running: bool = False

    def _init_observer_loop(self, inbox: "queue.Queue" = None) -> None:
        self._observers = []
        self._inbox = inbox if inbox is not None else queue.Queue()
        self._running = False
        # per-peer reassembly of transport chunk frames (lazily built: the
        # unchunked protocol never pays for it)
        self._chunk_assembler = None
        self._chunk_sweep_s = CHUNK_STREAM_TIMEOUT_S

    def configure_chunk_sweep(self, seconds: float) -> None:
        """Set the idle-stream eviction timeout (``extra.
        comm_chunk_idle_sweep_s``); applies to streams opened after the call
        — configure before the receive loop starts, as FedMLCommManager
        does."""
        self._chunk_sweep_s = float(seconds)
        if self._chunk_assembler is not None:
            self._chunk_assembler.stream_timeout_s = float(seconds)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _decode_bytes(self, data: bytes) -> Message:
        return Message.decode(data)

    def handle_receive_message(self) -> None:
        self._running = True
        # transiently-undecodable payloads wait here with a not-before
        # timestamp instead of sleeping the loop or cycling through the
        # inbox: healthy messages keep draining in arrival order while a
        # flaky object-store blob backs off
        retry_pending: list[tuple[float, bytes, int]] = []
        while self._running:
            item = None
            if retry_pending:
                now = time.monotonic()
                for i, (not_before, data, attempts) in enumerate(retry_pending):
                    if not_before <= now:
                        item = (data, attempts)
                        del retry_pending[i]
                        break
            if item is None:
                try:
                    raw = self._inbox.get(timeout=0.05)
                except queue.Empty:
                    self._sweep_chunk_streams()
                    continue
                # pre-redesign requeues carried (data, attempts) tuples;
                # accept both shapes so a mid-upgrade inbox still drains
                item = raw if isinstance(raw, tuple) else (raw, 0)
            data, attempts = item
            if isinstance(data, (bytes, bytearray, memoryview)) and wire.is_chunk_frame(data):
                # chunked upload: feed the per-peer assembler; leaves decode
                # incrementally, and only the FINAL chunk yields a Message
                CHUNK_FRAMES.inc()
                if self._chunk_assembler is None:
                    self._chunk_assembler = ChunkAssembler(self._chunk_sweep_s)
                msg, err, sender = self._chunk_assembler.feed(data)
                if err is not None:
                    MSG_DROPPED.inc(reason=err)
                    _emit_comm_event("dropped", reason=err, client=sender)
                    log.error("dropping chunk stream from sender %s: %s", sender, err)
                    continue
                if msg is None:
                    continue  # stream still in flight
                MSG_RECEIVED.inc(type=str(msg.get_type()))
                BYTES_RECEIVED.inc(msg.wire_nbytes)
                self._dispatch(msg)
                continue
            try:
                msg = self._decode_bytes(data)
            except (KeyError, ValueError):
                # a genuinely poisoned payload (store blob truly absent ->
                # KeyError, corrupt framing -> ValueError) must not kill the
                # receive loop: that silently drops every subsequent FL
                # message for the life of the process.  Drop it loudly.
                MSG_DROPPED.inc(reason="undecodable")
                _emit_comm_event("dropped", reason="undecodable")
                log.exception("dropping undecodable message (%d bytes)", len(data))
                continue
            except Exception:
                # transient decode failure (object store briefly unreachable,
                # HTTP 5xx/reset): the blob may well exist — MQTT already
                # acked, so there is no transport redelivery.  Defer and
                # retry a few times before giving up.
                if attempts < DECODE_RETRY_LIMIT:
                    DECODE_RETRIES.inc()
                    _emit_comm_event("retried")
                    log.warning(
                        "transient decode failure (attempt %d) — deferring",
                        attempts + 1, exc_info=True,
                    )
                    retry_pending.append((
                        time.monotonic() + backoff_delay(
                            attempts, purpose=BACKOFF_PURPOSE_DECODE_RETRY),
                        data, attempts + 1,
                    ))
                else:
                    MSG_DROPPED.inc(reason="retries_exhausted")
                    _emit_comm_event("dropped", reason="retries_exhausted")
                    log.exception(
                        "dropping message after %d decode attempts", attempts + 1
                    )
                continue
            MSG_RECEIVED.inc(type=str(msg.get_type()))
            if isinstance(data, (bytes, bytearray, memoryview)):
                BYTES_RECEIVED.inc(len(data))
            self._dispatch(msg)

    def _dispatch(self, msg: Message) -> None:
        if msg.recv_monotonic is None:
            msg.recv_monotonic = time.monotonic()
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.get_type(), msg)
            except Exception:
                # a handler crash must not kill the loop — same invariant as
                # the decode guard: one poisoned message, not a dead endpoint
                HANDLER_ERRORS.inc()
                log.exception(
                    "observer %r failed on message type %s",
                    obs, msg.get_type(),
                )

    def _sweep_chunk_streams(self) -> None:
        """Evict chunk streams whose sender went dark mid-upload; each
        eviction is a metered, sender-attributed drop."""
        if self._chunk_assembler is None:
            return
        for sender, stream_id in self._chunk_assembler.sweep():
            MSG_DROPPED.inc(reason="chunk_stream_timeout")
            _emit_comm_event("dropped", reason="chunk_stream_timeout", client=sender)
            log.warning("evicting stale chunk stream %s from sender %s",
                        stream_id, sender)

    def stop_receive_message(self) -> None:
        self._running = False


class BaseCommunicationManager(ABC):
    @abstractmethod
    def send_message(self, msg: Message) -> None: ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching received messages to observers, until
        stop_receive_message is called."""

    @abstractmethod
    def stop_receive_message(self) -> None: ...
