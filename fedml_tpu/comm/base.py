"""Abstract communication backend + observer interface.

Parity with ``core/distributed/communication/base_com_manager.py`` and
``observer.py``: a backend moves ``Message``s between numbered endpoints and
notifies registered observers on receive.
"""

from __future__ import annotations

import logging
import queue
import time
from abc import ABC, abstractmethod

from .message import Message

log = logging.getLogger(__name__)


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type: int, msg: Message) -> None: ...


class ObserverLoopMixin:
    """Shared observer registry + poll/decode/dispatch receive loop.

    Backends set ``self._inbox`` (a queue of raw payloads) and may override
    ``_decode_bytes``; everything else is identical across transports.
    """

    _observers: list
    _inbox: "queue.Queue"
    _running: bool = False

    def _init_observer_loop(self, inbox: "queue.Queue" = None) -> None:
        self._observers = []
        self._inbox = inbox if inbox is not None else queue.Queue()
        self._running = False

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _decode_bytes(self, data: bytes) -> Message:
        return Message.decode(data)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                item = self._inbox.get(timeout=0.05)
            except queue.Empty:
                continue
            # re-enqueued items carry their retry count (see below)
            data, attempts = item if isinstance(item, tuple) else (item, 0)
            try:
                msg = self._decode_bytes(data)
            except (KeyError, ValueError):
                # a genuinely poisoned payload (store blob truly absent ->
                # KeyError, corrupt framing -> ValueError) must not kill the
                # receive loop: that silently drops every subsequent FL
                # message for the life of the process.  Drop it loudly.
                log.exception("dropping undecodable message (%d bytes)", len(data))
                continue
            except Exception:
                # transient decode failure (object store briefly unreachable,
                # HTTP 5xx/reset): the blob may well exist — MQTT already
                # acked, so there is no transport redelivery.  Retry a few
                # times before giving up.
                if attempts < 3:
                    log.warning(
                        "transient decode failure (attempt %d) — requeueing",
                        attempts + 1, exc_info=True,
                    )
                    time.sleep(0.2 * (attempts + 1))
                    self._inbox.put((data, attempts + 1))
                else:
                    log.exception(
                        "dropping message after %d decode attempts", attempts + 1
                    )
                continue
            for obs in list(self._observers):
                try:
                    obs.receive_message(msg.get_type(), msg)
                except Exception:
                    # a handler crash must not kill the loop either — same
                    # invariant as the decode guard above
                    log.exception(
                        "observer %r failed on message type %s",
                        obs, msg.get_type(),
                    )

    def stop_receive_message(self) -> None:
        self._running = False


class BaseCommunicationManager(ABC):
    @abstractmethod
    def send_message(self, msg: Message) -> None: ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching received messages to observers, until
        stop_receive_message is called."""

    @abstractmethod
    def stop_receive_message(self) -> None: ...
