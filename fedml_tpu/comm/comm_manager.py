"""FedMLCommManager — handler registry + backend factory.

Parity with ``core/distributed/fedml_comm_manager.py:11``: server/client
managers subclass this, register per-msg_type handlers, and run a blocking
receive loop; ``_init_manager`` (:133) is the backend factory keyed by
``args.backend``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .. import constants as C
from ..core.flags import cfg_extra
from ..obs import trace as obstrace
from .base import BaseCommunicationManager, MSG_SENT, Observer, SEND_LATENCY
from .message import Message


class FedMLCommManager(Observer):
    def __init__(self, cfg, rank: int = 0, size: int = 0, backend: Optional[str] = None):
        self.cfg = cfg
        self.rank = rank
        self.size = size
        self.backend = backend or getattr(cfg, "backend", C.COMM_BACKEND_INPROC)
        self.message_handler_dict: dict[int, Callable[[Message], None]] = {}
        self.com_manager: BaseCommunicationManager = self._init_manager()
        # deterministic chaos injection (comm/chaos.py): any extra.chaos_*
        # fault enabled wraps the backend in the seeded fault scheduler; all
        # unset -> the backend object itself, byte-identical traffic
        from .chaos import wrap_with_chaos

        self.com_manager = wrap_with_chaos(self.com_manager, cfg, rank)
        # idle chunk-stream eviction timeout (extra.comm_chunk_idle_sweep_s);
        # configured before the receive loop starts
        if hasattr(self.com_manager, "configure_chunk_sweep"):
            self.com_manager.configure_chunk_sweep(
                float(cfg_extra(cfg, "comm_chunk_idle_sweep_s")))
        self.com_manager.add_observer(self)

    # -- reference API shape -------------------------------------------------
    def register_message_receive_handler(self, msg_type: int, handler: Callable) -> None:
        self.message_handler_dict[msg_type] = handler

    def send_message(self, message: Message) -> None:
        # send-side trace propagation: an explicitly stamped header (the
        # server's round stamp) wins; otherwise the ambient span — e.g. a
        # client replying from inside an activated handler — rides along
        obstrace.inject(message)
        t0 = time.perf_counter()
        self.com_manager.send_message(message)
        msg_type = str(message.get_type())
        MSG_SENT.inc(type=msg_type)
        SEND_LATENCY.observe(time.perf_counter() - t0, type=msg_type)

    def receive_message(self, msg_type: int, msg: Message) -> None:
        handler = self.message_handler_dict.get(msg_type)
        if handler is None:
            raise KeyError(
                f"no handler registered for msg_type {msg_type} (rank {self.rank}); "
                f"registered: {sorted(self.message_handler_dict)}"
            )
        # receive-side trace propagation: the message's trace header becomes
        # the ambient context for the handler, so spans opened inside (client
        # train, server aggregate) join the sender's round-scoped trace
        with obstrace.activate(obstrace.extract(msg)):
            handler(msg)

    def run(self) -> None:
        """Blocking receive loop (reference ``FedMLCommManager.run``)."""
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()

    def run_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def finish(self) -> None:
        self.com_manager.stop_receive_message()

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their protocol handlers here."""
        raise NotImplementedError

    # -- backend factory (reference _init_manager :133) ----------------------
    def _init_manager(self) -> BaseCommunicationManager:
        b = self.backend
        if b == C.COMM_BACKEND_INPROC:
            from .inproc import InProcCommManager

            return InProcCommManager(
                getattr(self.cfg, "run_id", "0"), self.rank,
                chunk_bytes=int(cfg_extra(self.cfg, "comm_chunk_bytes") or 0),
            )
        if b == C.COMM_BACKEND_GRPC:
            from .grpc_backend import GRPCCommManager

            base_port = int(cfg_extra(self.cfg, "grpc_base_port"))
            ip_config = cfg_extra(self.cfg, "grpc_ip_config", {})
            return GRPCCommManager(
                "0.0.0.0", base_port + self.rank, self.rank,
                ip_config=ip_config, base_port=base_port,
                chunk_bytes=int(cfg_extra(self.cfg, "comm_chunk_bytes") or 0),
            )
        if b == C.COMM_BACKEND_MQTT_S3:
            from .mqtt_s3 import MqttS3CommManager

            run_id = getattr(self.cfg, "run_id", "0")
            broker = store = None
            mqtt_host = cfg_extra(self.cfg, "mqtt_host")
            if mqtt_host:
                # real MQTT over TCP (in-repo MiniMqttBroker or any external
                # 3.1.1 broker); payloads ride the HTTP object store when one
                # is configured (reference: broker + S3, run_cross_silo.sh)
                from .mqtt_real import TcpMqttBroker

                broker = TcpMqttBroker(
                    mqtt_host, int(cfg_extra(self.cfg, "mqtt_port")),
                    client_id=f"{run_id}_{self.rank}",
                )
                store_url = cfg_extra(self.cfg, "object_store_url")
                if not store_url:
                    # a cross-process broker with the per-process in-memory
                    # store would strand every >8KB payload: the sender
                    # offloads to ITS store and the receiver can't resolve
                    # the key.  Small control messages would work, so the
                    # misconfiguration only explodes at the first model
                    # broadcast — refuse up front instead.
                    raise ValueError(
                        "extra.mqtt_host is set but extra.object_store_url is "
                        "not; a real broker needs a shared payload store "
                        "(comm.object_store_http.MiniObjectStoreServer or S3)"
                    )
                from .object_store_http import HttpObjectStore

                store = HttpObjectStore(store_url)
            return MqttS3CommManager(
                run_id, self.rank,
                broker=broker, store=store,
            )
        if b in (C.COMM_BACKEND_WEB3, C.COMM_BACKEND_THETA):
            from .blockchain import BlockchainCommManager

            return BlockchainCommManager(getattr(self.cfg, "run_id", "0"), self.rank)
        if b == C.COMM_BACKEND_TCP:
            from .tcp_backend import TCPCommManager

            base_port = int(cfg_extra(self.cfg, "tcp_base_port"))
            ip_config = cfg_extra(self.cfg, "tcp_ip_config", {})
            return TCPCommManager(
                "0.0.0.0", base_port + self.rank, self.rank,
                ip_config=ip_config, base_port=base_port,
                chunk_bytes=int(cfg_extra(self.cfg, "comm_chunk_bytes") or 0),
            )
        raise ValueError(
            f"unknown comm backend {b!r}; known: "
            f"{[C.COMM_BACKEND_INPROC, C.COMM_BACKEND_GRPC, C.COMM_BACKEND_MQTT_S3, C.COMM_BACKEND_TCP, C.COMM_BACKEND_WEB3, C.COMM_BACKEND_THETA]}"
        )
