"""Decentralized FL — DSGD and PushSum over topology mixing matrices.

Reference: ``simulation/sp/decentralized/`` (``client_dsgd.py``,
``client_pushsum.py``) + ``core/distributed/topology/`` and the MPI
``decentralized_framework`` (gossip message passing between neighbor ranks).

TPU-native form (SURVEY.md §2.14 P10): all N clients' parameters live as one
stacked pytree sharded over the mesh; a gossip round is

    local SGD (vmap over clients)  ->  P' = W @ P   (mixing matmul)

The neighbor exchange that the reference implements with per-edge messages is
a single (N, N) x (N, d) matmul on the MXU — sparse topologies are just
sparse rows of W.  PushSum additionally threads the scalar weight column and
de-biases by it (directed graphs).
"""

from __future__ import annotations

import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import hparams_from_config
from ..arguments import Config
from ..core import aot as aotlib, pytree as pt, rng
from ..core.flags import cfg_extra
from ..data.dataset import pad_eval_set, stack_clients
from ..fl.local_sgd import make_eval_fn, make_local_train_fn
from ..obs.metrics import MetricsLogger
from ..parallel import mesh as meshlib, topology as topo


class DecentralizedSimulator:
    """DSGD (symmetric row-stochastic W) / PushSum (column-stochastic directed
    W, so the de-bias ratio x/w recovers the uniform average)."""

    def __init__(self, cfg: Config, dataset, model, mesh=None, mode: str = None):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        if mode is None:
            mode = cfg_extra(cfg, "decentralized_mode")
        self.mode = mode
        n = dataset.n_clients
        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        spe = max(1, math.ceil(stacked.capacity / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        self._local_train = make_local_train_fn(model, self.hp)
        self.mesh = mesh if mesh is not None else meshlib.mesh_from_config(cfg)

        neighbor_num = int(cfg_extra(cfg, "topology_neighbor_num") or 2)
        if mode == "pushsum":
            # column-stochastic so the push weights evolve and x/w recovers
            # the uniform average (see topology.column_stochastic)
            W = topo.column_stochastic(
                topo.asymmetric_topology(n, neighbor_num, seed=cfg.random_seed)
            )
        elif mode == "ring":
            # uniform {prev, self, next} ring — mixed via ppermute halo
            # exchange (see _make_ring_mix), W kept only as the reference
            # matrix for parity checks
            if n < 3:
                # with n <= 2 prev == next, so the halo mix weights the single
                # neighbor twice ((x + 2*other)/3) while the dense
                # ring_topology reference collapses the duplicate edge —
                # the two would silently diverge
                raise ValueError(
                    f"mode='ring' needs n >= 3 clients (got {n}); use "
                    "mode='dsgd' for 1-2 clients"
                )
            W = topo.ring_topology(n)
        else:
            W = topo.symmetric_topology(n, neighbor_num, seed=cfg.random_seed)
        self.W = jnp.asarray(W)

        k0 = rng.root_key(cfg.random_seed)
        sample_x = jnp.asarray(stacked.x[0, : cfg.batch_size])
        one = model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            sample_x, train=True,
        )
        # every client starts from the same init, stacked over clients
        self.client_vars = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), one
        )
        self.client_vars = meshlib.shard_leading_axis(self.client_vars, self.mesh)
        self.push_weights = jnp.ones((n,))  # PushSum de-bias column
        self._data = tuple(meshlib.shard_leading_axis((jnp.asarray(stacked.x), jnp.asarray(stacked.y)), self.mesh))
        self.counts = jnp.asarray(stacked.counts)
        self.root_key = k0
        self.round_idx = 0

        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self._eval_fn = jax.jit(make_eval_fn(model, self.hp, batch_size=eval_bs))
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        # AOT program store (extra.aot_programs): ring gossip was 587 s of
        # recurring dryrun compile — warm restarts deserialize the exported
        # shard_map/ppermute program instead of re-tracing it.  Unset -> the
        # exact old jit path.
        self._aot = aotlib.store_from_config(cfg, trail=self.logger.log)
        round_fn = self._make_round_fn()
        if self._aot is not None:
            example = (self.client_vars, self.push_weights, self._data[0],
                       self._data[1], self.counts, jnp.int32(0), self.root_key)
            self._round_fn = self._aot.cached_jit(
                round_fn, example,
                key=aotlib.program_key(
                    "sim.gossip_round", mesh=self.mesh,
                    trees={"args": example}, hparams=self.hp,
                    config=aotlib.config_signature(cfg),
                    extra={"mode": self.mode, "neighbors": neighbor_num}),
            )
        else:
            self._round_fn = jax.jit(round_fn)

    def _gossip_axis(self) -> str:
        """The mesh axis the stacked-clients dim shards over (the same
        fallback convention as shard_leading_axis)."""
        if meshlib.AXIS_CLIENTS in self.mesh.shape:
            return meshlib.AXIS_CLIENTS
        return self.mesh.axis_names[0]

    def _make_ring_mix(self, n: int):
        """Ring gossip as ICI halo exchange: each device holds a contiguous
        block of clients; the two boundary rows travel via ``lax.ppermute``
        and everything else is a local shift.  Equivalent to
        ``ring_topology(n) @ P`` without ever materializing the (n, n)
        mixing matrix — per-round traffic is 2 rows/device instead of the
        full stacked model, which is what makes large-N sparse rings viable
        (reference P10 does this with per-edge MPI messages;
        ``decentralized_framework/algorithm_api.py``)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import SHARD_MAP_UNCHECKED, shard_map

        axis = self._gossip_axis()
        d = self.mesh.shape[axis]
        if n % d:
            raise ValueError(
                f"ring gossip needs the client count ({n}) divisible by the "
                f"{axis!r} mesh axis ({d}) — contiguous blocks per device"
            )
        fwd = [(i, (i + 1) % d) for i in range(d)]
        bwd = [(i, (i - 1) % d) for i in range(d)]

        def local_mix(block):
            # block: this device's (n/d, ...) rows.  Row j needs rows j-1 and
            # j+1; the block-edge neighbors live one device over.
            def leaf_mix(leaf):
                x = leaf.astype(jnp.float32)
                if d > 1:
                    prev_last = jax.lax.ppermute(x[-1:], axis, fwd)
                    next_first = jax.lax.ppermute(x[:1], axis, bwd)
                else:
                    prev_last, next_first = x[-1:], x[:1]
                left = jnp.concatenate([prev_last, x[:-1]], axis=0)
                right = jnp.concatenate([x[1:], next_first], axis=0)
                return ((x + left + right) / 3.0).astype(leaf.dtype)

            return jax.tree_util.tree_map(leaf_mix, block)

        spec = P(axis)
        return shard_map(
            local_mix, mesh=self.mesh, in_specs=(spec,), out_specs=spec,
            **SHARD_MAP_UNCHECKED,
        )

    def _make_round_fn(self):
        W = self.W
        mode = self.mode

        if mode == "ring":
            mix = self._make_ring_mix(int(self.counts.shape[0]))
        else:
            def mix(stacked_tree):
                return jax.tree_util.tree_map(
                    lambda leaf: jnp.tensordot(W, leaf.astype(jnp.float32), axes=([1], [0])).astype(leaf.dtype),
                    stacked_tree,
                )

        def round_fn(client_vars, push_w, data_x, data_y, counts, round_idx, key):
            rkey = rng.round_key(key, round_idx)
            n = counts.shape[0]
            keys = jax.vmap(lambda i: rng.client_key(rkey, i))(jnp.arange(n))
            trained, metrics = jax.vmap(
                lambda v, x, y, c, k: self._local_train(v, x, y, c, k, None)
            )(client_vars, data_x, data_y, counts, keys)
            if mode == "pushsum":
                # mix both the weighted params and the weights; de-bias
                weighted = jax.tree_util.tree_map(
                    lambda l: l * push_w.reshape((-1,) + (1,) * (l.ndim - 1)), trained
                )
                mixed = mix(weighted)
                new_w = W @ push_w
                debiased = jax.tree_util.tree_map(
                    lambda l: l / new_w.reshape((-1,) + (1,) * (l.ndim - 1)), mixed
                )
                return debiased, new_w, {k: jnp.mean(v) for k, v in metrics.items()}
            mixed = mix(trained)
            return mixed, push_w, {k: jnp.mean(v) for k, v in metrics.items()}

        return round_fn

    def run_round(self) -> dict:
        self.client_vars, self.push_weights, metrics = self._round_fn(
            self.client_vars, self.push_weights, self._data[0], self._data[1],
            self.counts, jnp.int32(self.round_idx), self.root_key,
        )
        self.round_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def consensus_model(self):
        """Average of all clients' models (the consensus point)."""
        return jax.tree_util.tree_map(lambda l: jnp.mean(l.astype(jnp.float32), axis=0).astype(l.dtype), self.client_vars)

    def consensus_distance(self) -> float:
        """Mean squared distance of clients to the consensus — the standard
        decentralized-convergence diagnostic."""
        mean = self.consensus_model()
        d = jax.tree_util.tree_map(
            lambda l, m: jnp.mean(jnp.sum((l.astype(jnp.float32) - m[None].astype(jnp.float32)) ** 2,
                                          axis=tuple(range(1, l.ndim)))),
            self.client_vars, mean,
        )
        return float(jax.tree_util.tree_reduce(jnp.add, d, jnp.float32(0)))

    def evaluate(self) -> dict:
        return {k: float(v) for k, v in self._eval_fn(self.consensus_model(), *self._test).items()}

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (r + 1) % self.cfg.frequency_of_the_test == 0 or r == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
                metrics["consensus_dist"] = self.consensus_distance()
            self.logger.log(metrics)
            history.append(metrics)
        return history
