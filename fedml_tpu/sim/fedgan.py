"""FedGAN — federated GAN training.

Reference: ``simulation/mpi/fedgan`` (``gan_trainer.py:11`` trains netd +
netg per client with BCE; ``FedGANAggregator`` FedAvg-aggregates BOTH nets).

TPU-native form: one jitted per-client GAN step — D step on real+fake, G
step through D — scanned over local batches, vmapped over the sampled client
axis; the server aggregate is a weighted tree-mean of the stacked (G, D)
pairs, identical in shape to the FedAvg engine's aggregation.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..arguments import Config
from ..core import pytree as pt, rng
from ..core.flags import cfg_extra
from ..models.gan import Discriminator, Generator
from ..obs.metrics import MetricsLogger


def _bce_logits(logits, target):
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, target))


class FedGANSimulator:
    def __init__(self, cfg: Config, dataset, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.z_dim = int(cfg_extra(cfg, "gan_z_dim"))
        out_shape = tuple(dataset.train_x.shape[1:])
        self.gen = Generator(out_shape=out_shape, z_dim=self.z_dim)
        self.disc = Discriminator()
        self.lr = cfg.learning_rate
        k0 = rng.root_key(cfg.random_seed)
        z0 = jnp.zeros((2, self.z_dim))
        x0 = jnp.zeros((2,) + out_shape)
        self.g_vars = self.gen.init({"params": jax.random.fold_in(k0, 1)}, z0)
        self.d_vars = self.disc.init({"params": jax.random.fold_in(k0, 2)}, x0)
        self.root_key = k0
        self.round_idx = 0
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)

        # stacked per-client data (uniform capacity like the engine)
        counts = np.array([len(ix) for ix in dataset.client_idx])
        cap = int(((counts.max() + cfg.batch_size - 1) // cfg.batch_size) * cfg.batch_size)
        xs = np.zeros((dataset.n_clients, cap) + out_shape, np.float32)
        for i, ix in enumerate(dataset.client_idx):
            reps = np.resize(np.asarray(ix), cap)
            xs[i] = dataset.train_x[reps]
        self._x = jnp.asarray(xs)
        self.counts = jnp.asarray(counts, jnp.float32)
        self._client_fn = jax.jit(jax.vmap(self._local_gan_train, in_axes=(None, None, 0, 0)))

    def _local_gan_train(self, g_vars, d_vars, x, key):
        cfg = self.cfg
        bs = cfg.batch_size
        steps = max(1, x.shape[0] // bs) * max(1, cfg.epochs)
        g_opt = optax.adam(self.lr, b1=0.5)
        d_opt = optax.adam(self.lr, b1=0.5)
        g_state = g_opt.init(g_vars)
        d_state = d_opt.init(d_vars)

        def step(carry, i):
            g_vars, d_vars, g_state, d_state, key = carry
            key, kz1, kz2, kb = jax.random.split(key, 4)
            ix = (jax.random.permutation(kb, x.shape[0]))[:bs]
            real = x[ix]
            z = jax.random.normal(kz1, (bs, self.z_dim))

            def d_loss_fn(dv):
                fake = self.gen.apply(g_vars, z)
                lr_ = _bce_logits(self.disc.apply(dv, real), jnp.ones(bs))
                lf_ = _bce_logits(self.disc.apply(dv, fake), jnp.zeros(bs))
                return lr_ + lf_

            d_loss, d_grad = jax.value_and_grad(d_loss_fn)(d_vars)
            d_up, d_state = d_opt.update(d_grad, d_state, d_vars)
            d_vars = optax.apply_updates(d_vars, d_up)

            z2 = jax.random.normal(kz2, (bs, self.z_dim))

            def g_loss_fn(gv):
                fake = self.gen.apply(gv, z2)
                return _bce_logits(self.disc.apply(d_vars, fake), jnp.ones(bs))

            g_loss, g_grad = jax.value_and_grad(g_loss_fn)(g_vars)
            g_up, g_state = g_opt.update(g_grad, g_state, g_vars)
            g_vars = optax.apply_updates(g_vars, g_up)
            return (g_vars, d_vars, g_state, d_state, key), (d_loss, g_loss)

        (g_vars, d_vars, _, _, _), (d_losses, g_losses) = jax.lax.scan(
            step, (g_vars, d_vars, g_state, d_state, key), jnp.arange(steps)
        )
        return g_vars, d_vars, d_losses.mean(), g_losses.mean()

    def run_round(self) -> dict:
        cfg = self.cfg
        r = self.round_idx
        n = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n, m))
        rkey = rng.round_key(self.root_key, r)
        keys = jnp.stack([rng.client_key(rkey, int(c)) for c in sampled])
        g_stack, d_stack, d_loss, g_loss = self._client_fn(
            self.g_vars, self.d_vars, self._x[sampled], keys
        )
        w = self.counts[sampled]
        w = w / w.sum()

        def wmean(stack):
            return jax.tree_util.tree_map(
                lambda s: jnp.tensordot(w, s, axes=1), stack
            )

        self.g_vars = wmean(g_stack)
        self.d_vars = wmean(d_stack)
        self.round_idx += 1
        return {"d_loss": float(d_loss.mean()), "g_loss": float(g_loss.mean())}

    def sample(self, n: int = 16, seed: int = 0):
        z = jax.random.normal(jax.random.PRNGKey(seed), (n, self.z_dim))
        return self.gen.apply(self.g_vars, z)

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            self.logger.log(metrics)
            history.append(metrics)
        return history
