"""FedNAS — federated neural architecture search.

Reference: ``simulation/mpi/fednas`` (``FedNASAggregator.py:9``: clients
alternate DARTS updates — model weights on the train split, architecture
alphas on the search split — and the server aggregates weights (sample-
weighted) and alphas (uniform ``__update_arch``) separately each round;
after ``comm_round`` rounds the argmax genotype is derived).

TPU-native form: the supernet (``models/darts.py``) keeps alphas inside the
param tree, so one vmapped jitted client function runs both alternating
updates as a scan; aggregation splits the stacked tree into (weights,
alphas) and applies the reference's two rules.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..arguments import Config
from ..core import rng
from ..core.flags import cfg_extra
from ..models.darts import DARTSSuperNet, derive_genotype
from ..obs.metrics import MetricsLogger


class FedNASSimulator:
    def __init__(self, cfg: Config, dataset, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.model = DARTSSuperNet(
            num_classes=dataset.class_num,
            n_cells=int(cfg_extra(cfg, "nas_cells")),
            features=int(cfg_extra(cfg, "nas_features")),
        )
        self.arch_lr = float(cfg_extra(cfg, "nas_arch_lr"))
        k0 = rng.root_key(cfg.random_seed)
        x0 = jnp.zeros((2,) + tuple(dataset.train_x.shape[1:]), jnp.float32)
        self.variables = self.model.init({"params": k0}, x0)
        self.root_key = k0
        self.round_idx = 0
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)

        # stack clients; each client's shard is split train/search half-half
        # (the reference gives each client a train and a validation loader)
        counts = np.array([len(ix) for ix in dataset.client_idx])
        cap = int(((counts.max() + cfg.batch_size - 1) // cfg.batch_size) * cfg.batch_size)
        feat = dataset.train_x.shape[1:]
        xs = np.zeros((dataset.n_clients, cap) + feat, np.float32)
        ys = np.zeros((dataset.n_clients, cap), np.int32)
        for i, ix in enumerate(dataset.client_idx):
            reps = np.resize(np.asarray(ix), cap)
            xs[i], ys[i] = dataset.train_x[reps], dataset.train_y[reps]
        self._x, self._y = jnp.asarray(xs), jnp.asarray(ys)
        self.counts = jnp.asarray(counts, jnp.float32)
        self._client_fn = jax.jit(jax.vmap(self._local_search, in_axes=(None, 0, 0, 0)))

        tx = jnp.asarray(dataset.test_x[: 512])
        ty = jnp.asarray(dataset.test_y[: 512])
        self._eval = jax.jit(lambda v: jnp.mean(
            (jnp.argmax(self.model.apply(v, tx, train=False), -1) == ty).astype(jnp.float32)
        ))

    def _split_wa(self, params):
        w = {k: v for k, v in params["params"].items() if k != "alphas"}
        return w, params["params"]["alphas"]

    def _local_search(self, variables, x, y, key):
        """Alternating DARTS updates: weight step on the first half batches,
        alpha step on the second half (first-order DARTS)."""
        cfg = self.cfg
        bs = cfg.batch_size
        half = x.shape[0] // 2
        steps = max(1, half // bs) * max(1, cfg.epochs)
        w_opt = optax.sgd(cfg.learning_rate, momentum=0.9)
        a_opt = optax.adam(self.arch_lr)
        params = variables["params"]
        w_state = w_opt.init(params)
        a_state = a_opt.init(params)

        def ce(p, xb, yb):
            logits = self.model.apply({"params": p}, xb, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

        def mask_tree(tree, alphas_on: bool):
            return jax.tree_util.tree_map_with_path(
                lambda path, g: g if (("alphas" in jax.tree_util.keystr(path)) == alphas_on) else jnp.zeros_like(g),
                tree,
            )

        def step(carry, i):
            params, w_state, a_state, key = carry
            key, kw, ka = jax.random.split(key, 3)
            iw = jax.random.randint(kw, (bs,), 0, half)
            ia = jax.random.randint(ka, (bs,), half, x.shape[0])
            # weight step (alphas frozen)
            lw, gw = jax.value_and_grad(ce)(params, x[iw], y[iw])
            up, w_state2 = w_opt.update(mask_tree(gw, False), w_state, params)
            params = optax.apply_updates(params, up)
            # alpha step on the search split (weights frozen)
            la, ga = jax.value_and_grad(ce)(params, x[ia], y[ia])
            up_a, a_state2 = a_opt.update(mask_tree(ga, True), a_state, params)
            params = optax.apply_updates(params, up_a)
            return (params, w_state2, a_state2, key), (lw, la)

        (params, _, _, _), (lw, la) = jax.lax.scan(
            step, (params, w_state, a_state, key), jnp.arange(steps)
        )
        return params, lw.mean(), la.mean()

    def run_round(self) -> dict:
        cfg = self.cfg
        r = self.round_idx
        n = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n, m))
        rkey = rng.round_key(self.root_key, r)
        keys = jnp.stack([rng.client_key(rkey, int(c)) for c in sampled])
        stacked, lw, la = self._client_fn(self.variables, self._x[sampled], self._y[sampled], keys)
        w = self.counts[sampled]
        w = w / w.sum()
        m_uniform = jnp.full_like(w, 1.0 / w.shape[0])

        def agg(s, weights):
            return jax.tree_util.tree_map(lambda t: jnp.tensordot(weights, t, axes=1), s)

        # reference: weights sample-weighted, alphas uniform (__update_arch)
        new_params = agg({k: v for k, v in stacked.items() if k != "alphas"}, w)
        new_alphas = agg(stacked["alphas"], m_uniform)
        self.variables = {"params": {**new_params, "alphas": new_alphas}}
        self.round_idx += 1
        return {"train_loss": float(lw.mean()), "arch_loss": float(la.mean())}

    def genotype(self):
        return derive_genotype(self.variables["params"]["alphas"])

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0,
                           test_acc=float(self._eval(self.variables)))
            self.logger.log(metrics)
            history.append(metrics)
        self.logger.log({"genotype": str(self.genotype())})
        return history
