"""Hierarchical FL — groups run sub-rounds, then a global aggregate.

Reference: ``simulation/sp/hierarchical_fl/`` (``trainer.py:10`` — each group
performs ``group_comm_round`` FedAvg sub-rounds over its members, then groups
are averaged globally) and the cross-silo hierarchical topology (SURVEY.md
§2.14 P5: intra-silo DP x inter-silo FL).

TPU-native form: group membership is a static (n_clients,) -> group map; a
global round is

    scan over sub-rounds:
        vmap local SGD over all sampled clients       (clients mesh axis)
        segment-weighted group means  (jax.ops.segment_sum — the intra-group
        "silo aggregation" collective)
    weighted mean over groups                          (global aggregate)

On a 2-D (silo, data) mesh the segment reduction rides the intra-silo ICI
axis and only the final group mean crosses silos (DCN) — the same traffic
shape as the reference's torchrun-DDP-inside + MQTT-across layout.
"""

from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import hparams_from_config
from ..arguments import Config
from ..core import aot as aotlib, pytree as pt, rng
from ..core.flags import cfg_extra
from ..data.dataset import pad_eval_set, stack_clients
from ..fl.local_sgd import make_eval_fn, make_local_train_fn
from ..obs.metrics import MetricsLogger
from ..parallel import mesh as meshlib

# THE shared pieces between this simulator and the protocol tree
# (cross_silo/edge.py): the round-robin group map and the weighted group
# sums.  Sharing them at SOURCE level (not just by convention) is what lets
# the parity-bridge test pin the two hierarchies to each other bitwise.
from ..cross_silo.edge import round_robin_groups


def segment_group_sums(leaf, w_sel, g_sel, num_groups: int):
    """Per-group weighted sums ``sum_c w_c * x_c`` of one stacked leaf —
    the sim-side twin of the protocol edge fold (an EdgePartialFold's
    partial is exactly one group's row of this, computed arrival-by-arrival
    instead of by segment reduction).  f32 multiply then segment add, the
    same IEEE ops as ``stream_fold.fold_leaf``."""
    wleaf = leaf.astype(jnp.float32) * w_sel.reshape((-1,) + (1,) * (leaf.ndim - 1))
    return jax.ops.segment_sum(wleaf, g_sel, num_segments=num_groups)


class HierarchicalSimulator:
    def __init__(self, cfg: Config, dataset, model, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        n = dataset.n_clients
        self.group_num = max(1, int(cfg.group_num))
        self.group_comm_round = max(1, int(cfg.group_comm_round))

        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        # Group assignment: "balanced" (default) uses the fedavg_seq
        # min-makespan scheduler to equalize total samples per group — with
        # ragged Dirichlet shards, round-robin groups can differ by 10x in
        # total work.  "round_robin" keeps the reference's even partition of
        # the client list (hierarchical_fl trainer.py:10).
        assignment_mode = cfg_extra(cfg, "group_assignment")
        if assignment_mode == "balanced":
            from ..sched.seq_scheduler import SeqTrainScheduler

            sched = SeqTrainScheduler(np.asarray(stacked.counts, np.float64), self.group_num).schedule_lpt()
            group_of = np.empty(n, np.int32)
            for g, members in enumerate(sched.assignment):
                group_of[np.asarray(members, np.int64)] = g
            self.group_of = jnp.asarray(group_of)
        else:
            # the same partition build_topology's fanout default produces,
            # by construction (shared helper)
            self.group_of = jnp.asarray(round_robin_groups(n, self.group_num))
        spe = max(1, math.ceil(stacked.capacity / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        self._local_train = make_local_train_fn(model, self.hp)
        self.mesh = mesh if mesh is not None else meshlib.mesh_from_config(cfg)

        k0 = rng.root_key(cfg.random_seed)
        sample_x = jnp.asarray(stacked.x[0, : cfg.batch_size])
        self.global_vars = model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            sample_x, train=True,
        )
        self._data = tuple(
            meshlib.shard_leading_axis((jnp.asarray(stacked.x), jnp.asarray(stacked.y)), self.mesh)
        )
        self.counts = jnp.asarray(stacked.counts)
        self.root_key = k0
        self.round_idx = 0

        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self._eval_fn = jax.jit(make_eval_fn(model, self.hp, batch_size=eval_bs))
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        # AOT program store (extra.aot_programs): this round program was the
        # single biggest recurring compile in the multichip dryrun (1236 s on
        # a 1-core box) — a warm process deserializes the export instead of
        # re-tracing the scan-of-sub-rounds.  Unset -> the exact old jit.
        self._aot = aotlib.store_from_config(cfg, trail=self.logger.log)
        round_fn = self._make_round_fn()
        if self._aot is not None:
            example = (self.global_vars, self._data[0], self._data[1],
                       self.counts, jnp.int32(0), self.root_key)
            self._round_fn = self._aot.cached_jit(
                round_fn, example,
                key=aotlib.program_key(
                    "sim.hierarchical_round", mesh=self.mesh,
                    trees={"args": example}, hparams=self.hp,
                    config=aotlib.config_signature(cfg),
                    extra={"groups": self.group_num,
                           "sub_rounds": self.group_comm_round}),
            )
        else:
            self._round_fn = jax.jit(round_fn)

    def _make_round_fn(self):
        G = self.group_num
        group_of = self.group_of
        sub_rounds = self.group_comm_round
        n_total = int(self.dataset.n_clients)
        # honor client_num_per_round: each sub-round samples m clients globally
        # (the reference hierarchical_fl samples per group per round — a
        # slightly different distribution: here a group can sit out a sub-round
        # when none of its members are drawn, in which case it keeps its model);
        # m == n_total short-circuits to the gather-free full-participation path
        m = min(max(1, int(self.cfg.client_num_per_round)), n_total)
        full = m == n_total

        def round_fn(global_vars, data_x, data_y, counts, round_idx, key):
            n = counts.shape[0]
            rkey = rng.round_key(key, round_idx)
            weights = counts.astype(jnp.float32)
            # group models start from the global model
            group_vars = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (G,) + x.shape), global_vars
            )

            def sub_round(group_vars, s):
                skey = jax.random.fold_in(rkey, s)
                if full:
                    idx = jnp.arange(n)
                    g_sel, w_sel = group_of, weights
                    sel_x, sel_y, sel_c = data_x, data_y, counts
                else:
                    idx = rng.sample_clients(skey, s, n_total, m)
                    g_sel = jnp.take(group_of, idx)
                    w_sel = jnp.take(weights, idx)
                    sel_x = jnp.take(data_x, idx, axis=0)
                    sel_y = jnp.take(data_y, idx, axis=0)
                    sel_c = jnp.take(counts, idx)
                keys = jax.vmap(lambda i: rng.client_key(skey, i))(idx)
                # each sampled client trains from ITS group's current model
                my_model = pt.tree_take(group_vars, g_sel)
                trained, metrics = jax.vmap(
                    lambda v, x, y, c, k: self._local_train(v, x, y, c, k, None)
                )(my_model, sel_x, sel_y, sel_c, keys)
                # per-group sample-weighted mean over sampled members; a group
                # with no sampled client keeps its current model
                wsum = jax.ops.segment_sum(w_sel, g_sel, num_segments=G)

                def red(leaf, old):
                    sgm = segment_group_sums(leaf, w_sel, g_sel, G)
                    mean = sgm / jnp.maximum(wsum, 1e-12).reshape((-1,) + (1,) * (sgm.ndim - 1))
                    keep = (wsum > 0).reshape((-1,) + (1,) * (sgm.ndim - 1))
                    return jnp.where(keep, mean, old.astype(jnp.float32)).astype(old.dtype)

                new_groups = jax.tree_util.tree_map(red, trained, group_vars)
                return new_groups, metrics

            group_vars, metrics = jax.lax.scan(sub_round, group_vars, jnp.arange(sub_rounds))
            # global aggregate: group means weighted by group sample mass
            wsum = jax.ops.segment_sum(weights, group_of, num_segments=G)
            new_global = pt.tree_weighted_mean(group_vars, wsum)
            round_metrics = {k: jnp.mean(v) for k, v in metrics.items()}
            return new_global, round_metrics

        return round_fn

    def run_round(self) -> dict:
        self.global_vars, metrics = self._round_fn(
            self.global_vars, self._data[0], self._data[1], self.counts,
            jnp.int32(self.round_idx), self.root_key,
        )
        self.round_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self) -> dict:
        return {k: float(v) for k, v in self._eval_fn(self.global_vars, *self._test).items()}

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (r + 1) % self.cfg.frequency_of_the_test == 0 or r == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
        return history
