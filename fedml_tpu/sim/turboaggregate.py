"""Turbo-Aggregate — multi-group ring aggregation with additive masking.

Reference: ``simulation/sp/turboaggregate/TA_trainer.py:12`` — NOTE that the
reference's ``TA_topology_vanilla`` (:109) is an empty stub (``pass``): its
"TurboAggregate" actually performs plain FedAvg with per-client dropout
flags.  This module implements the ACTUAL Turbo-Aggregate protocol (So,
Guler, Avestimehr 2021) the reference names:

- clients are partitioned into L groups arranged in a ring;
- each group's clients send their additively-masked models to the next
  group, which accumulates the running partial sum; the random masks are
  also forwarded and cancel telescopically at the final hop;
- a dropped client's contribution is recovered from the group-level
  redundancy (here: the surviving group members re-weight, the reference
  paper uses Lagrange coding — the fedml_tpu LightSecAgg stack already
  provides that machinery for the cross-silo platform).

The ring arithmetic runs in float on stacked trees (one tensordot per hop);
the property tested is that no single group observes an individual model in
the clear — only noise-masked models and running partial sums.  The masks are
float Gaussians at a fixed scale, so this is masking-within-noise (finite
SNR), NOT the information-theoretic guarantee of uniform finite-field masks;
for that, the cross-silo LightSecAgg stack (trust/secagg) is the real
protocol — this simulator mirrors the reference's float TA topology.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import create as create_algorithm, hparams_from_config
from ..arguments import Config
from ..core import pytree as pt, rng
from ..core.flags import cfg_extra
from ..data.dataset import pad_eval_set, stack_clients
from ..fl.local_sgd import make_eval_fn, make_local_train_fn
from ..obs.metrics import MetricsLogger


class TurboAggregateSimulator:
    def __init__(self, cfg: Config, dataset, model, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        self.n_groups = max(2, int(cfg_extra(cfg, "ta_group_num")))
        self.dropout_prob = float(cfg_extra(cfg, "ta_dropout_prob"))

        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        spe = max(1, -(-stacked.capacity // cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        self._local_train = jax.jit(jax.vmap(make_local_train_fn(model, self.hp),
                                             in_axes=(None, 0, 0, 0, 0, None)))
        k0 = rng.root_key(cfg.random_seed)
        self.global_vars = model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            jnp.asarray(stacked.x[0, : cfg.batch_size]), train=True,
        )
        self._x = jnp.asarray(stacked.x)
        self._y = jnp.asarray(stacked.y)
        self.counts = jnp.asarray(stacked.counts)
        self.root_key = k0
        self.round_idx = 0
        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self._eval_fn = jax.jit(make_eval_fn(model, self.hp, batch_size=eval_bs))
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        # audit trail for the privacy test: flat vectors each group observed
        self.observed_by_group: list[list[np.ndarray]] = []

    # -- the ring protocol ---------------------------------------------------
    def _ring_aggregate(self, flat_updates: jnp.ndarray, weights: jnp.ndarray,
                        groups: list[np.ndarray], key) -> jnp.ndarray:
        """Weighted sum over clients via the masked group ring.  flat_updates:
        (m, d) client-weighted contributions w_i * u_i."""
        d = flat_updates.shape[1]
        running = jnp.zeros(d)
        mask_sum = jnp.zeros(d)
        self.observed_by_group = []
        for g, members in enumerate(groups):
            if len(members) == 0:
                self.observed_by_group.append([])
                continue
            gkey = jax.random.fold_in(key, g)
            masks = jax.random.normal(
                jax.random.fold_in(gkey, 7), (len(members), d)
            ) * 10.0  # mask scale >> update scale
            masked = flat_updates[np.asarray(members)] * weights[np.asarray(members), None] + masks
            # next group in the ring receives ONLY masked models + the
            # running partial sum (records kept for the audit test)
            self.observed_by_group.append(
                [np.asarray(v) for v in masked] + [np.asarray(running)]
            )
            running = running + masked.sum(axis=0)
            mask_sum = mask_sum + masks.sum(axis=0)
        # final hop: the server removes the telescoped mask total
        return running - mask_sum

    def run_round(self) -> dict:
        cfg = self.cfg
        r = self.round_idx
        n = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n, m))
        rkey = rng.round_key(self.root_key, r)
        keys = jnp.stack([rng.client_key(rkey, int(c)) for c in sampled])
        new_vars, metrics = self._local_train(
            self.global_vars, self._x[sampled], self._y[sampled], self.counts[sampled], keys, None
        )
        _, unravel = pt.tree_flatten_to_vector(
            jax.tree_util.tree_map(lambda s: s[0], new_vars)
        )
        mat = jnp.stack([
            pt.tree_flatten_to_vector(jax.tree_util.tree_map(lambda s, i=i: s[i], new_vars))[0]
            for i in range(m)
        ])
        # per-client dropout (the reference TA_Client.set_dropout flag)
        drop_rng = np.random.RandomState(1000 + r)
        alive = drop_rng.rand(m) >= self.dropout_prob
        if not alive.any():
            alive[0] = True
        w = np.asarray(self.counts[sampled], np.float64) * alive
        w = jnp.asarray(w / w.sum(), jnp.float32)
        groups = np.array_split(np.flatnonzero(alive), self.n_groups)
        agg_flat = self._ring_aggregate(mat, w, groups, jax.random.fold_in(rkey, 0x7A))
        self.global_vars = unravel(agg_flat)
        self.round_idx += 1
        out = {k: float(np.mean(v)) for k, v in metrics.items()}
        out["alive"] = int(alive.sum())
        return out

    def evaluate(self) -> dict:
        return {k: float(v) for k, v in self._eval_fn(self.global_vars, *self._test).items()}

    def run(self) -> list[dict]:
        history = []
        cfg = self.cfg
        for r in range(cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if cfg.frequency_of_the_test and (
                (r + 1) % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
        return history
