"""Vertical (feature-partitioned) federated learning.

Reference: ``simulation/sp/classical_vertical_fl/`` (``vfl_api.py`` — a host
party holding labels + guest parties holding disjoint feature slices; guests
compute embeddings, the host combines them into the prediction; gradients
flow back through the embedding exchange) and the VFL models
``model/finance/vfl_*.py`` (lending-club / NUS-WIDE tabular tasks).

TPU-native form: the embedding exchange is autodiff through a composed
program — party bottoms are vmapped over a stacked party axis (each party's
model applied to its feature slice), the host top consumes the concatenated
embeddings, and one ``jax.grad`` performs what the reference does with manual
forward/backward message passing between party objects.
"""

from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ..algorithms import hparams_from_config
from ..arguments import Config
from ..core import rng
from ..core.flags import cfg_extra
from ..obs.metrics import MetricsLogger


class PartyBottom(nn.Module):
    embed_dim: int = 16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(self.embed_dim)(x)


class HostTop(nn.Module):
    num_classes: int = 2

    @nn.compact
    def __call__(self, h, train: bool = True):
        h = nn.relu(h)
        h = nn.Dense(32)(h)
        h = nn.relu(h)
        return nn.Dense(self.num_classes)(h)


class VFLSimulator:
    """K parties over a feature-partitioned dataset; joint SGD per round."""

    def __init__(self, cfg: Config, dataset, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.n_parties = max(2, int(cfg_extra(cfg, "vfl_party_num") or 2))
        x = dataset.train_x.reshape(dataset.train_x.shape[0], -1).astype(np.float32)
        tx = dataset.test_x.reshape(dataset.test_x.shape[0], -1).astype(np.float32)
        d = x.shape[1]
        # equal feature slices (pad feature dim to a multiple of n_parties)
        pad = (-d) % self.n_parties
        if pad:
            x = np.concatenate([x, np.zeros((x.shape[0], pad), np.float32)], axis=1)
            tx = np.concatenate([tx, np.zeros((tx.shape[0], pad), np.float32)], axis=1)
        self.slice_w = x.shape[1] // self.n_parties
        # (parties, N, slice) layout
        self.train_x = jnp.asarray(x.reshape(x.shape[0], self.n_parties, self.slice_w).transpose(1, 0, 2))
        self.test_x = jnp.asarray(tx.reshape(tx.shape[0], self.n_parties, self.slice_w).transpose(1, 0, 2))
        self.train_y = jnp.asarray(dataset.train_y)
        self.test_y = jnp.asarray(dataset.test_y)

        spe = max(1, math.ceil(x.shape[0] / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        embed = int(cfg_extra(cfg, "vfl_embed_dim") or 16)
        self.bottom = PartyBottom(embed_dim=embed)
        self.top = HostTop(num_classes=dataset.class_num)

        k0 = rng.root_key(cfg.random_seed)
        one_b = self.bottom.init({"params": jax.random.fold_in(k0, 1)}, self.train_x[0, : cfg.batch_size])
        self.party_vars = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p[None], (self.n_parties,) + p.shape).copy(), one_b
        )
        h0 = jnp.zeros((cfg.batch_size, self.n_parties * embed))
        self.top_vars = self.top.init({"params": jax.random.fold_in(k0, 2)}, h0)
        self.root_key = k0
        self.round_idx = 0
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        self._round_fn = jax.jit(self._make_round_fn())
        self._eval_fn = jax.jit(self._eval)

    def _forward(self, party_vars, top_vars, xb):
        # xb: (parties, batch, slice) -> embeddings (parties, batch, e)
        embeds = jax.vmap(lambda v, x: self.bottom.apply(v, x))(party_vars, xb)
        h = jnp.transpose(embeds, (1, 0, 2)).reshape(xb.shape[1], -1)  # concat parties
        return self.top.apply(top_vars, h)

    def _make_round_fn(self):
        hp = self.hp
        opt = optax.sgd(hp.learning_rate, momentum=hp.momentum or None)

        def loss_fn(params, xb, yb):
            pv, tv = params
            logits = self._forward(pv, tv, xb).astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

        grad_fn = jax.value_and_grad(loss_fn)

        def round_fn(party_vars, top_vars, round_idx, key):
            rkey = rng.round_key(key, round_idx)
            params = (party_vars, top_vars)
            opt_state = opt.init(params)
            n = self.train_y.shape[0]

            def step(c, s):
                params, opt_state = c
                perm = jax.random.permutation(jax.random.fold_in(rkey, s // hp.steps_per_epoch), n)
                start = (s % hp.steps_per_epoch) * hp.batch_size
                idx = jax.lax.dynamic_slice_in_dim(
                    jnp.concatenate([perm, perm[: hp.batch_size]]), start, hp.batch_size
                )
                xb = jnp.take(self.train_x, idx, axis=1)
                yb = jnp.take(self.train_y, idx, axis=0)
                loss, g = grad_fn(params, xb, yb)
                u, opt_state = opt.update(g, opt_state, params)
                return (optax.apply_updates(params, u), opt_state), loss

            (params, _), losses = jax.lax.scan(step, (params, opt_state), jnp.arange(hp.local_steps))
            pv, tv = params
            return pv, tv, {"train_loss": jnp.mean(losses)}

        return round_fn

    def _eval(self, party_vars, top_vars):
        logits = self._forward(party_vars, top_vars, self.test_x)
        acc = jnp.mean((jnp.argmax(logits, -1) == self.test_y).astype(jnp.float32))
        return {"test_acc": acc}

    def run_round(self) -> dict:
        self.party_vars, self.top_vars, metrics = self._round_fn(
            self.party_vars, self.top_vars, jnp.int32(self.round_idx), self.root_key
        )
        self.round_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self) -> dict:
        return {k: float(v) for k, v in self._eval_fn(self.party_vars, self.top_vars).items()}

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (r + 1) % self.cfg.frequency_of_the_test == 0 or r == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
        return history
