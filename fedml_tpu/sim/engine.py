"""MeshSimulator — FL simulation as one sharded, jitted program per round.

This subsumes the reference's three simulation backends (SURVEY.md §2.3):
- SP sequential loop        (``simulation/sp/fedavg/fedavg_api.py:66-177``)
- MPI worker processes      (``simulation/mpi/fedavg/FedAvgAPI.py``)
- NCCL LocalAggregators     (``simulation/nccl/base_framework/common.py:129``)

On TPU there is no actor system: the round IS a compiled function.

    round(global_vars, server_state, client_states, round_idx, key):
      sampled  = permutation-sample m of N client ids        (device-side)
      shards   = gather client data + state by id            (jnp.take)
      outputs  = vmap(algorithm.client_update) over clients  (sharded on mesh)
      agg      = hooks(before_agg) -> algorithm.aggregate    (all-reduce)
      global'  = algorithm.server_update(agg)
      states'  = scatter refreshed client states back

The ``clients`` mesh axis shards the vmapped dimension and the stacked client
data/state, so local SGD runs on every chip in parallel and the weighted mean
lowers to one ICI all-reduce — the reference's whole process/message machinery
(bullets P1-P3 of SURVEY.md §2.14) collapses into sharding annotations.

``backend="sp"`` runs the same pure functions in a host loop over clients
(one jitted client_update at a time) — the numerics-regression twin of the
reference's single-process simulator; tests assert MESH == SP.
"""

from __future__ import annotations

import math
import time
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import constants as C
from ..algorithms import create as create_algorithm, hparams_from_config
from ..analysis import tracesan
from ..arguments import Config
from ..core import aot as aotlib, pytree as pt, rng
from ..core.flags import cfg_extra
from ..data.dataset import FederatedDataset, StackedClientData, pad_eval_set, stack_clients
from ..fl.local_sgd import make_eval_fn
from ..parallel import mesh as meshlib
from ..obs import otlp as obsotlp, registry as obsreg
from ..obs.metrics import MetricsLogger
from ..obs.trace import traced

# measurement substrate for perf work (ISSUE 1): compile vs execute split,
# program-cache hit rate, round/eval wall time — all scrapable via /metrics
ROUND_TIME = obsreg.REGISTRY.histogram(
    "fedml_sim_round_seconds",
    "Per-round wall time (chunk-averaged inside scanned chunks).",
)
CHUNK_COMPILE_TIME = obsreg.REGISTRY.histogram(
    "fedml_sim_chunk_compile_seconds",
    "jit(scan(round)) chunk program compile time.",
)
CHUNK_EXECUTE_TIME = obsreg.REGISTRY.histogram(
    "fedml_sim_chunk_execute_seconds",
    "Scanned-chunk execute wall time (dispatch to host sync, post-compile).",
)
EVAL_TIME = obsreg.REGISTRY.histogram(
    "fedml_sim_eval_seconds",
    "Server-side evaluation wall time.",
)
CHUNK_CACHE = obsreg.REGISTRY.counter(
    "fedml_sim_chunk_cache_total",
    "Scanned-chunk program cache lookups; jit cache hits are the "
    "hit/miss delta over time.",
    labels=("result",),
)
FUSED_BLOCKS = obsreg.REGISTRY.gauge(
    "fedml_sim_fused_blocks",
    "1 when the simulator's model routes conv epilogues through the fused "
    "Pallas BasicBlock kernel (extra.fused_blocks), else 0.",
)
ACHIEVED_FLOPS = obsreg.REGISTRY.gauge(
    "fedml_sim_achieved_flops_per_sec",
    "XLA cost-model FLOPs of the last executed chunk divided by its wall "
    "time (extra.cost_model_gauges).",
)
SIM_MFU = obsreg.REGISTRY.gauge(
    "fedml_sim_mfu",
    "Model FLOP utilization of the last executed chunk: achieved FLOP/s "
    "over the device peak (0 when the device kind has no known peak — "
    "CPU runs report achieved FLOP/s only).  extra.cost_model_gauges.",
)

#: dense peak FLOP/s by TPU generation (bf16 MXU throughput, per chip) —
#: the MFU denominator.  Unlisted device kinds (CPU, GPU backends reached
#: through the portability shim) report MFU 0 rather than a made-up ratio.
_PEAK_FLOPS_BY_KIND = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v4i": 138e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}


def _device_peak_flops() -> float:
    """Aggregate peak FLOP/s across local devices, 0.0 when unknown.  The
    longest matching kind prefix wins so 'TPU v5 lite' beats 'TPU v5'."""
    import jax

    try:
        kind = str(getattr(jax.devices()[0], "device_kind", ""))
        per_chip = 0.0
        best = -1
        for k, v in _PEAK_FLOPS_BY_KIND.items():
            if kind.lower().startswith(k.lower()) and len(k) > best:
                per_chip, best = v, len(k)
        return per_chip * jax.device_count()
    except Exception:
        return 0.0


from ..core.checkpoint import RoundCheckpointMixin


class MeshSimulator(RoundCheckpointMixin):
    #: optional GangScheduler hook (cross_silo/runtime.py): when the
    #: multi-tenant control plane attaches one, each population cohort
    #: round requests a slot/lease before touching the mesh and releases
    #: it after the round commits — the same round-boundary arbitration
    #: the cross-silo servers use.  None (the default) = ungated,
    #: bit-identical to before the hook existed.
    round_gate = None

    def __init__(
        self,
        cfg: Config,
        dataset: FederatedDataset,
        model,
        algorithm=None,
        mesh=None,
        trust=None,
        logger: Optional[MetricsLogger] = None,
    ):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        self.backend = cfg.backend_sim if cfg.backend_sim else C.SIMULATION_BACKEND_MESH
        if trust is None:
            from ..trust.pipeline import build_trust_pipeline

            trust = build_trust_pipeline(cfg)
        self.trust = trust
        if trust is not None and trust.attacker is not None and trust.attacker.is_data_attack():
            dataset = trust.attacker.poison_data(dataset)
            self.dataset = dataset
        self.logger = logger or MetricsLogger(cfg.metrics_jsonl_path or None)
        # ahead-of-time program store (extra.aot_programs, ISSUE 7): the
        # scanned-chunk / population-round / eval programs are
        # jax.export-serialized under a tracing fingerprint so a restarted
        # server deserializes instead of re-tracing.  Flag unset -> None and
        # every jit below runs the exact pre-store path (bit-identical).
        self._aot = aotlib.store_from_config(cfg, trail=self.logger.log)
        # cost-model gauges (ISSUE 16 satellite): per-program flops/bytes at
        # compile, achieved-FLOP/s + MFU per executed chunk.  Flag unset ->
        # zero extra work on any hot path.
        self._cost_gauges = bool(cfg_extra(cfg, "cost_model_gauges"))
        self._chunk_flops: dict = {}
        # per-program device-time attribution (ISSUE 18, obs/profiler.py):
        # a programmatic trace window around rounds k..k+n behind
        # extra.profile_rounds.  Flag unset -> None, no trace, no window.
        from ..obs import profiler as obsprofiler

        self.profiler = obsprofiler.profiler_from_config(
            cfg, name="sim", peak_flops=_device_peak_flops() or None)

        # ---- data: pad + stack, shard over the clients axis ----
        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        self.capacity = stacked.capacity
        steps_per_epoch = max(1, math.ceil(self.capacity / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=steps_per_epoch)
        self.algorithm = (algorithm or create_algorithm(cfg, self.hp)).build(model)

        # which kernel path this run's model uses (fused Pallas epilogues vs
        # plain XLA loop fusions) — scrapable next to the round timings so an
        # A/B pair of runs is attributable from /metrics alone
        FUSED_BLOCKS.set(1.0 if getattr(model, "fused", False) else 0.0)

        self.mesh = mesh if mesh is not None else meshlib.mesh_from_config(cfg)
        # Client-axis padding (SURVEY §7 hard-part 2): stacks whose leading
        # (client) dim is not a multiple of the mesh axis would REPLICATE
        # (shard_leading_axis's correctness fallback) and serialize all client
        # compute.  Pad the stack with zero-count dummy rows instead; dummies
        # are never sampled (sampling stays over n_clients) and never
        # scattered to, so numerics are untouched.
        self._client_axis, self._lane_multiple = self._client_axis_info()
        self._n_real = dataset.n_clients
        self._n_pad = meshlib.round_up(self._n_real, self._lane_multiple)
        if self._n_pad > self._n_real:
            stacked = StackedClientData(
                x=meshlib.pad_leading_axis_np(stacked.x, self._n_pad),
                y=meshlib.pad_leading_axis_np(stacked.y, self._n_pad),
                counts=meshlib.pad_leading_axis_np(stacked.counts, self._n_pad),
            )
        self._data = self._place_data(stacked)
        # replicate ONCE at init: a bare jnp.asarray stays single-device and
        # every mesh dispatch would re-reshard it device-to-device per call
        # (witnessed by TRACESAN's round guard)
        self.counts = (jnp.asarray(stacked.counts)
                       if self.backend == C.SIMULATION_BACKEND_SP
                       else meshlib.replicate(stacked.counts, self.mesh))

        # ---- model/state init ----
        k0 = rng.root_key(cfg.random_seed)
        sample_x = jnp.asarray(stacked.x[0, : cfg.batch_size])
        self.global_vars = self.model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            sample_x, train=True,
        )
        self.global_vars = meshlib.replicate(jax.device_get(self.global_vars), self.mesh)
        self.server_state = self.algorithm.init_server_state(self.global_vars)
        cs_template = self.algorithm.init_client_state(self.global_vars)
        if cs_template is not None:
            n = self._n_pad  # dummy rows are never gathered or scattered
            stacked_cs = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), cs_template
            )
            self.client_states = meshlib.shard_leading_axis(stacked_cs, self.mesh)
        else:
            self.client_states = None

        # ---- test data (tiled to eval batch multiple) ----
        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_test = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_test))
        self._eval_bs = eval_bs  # the padding multiple of self._test
        eval_fn = make_eval_fn(model, self.hp, batch_size=eval_bs)
        if self._aot is not None:
            self._eval_fn = self._aot.cached_jit(
                eval_fn, (self.global_vars, *self._test),
                key=self._aot_key("sim.eval", trees={
                    "global_vars": self.global_vars, "test": self._test}),
            )
        else:
            self._eval_fn = jax.jit(eval_fn)

        # OTLP egress (gated on extra.otlp_endpoint; None -> spans keep
        # their no-sink default and no exporter thread exists): the
        # simulator's chunk/eval spans flow to the same collector the
        # cross-silo server exports to
        self._otlp = obsotlp.exporter_from_config(cfg)
        self._otlp_sink = self._otlp.enqueue_span if self._otlp is not None else None

        # replicated at init for the same reason as counts: the key is a
        # per-dispatch argument of every mesh round program
        self.root_key = self._stage_scalar(k0)
        self.round_idx = 0
        # history for cross-round defenses: flat global delta of the previous
        # round, threaded through the jitted round as a real argument (a
        # captured attribute would be baked in at trace time)
        if self.trust is not None and self.trust.needs_history:
            flat, _ = pt.tree_flatten_to_vector(self.global_vars)
            self.defense_history = jnp.zeros_like(flat)
        else:
            self.defense_history = None
        self._round_fn = jax.jit(self._make_round_fn()) if self.backend != C.SIMULATION_BACKEND_SP else None
        self._client_fn_sp = jax.jit(self._sp_client_update) if self.backend == C.SIMULATION_BACKEND_SP else None
        # scanned multi-round programs, keyed by chunk length (one compile per
        # distinct length); see run_rounds
        self._multi_round_fns: dict[int, Callable] = {}

        # -- population mode (extra.population_store): stream per-round
        # cohorts from the sharded on-disk store instead of sampling the
        # device-resident stack.  Everything above stays as-is — the base
        # dataset is small by construction (the store replicates it across
        # the population) and the default path is untouched when unset.
        self._population = None
        pop_root = cfg_extra(cfg, "population_store")
        if pop_root:
            if self.backend == C.SIMULATION_BACKEND_SP:
                raise ValueError(
                    "population_store streams cohorts into the vmapped MESH "
                    "round; it has no meaning on the SP host loop")
            self._init_population(str(pop_root), stacked)

    # ------------------------------------------------------------------
    def _client_axis_info(self) -> tuple[str, int]:
        """(axis name, axis size) the stacked-client dim shards over; size 1
        on the SP backend (no padding needed for a host loop)."""
        if self.backend == C.SIMULATION_BACKEND_SP:
            return meshlib.AXIS_CLIENTS, 1
        axis = (meshlib.AXIS_CLIENTS if meshlib.AXIS_CLIENTS in self.mesh.shape
                else self.mesh.axis_names[0])
        return axis, int(self.mesh.shape[axis])

    def _pad_lanes(self, sampled, m: int, m_pad: int):
        """Extend the sampled id vector with client-0 lanes up to the mesh
        multiple.  Pad lanes redo client 0's local SGD (same cost as an idle
        replicated lane, but the real lanes stay sharded); their outputs are
        sliced away before the server path, so aggregation, trust hooks and
        metrics see exactly the real ``m`` clients."""
        if m_pad == m:
            return sampled
        return jnp.concatenate([sampled, jnp.zeros(m_pad - m, jnp.int32)])

    def _constrain_lanes(self, tree):
        """Pin the vmapped-client dim to the clients axis — GSPMD would
        otherwise be free to replicate the gathered per-lane operands."""
        if self._lane_multiple <= 1 or tree is None:
            return tree
        mesh, axis = self.mesh, self._client_axis
        return jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
            ),
            tree,
        )

    @staticmethod
    def _slice_lanes(tree, m: int):
        return jax.tree_util.tree_map(lambda a: a[:m], tree)

    def _gather_round_inputs(self, sampled, m, m_pad, counts, data_x, data_y,
                             client_states, key, round_idx):
        """Shared per-round gather: pad the sampled ids to the lane multiple,
        pull each lane's data/state/count/key, and pin the lane dim to the
        clients axis.  Both the FedAvg-family round and the MyAvg round use
        this verbatim — lane handling must never diverge between them."""
        lanes = self._pad_lanes(sampled, m, m_pad)
        xs = self._constrain_lanes(jnp.take(data_x, lanes, axis=0))
        ys = self._constrain_lanes(jnp.take(data_y, lanes, axis=0))
        cnts = jnp.take(counts, lanes)
        cs = self._constrain_lanes(
            pt.tree_take(client_states, lanes) if client_states is not None else None
        )
        rkey = rng.round_key(key, round_idx)
        keys = jax.vmap(lambda i: rng.client_key(rkey, i))(lanes)
        return xs, ys, cnts, cs, rkey, keys

    # ------------------------------------------------------------------
    def _place_data(self, stacked: StackedClientData):
        x = jnp.asarray(stacked.x)
        if self.hp.compute_dtype == "bfloat16" and jnp.issubdtype(x.dtype, jnp.floating):
            # store device-resident shards in the compute dtype: halves HBM
            # footprint AND the per-round sampled-client gather traffic
            x = x.astype(jnp.bfloat16)
        y = jnp.asarray(stacked.y)
        if self.backend == C.SIMULATION_BACKEND_SP:
            return (x, y)
        return tuple(meshlib.shard_leading_axis((x, y), self.mesh))

    # ------------------------------------------------------------------
    def _make_round_fn(self):
        algo = self.algorithm
        cfg = self.cfg
        n_total = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n_total)

        m_pad = meshlib.round_up(m, self._lane_multiple)

        def round_fn(global_vars, server_state, client_states, counts, data_x, data_y, round_idx, key, prev_delta):
            sampled = rng.sample_clients(key, round_idx, n_total, m)
            xs, ys, cnts, cs, rkey, keys = self._gather_round_inputs(
                sampled, m, m_pad, counts, data_x, data_y, client_states, key, round_idx
            )

            def one_client(cstate, x, y, cnt, k):
                out = algo.client_update(global_vars, cstate, server_state, x, y, cnt, k)
                return out.contribution, out.client_state, out.metrics

            if cs is not None:
                contribs, new_cs, metrics = jax.vmap(one_client, in_axes=(0, 0, 0, 0, 0))(cs, xs, ys, cnts, keys)
            else:
                contribs, new_cs, metrics = jax.vmap(
                    lambda x, y, cnt, k: one_client(None, x, y, cnt, k)
                )(xs, ys, cnts, keys)

            # drop the pad lanes: everything downstream (trust hooks,
            # aggregation, scatter, metrics) sees exactly the real m clients
            contribs = self._slice_lanes(contribs, m)
            new_cs = self._slice_lanes(new_cs, m) if new_cs is not None else None
            metrics = self._slice_lanes(metrics, m)
            weights = cnts[:m].astype(jnp.float32)
            new_global, new_server, new_delta = self._server_path(
                contribs, weights, sampled, global_vars, server_state, rkey, round_idx, prev_delta
            )

            if client_states is not None:
                new_states = jax.tree_util.tree_map(
                    lambda full, upd: full.at[sampled].set(upd), client_states, new_cs
                )
            else:
                new_states = None
            round_metrics = {k: jnp.mean(v) for k, v in metrics.items()}
            return new_global, new_server, new_states, new_delta, round_metrics

        return round_fn

    def _server_path(self, contribs, weights, sampled, global_vars, server_state, rkey, round_idx, prev_delta):
        """Trust hooks + aggregation + server update — shared by the MESH
        round program and the SP host loop, so security semantics are
        backend-independent."""
        algo = self.algorithm
        if self.trust is not None:
            contribs, weights = self.trust.on_client_outputs(
                contribs, weights, sampled, global_vars, rkey
            )
            contribs, weights, agg_override = self.trust.on_aggregation(
                contribs, weights, global_vars, rkey, prev_delta=prev_delta
            )
        else:
            agg_override = None
        agg = agg_override if agg_override is not None else algo.aggregate(contribs, weights)
        new_global, new_server = algo.server_update(global_vars, server_state, agg, round_idx)
        if self.trust is not None:
            new_global = self.trust.on_after_aggregation(new_global, global_vars, rkey)
        new_delta = None
        if prev_delta is not None:
            new_flat, _ = pt.tree_flatten_to_vector(new_global)
            old_flat, _ = pt.tree_flatten_to_vector(global_vars)
            new_delta = new_flat - old_flat
        return new_global, new_server, new_delta

    def _sp_client_update(self, global_vars, cstate, server_state, x, y, cnt, key):
        out = self.algorithm.client_update(global_vars, cstate, server_state, x, y, cnt, key)
        return out.contribution, out.client_state, out.metrics

    # -- population mode (extra.population_store) ----------------------------
    def _init_population(self, root: str, stacked) -> None:
        """Assemble the sharded store + hierarchical sampler + prefetch
        pipeline (fedml_tpu/population/) and the jitted cohort round.  The
        store — not a device stack — is the authority for per-client state
        in this mode, so the device-stacked ``client_states`` is dropped."""
        from types import SimpleNamespace

        from ..population import build_population_components

        cs_template = self.algorithm.init_client_state(self.global_vars)
        state_template = (
            jax.device_get(cs_template) if cs_template is not None else None
        )
        n_real = self._n_real
        store, sampler, pipeline = build_population_components(
            self.cfg, root,
            stacked.x[:n_real], stacked.y[:n_real], stacked.counts[:n_real],
            self.capacity, state_template=state_template,
        )
        m = sampler.cohort_size
        m_pad = meshlib.round_up(m, self._lane_multiple)
        # with the AOT store the cohort round binds lazily at round 0 (the
        # export fingerprint wants the real stacked example args); without it
        # the program is jitted here exactly as before
        self._population = SimpleNamespace(
            store=store, sampler=sampler, pipeline=pipeline,
            m=m, m_pad=m_pad,
            round_fn=(jax.jit(self._make_population_round_fn(m))
                      if self._aot is None else None),
        )
        self.client_states = None  # per-client state lives in the store

    def _make_population_round_fn(self, m: int):
        """The cohort round: same client math, trust hooks, and server path
        as :meth:`_make_round_fn`, but the cohort's data/state arrive as
        stacked arguments (host-gathered from the store) instead of being
        jnp.take'd out of a device-resident population stack, and the
        sampled ids ride in as ``lane_ids`` so per-client RNG keys fold the
        same streams the in-memory path folds."""
        algo = self.algorithm

        def round_fn(global_vars, server_state, cs, cnts, xs, ys, lane_ids,
                     round_idx, key, prev_delta):
            xs = self._constrain_lanes(xs)
            ys = self._constrain_lanes(ys)
            cs = self._constrain_lanes(cs)
            rkey = rng.round_key(key, round_idx)
            keys = jax.vmap(lambda i: rng.client_key(rkey, i))(lane_ids)

            def one_client(cstate, x, y, cnt, k):
                out = algo.client_update(global_vars, cstate, server_state, x, y, cnt, k)
                return out.contribution, out.client_state, out.metrics

            if cs is not None:
                contribs, new_cs, metrics = jax.vmap(one_client, in_axes=(0, 0, 0, 0, 0))(cs, xs, ys, cnts, keys)
            else:
                contribs, new_cs, metrics = jax.vmap(
                    lambda x, y, cnt, k: one_client(None, x, y, cnt, k)
                )(xs, ys, cnts, keys)
            contribs = self._slice_lanes(contribs, m)
            new_cs = self._slice_lanes(new_cs, m) if new_cs is not None else None
            metrics = self._slice_lanes(metrics, m)
            weights = cnts[:m].astype(jnp.float32)
            new_global, new_server, new_delta = self._server_path(
                contribs, weights, lane_ids[:m], global_vars, server_state,
                rkey, round_idx, prev_delta,
            )
            round_metrics = {k: jnp.mean(v) for k, v in metrics.items()}
            return new_global, new_server, new_cs, new_delta, round_metrics

        return round_fn

    @staticmethod
    def _pad_cohort_rows(tree, m_pad: int):
        """Row-repeat lane padding on host arrays: pad lanes replay row 0
        (the same client the padded ID vector repeats); they are sliced away
        on device before aggregation and never scattered back."""
        def pad(a):
            a = np.asarray(a)
            if a.shape[0] >= m_pad:
                return a
            reps = np.concatenate([
                np.arange(a.shape[0]), np.zeros(m_pad - a.shape[0], np.int64)])
            return a[reps]

        return jax.tree_util.tree_map(pad, tree)

    def _run_population_rounds(self, n: int) -> list[dict]:
        """Streamed cohort execution: gather cohort r+1's data on the
        prefetch thread while cohort r runs through the vmapped round, then
        scatter refreshed per-client state back to its shards.  State is
        gathered on the critical path AFTER the previous round's scatter —
        a client sampled in consecutive cohorts must see its fresh state."""
        pop = self._population
        out = []
        for _ in range(n):
            if self.round_gate is not None:
                # cohort rounds arbitrate through the same device-slot
                # scheduler as the cross-silo servers: block this (caller)
                # thread until the slot/lease grant lands, run the round,
                # release at the round boundary
                import threading

                granted = threading.Event()
                self.round_gate.request(self, granted.set)
                granted.wait()
            try:
                out.append(self._run_one_population_round())
            finally:
                if self.round_gate is not None:
                    self.round_gate.release(self)
        # host boundary: the on-disk shards are this mode's checkpointable
        # client state — keep them consistent before eval/checkpoint runs
        pop.store.flush()
        return out

    def _run_one_population_round(self) -> dict:
        """One streamed cohort round (the body :meth:`_run_population_rounds`
        gates); returns the round's host metrics."""
        from ..population.cohorts import CohortPipeline

        pop = self._population
        r = self.round_idx
        t0 = time.perf_counter()
        pop.pipeline.prefetch_round(r)
        ids, batch = pop.pipeline.obtain(r)
        if r + 1 < self.cfg.comm_round:
            pop.pipeline.prefetch_round(r + 1)
        lanes = CohortPipeline.pad_ids(ids, pop.m_pad)
        xs = self._pad_cohort_rows(batch.x, pop.m_pad)
        if self.hp.compute_dtype == "bfloat16" and np.issubdtype(xs.dtype, np.floating):
            import ml_dtypes

            xs = xs.astype(ml_dtypes.bfloat16)
        ys = self._pad_cohort_rows(batch.y, pop.m_pad)
        cs = pop.store.gather_state(ids)
        if cs is not None:
            cs = meshlib.shard_leading_axis(
                self._pad_cohort_rows(cs, pop.m_pad), self.mesh)
        xs, ys = meshlib.shard_leading_axis((xs, ys), self.mesh)
        cnts = jnp.asarray(self._pad_cohort_rows(batch.counts, pop.m_pad))
        args = (
            self.global_vars, self.server_state, cs, cnts, xs, ys,
            jnp.asarray(lanes, jnp.int32), jnp.int32(r), self.root_key,
            self.defense_history,
        )
        if pop.round_fn is None:
            # first cohort with the AOT store: load (or export) the
            # round program — a restarted server skips the re-trace
            raw = self._make_population_round_fn(pop.m)
            pop.round_fn = self._aot.cached_jit(
                raw, args,
                key=self._aot_key("sim.population_round",
                                  trees={"args": args},
                                  extra={"cohort": pop.m}),
            )
        with traced("sim.population_round", round_idx=r, cohort=pop.m,
                    sink=self._otlp_sink):
            with tracesan.round_guard(r):
                gv, ss, new_cs, nd, metrics = pop.round_fn(*args)
            with tracesan.allow("round_metrics"):
                host = {k: float(v) for k, v in metrics.items()}  # graftlint: disable=GL010(annotated measurement site: round-boundary metric export — one scalar-dict sync per cohort round, behind the TRACESAN round_metrics allowlist)
        if new_cs is not None:
            pop.store.scatter_state(ids, new_cs)
        self.global_vars, self.server_state = gv, ss
        if nd is not None:
            self.defense_history = nd
        self.round_idx += 1
        ROUND_TIME.observe(time.perf_counter() - t0)
        return host

    # ------------------------------------------------------------------
    def _aot_key(self, site: str, trees: Optional[dict] = None,
                 extra: Optional[dict] = None) -> str:
        """Program-store fingerprint for one of this simulator's traced
        programs: mesh + argument tree signatures + hparams + the full
        (volatile-stripped) config, so any knob that changes tracing — chunk
        size, fused_blocks, codec/trust flags, donation gating — changes the
        key (see core/aot.py)."""
        return aotlib.program_key(
            site,
            mesh=None if self.backend == C.SIMULATION_BACKEND_SP else self.mesh,
            trees=trees,
            hparams=self.hp,
            config=aotlib.config_signature(self.cfg),
            extra=dict(extra or {}, backend_sim=self.backend),
        )

    def warm_start(self) -> dict:
        """The AOT store's ``warm()`` path: pre-load (or pre-build) every
        scanned-chunk program :meth:`run` will need before round 0, so a
        restarted server's first round pays dispatch, not tracing.  No-op
        without ``extra.aot_programs`` / off the mesh chunk path."""
        if (self._aot is None or self._population is not None
                or self.backend == C.SIMULATION_BACKEND_SP):
            return {"warmed": 0}
        lengths, r = set(), self.round_idx
        while r < self.cfg.comm_round:
            end = self._next_boundary(r)
            lengths.add(end - r)
            r = end
        args = (
            self.global_vars, self.server_state, self.client_states,
            self.counts, self._data[0], self._data[1],
            jnp.int32(self.round_idx), self.root_key, self.defense_history,
        )
        for n in sorted(lengths):
            self._get_multi_round_fn(n, example_args=args)
        return {"warmed": len(lengths)}

    def _get_multi_round_fn(self, n: int, example_args: Optional[tuple] = None):
        """jit(scan(round)) over ``n`` rounds — ONE dispatch and ONE host
        sync per chunk.  On TPU every host<->device round trip is latency
        (and over a tunneled single-chip setup it dominates: per-round metric
        pulls were 3-8x the compute itself); the round loop belongs on the
        device, which is exactly SURVEY.md §7's ``jit(scan(round))`` form.

        With ``example_args`` the chunk is AOT-compiled (lower + compile)
        so compile time is measured separately from execute time.  The
        carried state is donated only off-CPU: executing the donated scanned
        chunk on XLA:CPU (jax 0.4.37) corrupts the heap — the tier-1 suite
        died with wandering segfaults/aborts (device_get, tracing, GC, and
        most reliably when the serialized donated executable was reloaded
        from the persistent compilation cache) until CPU donation was
        dropped.

        With ``extra.aot_programs`` the chunk program comes out of the AOT
        program store (core/aot.py): a warm process deserializes the exported
        StableHLO instead of re-tracing, and the wrapper's compile goes back
        through the persistent compilation cache — safe to re-enable for
        chunk programs because the stored artifact is donation-free (the heap
        corruption above only ever reproduced when a *donated* chunk
        executable was reloaded on XLA:CPU; donation stays CPU-gated on the
        wrapper).  RE-PROBE on a jax upgrade past 0.4.37: lift the CPU
        donation gate under tier-1 — if the wandering segfaults stay gone,
        donate on CPU too and drop this note."""
        fn = self._multi_round_fns.get(n)
        if fn is not None:
            CHUNK_CACHE.inc(result="hit")
            return fn
        CHUNK_CACHE.inc(result="miss")
        round_fn = self._make_round_fn()

        def multi(global_vars, server_state, client_states, counts, data_x, data_y,
                  start_round, key, prev_delta):
            def body(carry, r):
                gv, ss, cs, pd = carry
                ngv, nss, ncs, nd, metrics = round_fn(gv, ss, cs, counts, data_x, data_y, r, key, pd)
                return (ngv, nss, ncs, nd), metrics
            (gv, ss, cs, pd), stacked_metrics = jax.lax.scan(
                body, (global_vars, server_state, client_states, prev_delta),
                start_round + jnp.arange(n, dtype=jnp.int32),
            )
            return gv, ss, cs, pd, stacked_metrics

        # donate the big carried state: the round rewrites params/opt/client
        # stacks in place instead of holding two copies in HBM.  NOT on CPU:
        # donated scan carries corrupt the heap there (see docstring) and
        # host RAM doesn't need the in-place rewrite anyway.
        donate = () if jax.default_backend() == "cpu" else (0, 1, 2, 8)
        jitted = jax.jit(multi, donate_argnums=donate)
        fn = jitted
        if example_args is not None:
            t0 = time.perf_counter()
            prog = None
            if self._aot is not None:
                # store the donation-free export; donation is re-applied on
                # the wrapper below so one artifact serves CPU and TPU
                prog = self._aot.get_or_build(
                    self._aot_key("sim.multi_round",
                                  trees={"args": example_args},
                                  extra={"chunk": n, "donate": list(donate)}),
                    lambda: aotlib.export_program(jax.jit(multi), example_args),
                )
            try:
                with traced("sim.chunk_compile", rounds=n, sink=self._otlp_sink):
                    if prog is not None:
                        fn = prog.bind(example_args, donate_argnums=donate)
                    else:
                        fn = jitted.lower(*example_args).compile()
            except Exception:
                # AOT unsupported for these inputs — the lazy jit still works
                fn = jitted
            CHUNK_COMPILE_TIME.observe(time.perf_counter() - t0)
            if self._cost_gauges:
                cost = aotlib.record_program_cost(fn, f"sim.multi_round.{n}")
                if cost is not None:
                    self._chunk_flops[n] = cost["flops"]
        self._multi_round_fns[n] = fn
        return fn

    def _stage_scalar(self, x):
        """Explicitly place a per-round host scalar with the replicated
        sharding the compiled programs expect.  Staging it deliberately (an
        explicit ``device_put``, outside the TRACESAN round guard) keeps the
        dispatch itself transfer-free — a bare ``jnp.int32`` lands on one
        device and every mesh dispatch re-reshards it device-to-device."""
        if self.backend == C.SIMULATION_BACKEND_SP:
            return x
        return jax.device_put(x, meshlib.replicated(self.mesh))

    def run_rounds(self, n: int) -> list[dict]:
        """Run ``n`` rounds as one compiled program (mesh backend); falls back
        to the host loop per round on the SP backend.  Returns one metrics
        dict per round; the device is synced ONCE, at the end.

        The carried state is DONATED to the chunk (in-place HBM rewrite); if
        the chunk itself fails (OOM, device loss) the simulator's state
        buffers are gone — recover via ``try_resume`` from the last
        checkpoint, not by retrying in-process."""
        if n <= 0:
            return []
        if self._population is not None:
            return self._run_population_rounds(n)
        if self.backend == C.SIMULATION_BACKEND_SP:
            out = []
            for _ in range(n):
                t0 = time.perf_counter()
                out.append(self.run_round())
                ROUND_TIME.observe(time.perf_counter() - t0)
            return out
        args = (
            self.global_vars, self.server_state, self.client_states,
            self.counts, self._data[0], self._data[1],
            self._stage_scalar(jnp.int32(self.round_idx)), self.root_key,
            self.defense_history,
        )
        fn = self._get_multi_round_fn(n, example_args=args)
        if self.profiler is not None:
            self.profiler.maybe_start(self.round_idx)
        t0 = time.perf_counter()
        try:
            with traced("sim.chunk", rounds=n, start_round=self.round_idx,
                        sink=self._otlp_sink):
                with tracesan.round_guard(self.round_idx, rounds=n):
                    gv, ss, cs, nd, stacked = fn(*args)
                with tracesan.allow("round_metrics"):
                    host = jax.device_get(stacked)  # graftlint: disable=GL010(annotated measurement site: THE single explicit host sync for the whole scanned chunk — n rounds of stacked metrics in one transfer)
        except Exception as e:
            if self.profiler is not None:
                self.profiler.finalize()  # keep the trace of the failing chunk
            raise RuntimeError(
                f"scanned chunk of {n} rounds failed at round {self.round_idx}; "
                "carried state was donated and is no longer valid — resume from "
                "the last checkpoint"
            ) from e
        execute_s = time.perf_counter() - t0
        CHUNK_EXECUTE_TIME.observe(execute_s)
        if self.profiler is not None:
            self.profiler.note_program(f"sim.multi_round.{n}",
                                       flops=self._chunk_flops.get(n), rounds=n)
            self.profiler.maybe_stop(self.round_idx + n)
        if self._cost_gauges and self._chunk_flops.get(n):
            achieved = self._chunk_flops[n] / max(execute_s, 1e-9)
            ACHIEVED_FLOPS.set(achieved)
            peak = _device_peak_flops()
            SIM_MFU.set(achieved / peak if peak else 0.0)
        for _ in range(n):
            ROUND_TIME.observe(execute_s / n)
        self.global_vars, self.server_state, self.client_states = gv, ss, cs
        if nd is not None:
            self.defense_history = nd
        self.round_idx += n
        return [{k: float(v[i]) for k, v in host.items()} for i in range(n)]

    # ------------------------------------------------------------------
    def run_round(self) -> dict:
        if self._population is not None:
            return self._run_population_rounds(1)[0]
        r = self.round_idx
        if self.backend == C.SIMULATION_BACKEND_SP:
            metrics = self._run_round_sp(r)
        else:
            # staged OUTSIDE the guard: uploading the round index is an
            # explicit (and replicated — see _stage_scalar) h2d per round
            r_dev = self._stage_scalar(jnp.int32(r))
            with tracesan.round_guard(r):
                gv, ss, cs, nd, metrics = self._round_fn(
                    self.global_vars, self.server_state, self.client_states,
                    self.counts, self._data[0], self._data[1],
                    r_dev, self.root_key, self.defense_history,
                )
            self.global_vars, self.server_state, self.client_states = gv, ss, cs
            if nd is not None:
                self.defense_history = nd
        self.round_idx += 1
        with tracesan.allow("round_metrics"):
            return {k: float(v) for k, v in metrics.items()}  # graftlint: disable=GL010(annotated measurement site: single-round entry point syncs its own metric dict — the chunked path run_rounds amortizes this to one sync per chunk)

    def _run_round_sp(self, r: int) -> dict:
        """Sequential reference twin: same sampling, same per-client keys, same
        aggregate — but a host loop like ``fedavg_api.py:88-103``."""
        cfg = self.cfg
        n_total = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n_total)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n_total, m))
        rkey = rng.round_key(self.root_key, r)
        contribs, new_states, metrics_list = [], [], []
        for ci in sampled:
            k = rng.client_key(rkey, int(ci))
            cs = (
                jax.tree_util.tree_map(lambda s: s[int(ci)], self.client_states)
                if self.client_states is not None else None
            )
            x = self._data[0][int(ci)]
            y = self._data[1][int(ci)]
            contrib, new_cs, mt = self._client_fn_sp(
                self.global_vars, cs, self.server_state, x, y, self.counts[int(ci)], k
            )
            contribs.append(contrib)
            new_states.append(new_cs)
            metrics_list.append(mt)
        stacked = pt.tree_stack(contribs)
        weights = self.counts[sampled].astype(jnp.float32)
        self.global_vars, self.server_state, nd = self._server_path(
            stacked, weights, jnp.asarray(sampled, jnp.int32), self.global_vars,
            self.server_state, rkey, jnp.int32(r), self.defense_history,
        )
        if nd is not None:
            self.defense_history = nd
        if self.client_states is not None and new_states[0] is not None:
            for ci, ncs in zip(sampled, new_states):
                self.client_states = jax.tree_util.tree_map(
                    lambda full, upd: full.at[int(ci)].set(upd), self.client_states, ncs
                )
        stacked_m = pt.tree_stack(metrics_list)
        return {k: jnp.mean(v) for k, v in stacked_m.items()}

    # ------------------------------------------------------------------
    def evaluate(self) -> dict:
        t0 = time.perf_counter()
        with traced("sim.eval", round_idx=self.round_idx, sink=self._otlp_sink):
            res = self._eval_fn(self.global_vars, *self._test)
            out = {k: float(v) for k, v in res.items()}  # graftlint: disable=GL010(annotated measurement site: evaluation runs OFF the round loop at frequency_of_the_test cadence — its scalar sync never sits on the steady-state path)
        EVAL_TIME.observe(time.perf_counter() - t0)
        return out

    # -- checkpoint / resume (first-class, SURVEY.md §5; save/resume plumbing
    # from core.checkpoint.RoundCheckpointMixin) ------------------------------
    def _ckpt_state(self) -> dict:
        state = {
            "global_vars": self.global_vars,
            "server_state": self.server_state,
            "round_idx": self.round_idx,
            "root_key": self.root_key,
        }
        if self.client_states is not None:
            # store only the real clients — pad rows are a property of THIS
            # mesh; a resume may run on a different device count
            state["client_states"] = self._slice_lanes(self.client_states, self._n_real)
        if self.defense_history is not None:
            state["defense_history"] = self.defense_history
        return state

    def _apply_ckpt_state(self, state: dict) -> None:
        # re-apply the mesh placement __init__ establishes — restore hands
        # back host arrays, which would otherwise land unsharded on device 0
        self.global_vars = meshlib.replicate(state["global_vars"], self.mesh)
        self.server_state = jax.device_get(state["server_state"])
        self.server_state = meshlib.replicate(self.server_state, self.mesh)
        self.round_idx = int(state["round_idx"])
        # the checkpointed RNG key is authoritative (guards against a drifted
        # --random_seed silently changing the sampling stream mid-run)
        self.root_key = self._stage_scalar(jnp.asarray(state["root_key"]))
        if "client_states" in state:
            cs = meshlib.pad_leading_axis_np(state["client_states"], self._n_pad)
            self.client_states = meshlib.shard_leading_axis(cs, self.mesh)
        if "defense_history" in state:
            self.defense_history = jnp.asarray(state["defense_history"])

    def _next_boundary(self, r0: int) -> int:
        """First round index > r0 at which the host must intervene (eval,
        checkpoint, contribution snapshot, or the end of training); rounds in
        between run as one device-resident scanned chunk."""
        cfg = self.cfg
        ends = [cfg.comm_round]
        if cfg.frequency_of_the_test:
            f = cfg.frequency_of_the_test
            ends.append(((r0 // f) + 1) * f)
        if cfg.checkpoint_every_rounds:
            c = cfg.checkpoint_every_rounds
            ends.append(((r0 // c) + 1) * c)
        if getattr(cfg, "enable_contribution", False) and r0 < cfg.comm_round - 1:
            # a chunk must not straddle the last round: its pre-round state
            # gets snapshotted for contribution replay
            ends.append(cfg.comm_round - 1)
        return max(r0 + 1, min(e for e in ends if e > r0))

    def run(self) -> list[dict]:
        """The fit loop (reference ``FedAvgAPI.train`` ``fedavg_api.py:66``),
        executed in device-resident chunks between host boundaries."""
        history = []
        cfg = self.cfg
        self.try_resume()
        if self._aot is not None:
            self.warm_start()  # resolve every chunk program before round 0
        while self.round_idx < cfg.comm_round:
            r0 = self.round_idx
            if getattr(cfg, "enable_contribution", False) and r0 == cfg.comm_round - 1:
                # retain the pre-round state so contribution is assessed on
                # the ACTUAL last-round contributions (deterministic replay),
                # not fresh updates from the post-round global — reference
                # semantics (contribution_assessor_manager.py:9 assesses from
                # Context state captured during the round)
                self._contribution_snapshot = self._snapshot_pre_round(r0)
            end = self._next_boundary(r0)
            t0 = time.perf_counter()
            chunk = self.run_rounds(end - r0)
            span = time.perf_counter() - t0
            for i, metrics in enumerate(chunk):
                # chunk-average wall time: rounds inside one scanned chunk are
                # not individually timeable (and the first chunk's average
                # includes the scan program's compile); chunk_time_s is the
                # honest measured quantity
                metrics["round_time_s"] = span / len(chunk)
                metrics["chunk_time_s"] = span
                metrics["chunk_rounds"] = len(chunk)
                metrics["round"] = r0 + i
            r_last = r0 + len(chunk) - 1
            if cfg.frequency_of_the_test and (
                (r_last + 1) % cfg.frequency_of_the_test == 0 or r_last == cfg.comm_round - 1
            ):
                chunk[-1].update(self.evaluate())
            for metrics in chunk:
                self.logger.log(metrics)
                history.append(metrics)
            self.maybe_save_checkpoint(r_last)
        if getattr(cfg, "enable_contribution", False):
            scores = self.assess_contribution()
            if scores is not None:
                self.logger.log({f"contribution_c{i}": float(s) for i, s in enumerate(scores)})
        if self.profiler is not None:
            # a window still open at fit end (profile_rounds past comm_round)
            # closes and attributes here rather than losing the trace
            self.profiler.finalize()
        if self._otlp is not None:
            # end-of-fit egress: drain queued spans and ship the registry
            # snapshot; flush (not close) so a caller running fit again on
            # the same simulator keeps exporting
            self._otlp.export_metrics_now()
            self._otlp.flush(timeout=5.0)
        return history

    def _snapshot_pre_round(self, r: int) -> dict:
        # only the sampled clients' states are ever replayed (the sampled set
        # is deterministic in (root_key, r)), so don't host-copy the full
        # n_total stack — with SCAFFOLD-style per-client state that would be
        # n_total/m times more RAM than needed
        n_total = self.dataset.n_clients
        m = min(self.cfg.client_num_per_round, n_total)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n_total, m))
        return {
            "round": r,
            "global_vars": jax.device_get(self.global_vars),
            "server_state": jax.device_get(self.server_state),
            "client_states": (
                {
                    int(ci): jax.device_get(
                        jax.tree_util.tree_map(lambda s: s[int(ci)], self.client_states)
                    )
                    for ci in sampled
                }
                if self.client_states is not None else None
            ),
        }

    def last_round_contributions(self):
        """Deterministically replay the last round's EXACT client
        contributions from the retained pre-round snapshot: same sampled set,
        same round key, same pre-round global/server/client states as the
        round that was aggregated.  Returns (stacked, weights, sampled,
        snapshot) or None when no snapshot was retained."""
        snap = getattr(self, "_contribution_snapshot", None)
        if snap is None:
            return None
        r = snap["round"]
        n_total = self.dataset.n_clients
        m = min(self.cfg.client_num_per_round, n_total)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n_total, m))
        rkey = rng.round_key(self.root_key, r)
        fn = self._client_fn_sp or jax.jit(self._sp_client_update)
        contribs, weights = [], []
        for ci in sampled:
            cs = snap["client_states"][int(ci)] if snap["client_states"] is not None else None
            contrib, _, _ = fn(
                snap["global_vars"], cs, snap["server_state"],
                self._data[0][int(ci)], self._data[1][int(ci)],
                self.counts[int(ci)], rng.client_key(rkey, int(ci)),
            )
            contribs.append(contrib)
            weights.append(float(self.counts[int(ci)]))
        return pt.tree_stack(contribs), weights, sampled, snap

    def assess_contribution(self):
        """Shapley contribution of the last round's sampled clients
        (reference ``ServerAggregator.assess_contribution``
        ``server_aggregator.py:105``): scores the coalitions of the ACTUAL
        last-round contributions (replayed from the pre-round snapshot) by
        test accuracy."""
        from ..trust.contribution import ContributionAssessorManager

        mgr = ContributionAssessorManager(self.cfg)
        if not mgr.enabled or self.round_idx == 0:
            return None
        replay = self.last_round_contributions()
        if replay is None:
            return None
        stacked, weights, sampled, snap = replay
        one = jax.tree_util.tree_map(lambda x: x[0], stacked)
        if jax.tree_util.tree_structure(one) != jax.tree_util.tree_structure(self.global_vars):
            return None  # contribution defined on weight-style contributions

        def eval_fn(agg_vars):
            return self._eval_fn(agg_vars, *self._test)["test_acc"]

        return mgr.assess(stacked, np.asarray(weights), eval_fn, empty_model=snap["global_vars"])
