"""Split learning: SplitNN and FedGKT.

Reference: ``simulation/mpi/split_nn/`` (P7 — model cut at a layer, clients
hold the bottom, the server the top; activations/grads cross the boundary;
clients train in relay) and ``simulation/mpi/fedgkt/`` (P8 — Group Knowledge
Transfer: small client extractor+head, big server model on exchanged
features, bidirectional KD with logit exchange).

TPU-native form: the activation/grad "exchange" is just end-to-end autodiff
of the composed (bottom, top) program — what the reference implements as two
processes passing tensors is one ``jax.grad`` through both halves.  The relay
(server weights updated sequentially across clients) is a ``lax.scan`` over
the client dimension; each client's local pass is itself a scan over batches.
"""

from __future__ import annotations

import math
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..algorithms import hparams_from_config
from ..arguments import Config
from ..core import pytree as pt, rng
from ..data.dataset import pad_eval_set, stack_clients
from ..obs.metrics import MetricsLogger
from ..models import resnet, simple


def create_split_model(cfg: Config, out_dim: int):
    """(bottom, top) module pair.  CIFAR-family -> split resnet56 halves
    (reference ``model/cv/resnet56/`` client/server split); otherwise a simple
    MLP split for tabular/synthetic tasks."""
    if cfg.dataset.startswith("cifar") or cfg.dataset == "cinic10":
        return (
            resnet.SplitResNet56Client(norm=cfg.norm),
            resnet.SplitResNet56Server(num_classes=out_dim, norm=cfg.norm),
        )

    class BottomMLP(simple.nn.Module):
        @simple.nn.compact
        def __call__(self, x, train: bool = True):
            x = x.reshape((x.shape[0], -1))
            x = simple.nn.Dense(64)(x)
            return simple.nn.relu(x)

    class TopMLP(simple.nn.Module):
        num_classes: int = out_dim

        @simple.nn.compact
        def __call__(self, h, train: bool = True):
            h = simple.nn.Dense(64)(h)
            h = simple.nn.relu(h)
            return simple.nn.Dense(self.num_classes)(h)

    return BottomMLP(), TopMLP()


class SplitNNSimulator:
    """Relay SplitNN: per round, scan over clients; each client trains its own
    bottom jointly with the SHARED server top (updated in relay order, exactly
    the reference's sequential client protocol)."""

    def __init__(self, cfg: Config, dataset, model=None, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.bottom, self.top = create_split_model(cfg, dataset.class_num)
        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        spe = max(1, math.ceil(stacked.capacity / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        n = dataset.n_clients

        k0 = rng.root_key(cfg.random_seed)
        sx = jnp.asarray(stacked.x[0, : cfg.batch_size])
        bvars = self.bottom.init({"params": jax.random.fold_in(k0, 1)}, sx, train=True)
        h0 = self.bottom.apply(bvars, sx, train=False)
        tvars = self.top.init({"params": jax.random.fold_in(k0, 2)}, h0, train=True)
        # per-client bottoms (stacked), shared top
        self.client_bottoms = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape).copy(), bvars
        )
        self.top_vars = tvars
        self._data = (jnp.asarray(stacked.x), jnp.asarray(stacked.y))
        self.counts = jnp.asarray(stacked.counts)
        self.root_key = k0
        self.round_idx = 0
        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        self._round_fn = jax.jit(self._make_round_fn())
        self._eval_fn = jax.jit(self._make_eval_fn(eval_bs))

    def _composed_loss(self, bvars, tvars, x, y):
        h = self.bottom.apply(bvars, x, train=True)
        logits = self.top.apply(tvars, h, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()

    def _make_round_fn(self):
        hp = self.hp
        opt = optax.sgd(hp.learning_rate, momentum=hp.momentum or None)
        grad_fn = jax.value_and_grad(self._composed_loss, argnums=(0, 1))

        def client_pass(carry, inputs):
            tvars, key = carry
            bvars, x, y, cnt = inputs
            b_opt = opt.init(bvars)
            t_opt = opt.init(tvars)

            def step(c, s):
                bvars, tvars, b_opt, t_opt = c
                perm = jax.random.permutation(jax.random.fold_in(key, s), x.shape[0])
                idx = jax.lax.dynamic_slice_in_dim(perm, 0, hp.batch_size)
                loss, (gb, gt) = grad_fn(bvars, tvars, jnp.take(x, idx, 0), jnp.take(y, idx, 0))
                ub, b_opt = opt.update(gb, b_opt, bvars)
                ut, t_opt = opt.update(gt, t_opt, tvars)
                return (optax.apply_updates(bvars, ub), optax.apply_updates(tvars, ut), b_opt, t_opt), loss

            (bvars, tvars, _, _), losses = jax.lax.scan(
                step, (bvars, tvars, b_opt, t_opt), jnp.arange(hp.local_steps)
            )
            return (tvars, jax.random.fold_in(key, 7)), (bvars, jnp.mean(losses))

        def round_fn(client_bottoms, top_vars, data_x, data_y, counts, round_idx, key):
            rkey = rng.round_key(key, round_idx)
            (top_vars, _), (new_bottoms, losses) = jax.lax.scan(
                client_pass, (top_vars, rkey), (client_bottoms, data_x, data_y, counts)
            )
            return new_bottoms, top_vars, {"train_loss": jnp.mean(losses)}

        return round_fn

    def _make_eval_fn(self, eval_bs):
        def eval_fn(bvars, tvars, x, y, n_valid):
            n_batches = x.shape[0] // eval_bs

            def body(carry, i):
                correct, seen = carry
                bx = jax.lax.dynamic_slice_in_dim(x, i * eval_bs, eval_bs)
                by = jax.lax.dynamic_slice_in_dim(y, i * eval_bs, eval_bs)
                pos = i * eval_bs + jnp.arange(eval_bs)
                mask = (pos < n_valid).astype(jnp.float32)
                h = self.bottom.apply(bvars, bx, train=False)
                logits = self.top.apply(tvars, h, train=False)
                ok = (jnp.argmax(logits, -1) == by).astype(jnp.float32)
                return (correct + jnp.sum(ok * mask), seen + jnp.sum(mask)), None

            (correct, seen), _ = jax.lax.scan(body, (jnp.float32(0), jnp.float32(0)), jnp.arange(n_batches))
            return {"test_acc": correct / jnp.maximum(seen, 1.0)}

        return eval_fn

    def run_round(self) -> dict:
        self.client_bottoms, self.top_vars, metrics = self._round_fn(
            self.client_bottoms, self.top_vars, self._data[0], self._data[1],
            self.counts, jnp.int32(self.round_idx), self.root_key,
        )
        self.round_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self) -> dict:
        # evaluate with client 0's bottom (reference evaluates per client)
        b0 = jax.tree_util.tree_map(lambda s: s[0], self.client_bottoms)
        return {k: float(v) for k, v in self._eval_fn(b0, self.top_vars, *self._test).items()}

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (r + 1) % self.cfg.frequency_of_the_test == 0 or r == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
        return history


class FedGKTSimulator:
    """Group Knowledge Transfer (compact faithful variant).

    Per round:
      1. each client trains extractor+head on its shard (CE + KD to the
         server logits it received last round),
      2. clients emit features/labels/logits for a fixed per-client probe set,
      3. the server model trains on the pooled features (CE + KD to client
         logits) and sends back fresh per-sample server logits.
    All three phases are vmapped/scanned device code; the feature exchange is
    an array, not a message.
    """

    def __init__(self, cfg: Config, dataset, model=None, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.bottom, self.top = create_split_model(cfg, dataset.class_num)
        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        spe = max(1, math.ceil(stacked.capacity / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        n = dataset.n_clients
        self.n_classes = dataset.class_num
        self.probe = min(int(stacked.capacity), 128)  # per-client exchanged samples

        k0 = rng.root_key(cfg.random_seed)
        sx = jnp.asarray(stacked.x[0, : cfg.batch_size])
        bvars = self.bottom.init({"params": jax.random.fold_in(k0, 1)}, sx, train=True)
        h0 = self.bottom.apply(bvars, sx, train=False)
        # client head: small classifier on features
        self.head = simple.MLP(hidden=64, num_classes=self.n_classes)
        hvars = self.head.init({"params": jax.random.fold_in(k0, 3)}, h0.reshape(h0.shape[0], -1), train=True)
        tvars = self.top.init({"params": jax.random.fold_in(k0, 2)}, h0, train=True)
        self.client_bottoms = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape).copy(), bvars
        )
        self.client_heads = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape).copy(), hvars
        )
        self.server_vars = tvars
        self.server_logits = jnp.zeros((n, self.probe, self.n_classes))
        self._data = (jnp.asarray(stacked.x), jnp.asarray(stacked.y))
        self.counts = jnp.asarray(stacked.counts)
        self.root_key = k0
        self.round_idx = 0
        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        self._round_fn = jax.jit(self._make_round_fn())
        self._eval_fn = jax.jit(self._make_eval_fn(eval_bs))

    @staticmethod
    def _kd(student_logits, teacher_logits, T: float = 1.0):
        t = jax.nn.softmax(teacher_logits / T, axis=-1)
        s = jax.nn.log_softmax(student_logits / T, axis=-1)
        return -jnp.mean(jnp.sum(t * s, axis=-1))

    def _make_round_fn(self):
        hp = self.hp
        opt = optax.sgd(hp.learning_rate, momentum=hp.momentum or None)
        kd_on = lambda r: (r > 0)

        def client_phase(bvars, hvars, x, y, slogits, key, round_idx):
            def loss_fn(bh, bx, by, bsl):
                bv, hv = bh
                feats = self.bottom.apply(bv, bx, train=True)
                logits = self.head.apply(hv, feats.reshape(feats.shape[0], -1), train=True)
                ce = optax.softmax_cross_entropy_with_integer_labels(logits.astype(jnp.float32), by).mean()
                kd = jnp.where(round_idx > 0, self._kd(logits.astype(jnp.float32), bsl), 0.0)
                return ce + kd

            grad_fn = jax.value_and_grad(loss_fn)
            opt_state = opt.init((bvars, hvars))

            def step(c, s):
                bh, opt_state = c
                perm = jax.random.permutation(jax.random.fold_in(key, s), x.shape[0])
                idx = jax.lax.dynamic_slice_in_dim(perm, 0, hp.batch_size)
                sl_idx = jnp.minimum(idx, self.probe - 1)
                loss, g = grad_fn(bh, jnp.take(x, idx, 0), jnp.take(y, idx, 0), jnp.take(slogits, sl_idx, 0))
                u, opt_state = opt.update(g, opt_state, bh)
                return (optax.apply_updates(bh, u), opt_state), loss

            (bh, _), losses = jax.lax.scan(step, ((bvars, hvars), opt_state), jnp.arange(hp.local_steps))
            bvars, hvars = bh
            probe_x = x[: self.probe]
            feats = self.bottom.apply(bvars, probe_x, train=False)
            logits = self.head.apply(hvars, feats.reshape(feats.shape[0], -1), train=False)
            return bvars, hvars, feats, logits, jnp.mean(losses)

        def round_fn(client_bottoms, client_heads, server_vars, server_logits,
                     data_x, data_y, counts, round_idx, key):
            rkey = rng.round_key(key, round_idx)
            n = counts.shape[0]
            keys = jax.vmap(lambda i: rng.client_key(rkey, i))(jnp.arange(n))
            new_b, new_h, feats, clogits, losses = jax.vmap(
                lambda b, h, x, y, sl, k: client_phase(b, h, x, y, sl, k, round_idx)
            )(client_bottoms, client_heads, data_x, data_y, server_logits, keys)
            probe_y = data_y[:, : self.probe]

            # server phase: train top on pooled features with CE + KD
            flat_feats = feats.reshape((-1,) + feats.shape[2:])
            flat_y = probe_y.reshape(-1)
            flat_cl = clogits.reshape(-1, self.n_classes)

            def s_loss(tv, bx, by, bcl):
                logits = self.top.apply(tv, bx, train=True).astype(jnp.float32)
                return (
                    optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()
                    + self._kd(logits, bcl)
                )

            s_grad = jax.value_and_grad(s_loss)
            s_opt = opt.init(server_vars)
            bs = self.hp.batch_size
            n_batches = flat_feats.shape[0] // bs

            def s_step(c, i):
                tv, s_opt = c
                perm = jax.random.permutation(jax.random.fold_in(rkey, 0x5E), flat_feats.shape[0])
                idx = jax.lax.dynamic_slice_in_dim(perm, (i % n_batches) * bs, bs)
                loss, g = s_grad(tv, jnp.take(flat_feats, idx, 0), jnp.take(flat_y, idx, 0), jnp.take(flat_cl, idx, 0))
                u, s_opt = opt.update(g, s_opt, tv)
                return (optax.apply_updates(tv, u), s_opt), loss

            (server_vars, _), _ = jax.lax.scan(s_step, (server_vars, s_opt), jnp.arange(max(1, n_batches)))
            # fresh server logits per client probe set
            new_slogits = jax.vmap(lambda f: self.top.apply(server_vars, f, train=False))(feats)
            return new_b, new_h, server_vars, new_slogits.astype(jnp.float32), {"train_loss": jnp.mean(losses)}

        return round_fn

    def _make_eval_fn(self, eval_bs):
        def eval_fn(bvars, server_vars, x, y, n_valid):
            n_batches = x.shape[0] // eval_bs

            def body(carry, i):
                correct, seen = carry
                bx = jax.lax.dynamic_slice_in_dim(x, i * eval_bs, eval_bs)
                by = jax.lax.dynamic_slice_in_dim(y, i * eval_bs, eval_bs)
                pos = i * eval_bs + jnp.arange(eval_bs)
                mask = (pos < n_valid).astype(jnp.float32)
                h = self.bottom.apply(bvars, bx, train=False)
                logits = self.top.apply(server_vars, h, train=False)
                ok = (jnp.argmax(logits, -1) == by).astype(jnp.float32)
                return (correct + jnp.sum(ok * mask), seen + jnp.sum(mask)), None

            (c, s), _ = jax.lax.scan(body, (jnp.float32(0), jnp.float32(0)), jnp.arange(n_batches))
            return {"test_acc": c / jnp.maximum(s, 1.0)}

        return eval_fn

    def run_round(self) -> dict:
        (self.client_bottoms, self.client_heads, self.server_vars,
         self.server_logits, metrics) = self._round_fn(
            self.client_bottoms, self.client_heads, self.server_vars, self.server_logits,
            self._data[0], self._data[1], self.counts, jnp.int32(self.round_idx), self.root_key,
        )
        self.round_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self) -> dict:
        b0 = jax.tree_util.tree_map(lambda s: s[0], self.client_bottoms)
        return {k: float(v) for k, v in self._eval_fn(b0, self.server_vars, *self._test).items()}

    def run(self) -> list[dict]:
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (r + 1) % self.cfg.frequency_of_the_test == 0 or r == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
        return history
