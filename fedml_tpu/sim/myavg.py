"""MyAvg — CKA layer-selective personalized aggregation (fork research).

Re-implements the fork's research algorithm family (reference
``my_research/sp_fedavg_cifar10_resnet20_example/MyAvgAPI_7.py``; dispatched by
``python/fedml/simulation/simulator.py:88-95`` as ``MyAgg-*``):

- **Personalized clients** (``MyAvgAPI_7.py:289-292``, ``set_param=False``):
  every client keeps its OWN model across rounds; local SGD starts from the
  personal weights, never from the global model.
- **Mod-N round-interval layer schedule** (``MyAvgAPI_7.py:242-263``): each
  round a substring :class:`LayerFilter` decides WHICH layers aggregate.  On
  rounds divisible by an ``agg_mod_list`` entry (first match wins, round 0
  exempt) the filter from ``agg_mod_dict[mod]`` applies; otherwise the default
  ``agg_*_layer`` filter.  Unaggregated layers stay local to each client.
- **CKA top-k partner aggregation** (``MyAvgAPI_7.py:364-435`` +
  ``my_utils.py:61-74``): for layers selected by the ``cka_*_layer`` filter,
  each client aggregates a layer only over its ``cka_select_topk`` most
  CKA-similar peers (linear CKA over the clients' layer DELTAS, conv kernels
  mean-pooled over their spatial dims; self always included; similarities
  outside ``[cka_low_thresh, cka_high_thresh]`` dropped).  For >=2-D layers
  the partner-averaged delta is corrected against the global-average delta:
  when their inner product is negative the conflicting component is projected
  out, and the result is rescaled to the mean of the two norms
  (``MyAvgAPI_7.py:410-434``; the reference's ``trace``/``dot`` forms are the
  Frobenius inner product on 2-D weights — used here for every >=2-D leaf).
- The **server model** takes the plain sample-weighted average of the
  aggregated layers (``g_all_global``), serving as the evaluation model.

TPU-native design: there is no per-round Python filtering.  The layer filters
compile to per-leaf {0,1} mask TABLES indexed by a round-derived config id, so
the whole round — personal local SGD (vmapped over the ``clients`` mesh axis),
CKA matrices, top-k partner selection (``lax.top_k``), masked weighted means —
is ONE jitted function of ``round_idx``, scan-compatible with
``MeshSimulator.run_rounds`` (the reference recomputes filters and loops
layers in Python every round).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants as C
from ..core import pytree as pt, rng
from ..fl.local_sgd import make_eval_fn
from ..parallel import mesh as meshlib
from .engine import MeshSimulator


class LayerFilter:
    """Substring layer selection — semantics of ``my_utils.py:13-44``.

    A dotted leaf path is kept iff it contains NO ``unselect`` key, ALL
    ``all_select`` keys, and (if any given) at least one ``any_select`` key.
    An entirely empty filter keeps everything.
    """

    def __init__(self, unselect: Sequence[str] = (), all_select: Sequence[str] = (),
                 any_select: Sequence[str] = ()):
        self.unselect = tuple(unselect or ())
        self.all_select = tuple(all_select or ())
        self.any_select = tuple(any_select or ())

    def __call__(self, path: str) -> bool:
        if not (self.unselect or self.all_select or self.any_select):
            return True
        return (
            all(k not in path for k in self.unselect)
            and all(k in path for k in self.all_select)
            and (not self.any_select or any(k in path for k in self.any_select))
        )

    def __repr__(self):
        return (f"LayerFilter(unselect={self.unselect}, "
                f"all={self.all_select}, any={self.any_select})")


def leaf_paths(tree) -> list[str]:
    """Dotted path per leaf, e.g. ``params.conv1.kernel`` — the name the
    substring filters match against (reference filters match torch state_dict
    keys; configs supply their own substrings either way)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, _leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        out.append(".".join(parts))
    return out


def _as_rows(x: jax.Array) -> jax.Array:
    """Reduce one client's layer delta to the 2-D matrix CKA runs on
    (``my_utils.py:68-71``): convs are mean-pooled over spatial dims and
    oriented rows=output-features (torch OIHW ``mean(dim=[-1,-2])`` ==
    flax HWIO ``mean(axis=spatial)`` transposed); 1-D leaves become a
    column vector."""
    if x.ndim == 0:
        return x.reshape(1, 1)
    if x.ndim == 1:
        return x[:, None]
    if x.ndim == 2:
        return x.T  # flax [in, out] -> rows = out (torch [out, in] parity)
    spatial = tuple(range(x.ndim - 2))
    return x.mean(axis=spatial).T  # [in, out] -> [out, in]


def linear_cka_matrix(deltas: jax.Array) -> jax.Array:
    """Pairwise linear CKA over ``m`` clients' reduced layer matrices.

    ``deltas``: [m, r, c].  Returns [m, m] with 1s on the diagonal, clipped to
    <= 1 (``my_utils.py:72-73``).  Linear-kernel CKA with the centered-HSIC
    normalization of ``my_utils.py:185-212``: with Kc = H X Xt H,
    CKA(i, j) = <Kc_i, Kc_j> / (||Kc_i|| ||Kc_j||) — the 1/(n-1)^2 factors
    cancel.  Computed for ALL pairs as one Gram matmul instead of the
    reference's O(m^2) Python loop.
    """
    m, r, _ = deltas.shape
    x = deltas.astype(jnp.float32)
    k = jnp.einsum("mrc,msc->mrs", x, x)  # per-client kernel [m, r, r]
    # center: H K H with H = I - 11^T/r
    k = k - k.mean(axis=1, keepdims=True)
    k = k - k.mean(axis=2, keepdims=True)
    flat = k.reshape(m, r * r)
    gram = flat @ flat.T  # <Kc_i, Kc_j>
    diag = jnp.sqrt(jnp.clip(jnp.diagonal(gram), 0.0))
    denom = diag[:, None] * diag[None, :]
    cka = jnp.where(denom > 0, gram / jnp.where(denom > 0, denom, 1.0), 0.0)
    # degenerate (zero-delta) clients: fall back to self-similarity only
    cka = jnp.where(jnp.eye(m, dtype=bool), 1.0, cka)
    return jnp.minimum(cka, 1.0)


class MyAvgSimulator(MeshSimulator):
    """MeshSimulator with the MyAvg server path.

    ``client_states`` holds every client's personal model (stacked, sharded on
    the ``clients`` axis); the jitted round trains the sampled clients from
    their personal weights, then rebuilds both the server model and each
    sampled client's personal model per the mask tables + CKA selection.
    """

    def __init__(self, cfg, dataset, model, mesh=None, logger=None):
        if cfg.backend_sim == C.SIMULATION_BACKEND_SP:
            raise NotImplementedError(
                "MyAvg runs as the mesh round program; the sequential SP twin "
                "is not provided for it (set backend_sim='MESH')"
            )
        active_trust = [
            f for f in ("enable_secagg", "enable_fhe", "enable_contribution")
            if getattr(cfg, f, False)
        ]
        if active_trust:
            # secagg/fhe change the aggregation PROTOCOL (masked/encrypted
            # sums are incompatible with per-leaf CKA personalization, which
            # needs individual client deltas in the clear) and contribution
            # replay assumes the FedAvg server path — refuse loudly.
            # Attacks, defenses, and DP compose: the MyAvg round routes its
            # stacked trained models through the same trust hooks as the
            # engine round (round-3 verdict item 9).
            raise NotImplementedError(
                f"trust features {active_trust} are not wired into the MyAvg "
                "round; use a FedAvg-family optimizer for them"
            )
        orig_name = cfg.federated_optimizer
        # local training is plain client SGD (the reference's MyTrainer_7 is
        # the stock classification trainer, MyAvgAPI_7.py:16-70); the MyAvg
        # logic is all server-side
        cfg = dataclasses.replace(cfg, federated_optimizer=C.FEDERATED_OPTIMIZER_FEDAVG)
        super().__init__(cfg, dataset, model, mesh=mesh, logger=logger)
        # cfg must keep reporting the real optimizer to logging/bookkeeping
        self.cfg = dataclasses.replace(self.cfg, federated_optimizer=orig_name)
        if self.trust is not None and self.trust.defense is not None:
            from ..trust.defense.base import Defense

            if type(self.trust.defense).on_agg is not Defense.on_agg:
                # an aggregation-REPLACING defense (krum/median/bulyan/...)
                # collapses the m client deltas to one aggregate, which
                # destroys exactly the per-client structure the CKA partner
                # selection personalizes from — only transforming defenses
                # (clipping, reweighting, filtering via before()) compose
                raise NotImplementedError(
                    f"defense {type(self.trust.defense).name!r} replaces the "
                    "aggregation (on_agg); MyAvg needs per-client deltas — "
                    "use a transforming defense (e.g. norm_diff_clipping, "
                    "weak_dp, foolsgold) or a FedAvg-family optimizer"
                )

        n = self._n_pad  # engine pads the client axis to the mesh multiple
        stacked = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), self.global_vars
        )
        self.client_states = meshlib.shard_leading_axis(stacked, self.mesh)

        # per-client test shards (LEAF-style test_client_idx): personalized
        # eval must score each personal model on ITS OWN conditional — under
        # client-dependent class conditionals the union test set would punish
        # exactly the specialization MyAvg optimizes
        self._personal_test = None
        if dataset.test_client_idx is not None:
            eval_bs = self._eval_bs
            caps = [len(ix) for ix in dataset.test_client_idx]
            empty = [i for i, c in enumerate(caps) if c == 0]
            if empty:
                # an empty shard would silently score 0.0 and collapse the
                # min-accuracy headline metric into noise
                raise ValueError(
                    f"clients {empty} have EMPTY per-client test shards; "
                    "personalized eval needs at least one test sample per "
                    "client (raise synthetic_test_size or fix test_client_idx)"
                )
            cap = meshlib.round_up(max(max(caps), 1), eval_bs)
            tx = np.zeros((len(caps), cap) + dataset.test_x.shape[1:], dataset.test_x.dtype)
            ty = np.zeros((len(caps), cap) + dataset.test_y.shape[1:], dataset.test_y.dtype)
            for i, ix in enumerate(dataset.test_client_idx):
                reps = np.resize(ix, cap)  # cyclic pad; n_valid masks the rest
                tx[i], ty[i] = dataset.test_x[reps], dataset.test_y[reps]
            self._personal_test = (
                jnp.asarray(tx), jnp.asarray(ty),
                jnp.asarray(caps, jnp.int32),
            )

        # ---- static mask tables -------------------------------------------
        paths = leaf_paths(self.global_vars)
        self._paths = paths
        default_f = LayerFilter(cfg.agg_unselect_layer, cfg.agg_all_select_layer,
                                cfg.agg_any_select_layer)
        self._mods = [int(mi) for mi in cfg.agg_mod_list]
        if any(mi <= 0 for mi in self._mods):
            # a 0 would trace round_idx % 0 into XLA (undefined, silent)
            raise ValueError(f"agg_mod_list entries must be positive, got {self._mods}")
        mod_filters = []
        for mi in self._mods:
            spec = cfg.agg_mod_dict.get(mi, cfg.agg_mod_dict.get(str(mi), {}))
            mod_filters.append(LayerFilter(
                spec.get("agg_unselect_layer", ()),
                spec.get("agg_all_select_layer", ()),
                spec.get("agg_any_select_layer", ()),
            ))
        filters = [default_f] + mod_filters  # config id 0 = default
        # [n_leaves, n_configs] 0/1 — which leaves aggregate under which config
        self._agg_table = [
            jnp.asarray([1.0 if f(p) else 0.0 for f in filters], jnp.float32)
            for p in paths
        ]
        cka_f = LayerFilter(cfg.cka_unselect_layer, cfg.cka_all_select_layer,
                            cfg.cka_any_select_layer)
        self._cka_flags = [bool(cka_f(p)) for p in paths]
        # filters come from hand-mapped torch state_dict substrings; a typo
        # (or a flax-vs-torch naming mismatch) silently degenerates MyAvg to
        # plain FedAvg — every configured substring must match SOME leaf,
        # and a configured CKA filter must select at least one leaf
        all_subs = set(cfg.agg_unselect_layer) | set(cfg.agg_all_select_layer) \
            | set(cfg.agg_any_select_layer) | set(cfg.cka_unselect_layer) \
            | set(cfg.cka_all_select_layer) | set(cfg.cka_any_select_layer)
        for spec in cfg.agg_mod_dict.values():
            for key in ("agg_unselect_layer", "agg_all_select_layer", "agg_any_select_layer"):
                all_subs |= set(spec.get(key, ()))
        dead = sorted(s for s in all_subs if not any(s in p for p in paths))
        if dead:
            raise ValueError(
                f"MyAvg layer-filter substrings {dead} match NO model leaf "
                f"path; known paths: {paths}"
            )
        cka_configured = bool(cfg.cka_any_select_layer or cfg.cka_all_select_layer
                              or cfg.cka_unselect_layer)
        if cka_configured and not any(self._cka_flags):
            raise ValueError(
                "cka_*_select_layer is configured but selects zero leaves — "
                "the CKA personalization would silently never run"
            )
        self._topk = int(cfg.cka_select_topk)
        self._thresh = (float(cfg.cka_low_thresh), float(cfg.cka_high_thresh))
        # rebuild the jitted round over the override (the parent compiled the
        # plain FedAvg round before these tables existed)
        self._round_fn = jax.jit(self._make_round_fn())
        self._multi_round_fns = {}

    # ------------------------------------------------------------------
    def _config_id(self, round_idx):
        """First ``agg_mod_list`` entry dividing ``round_idx`` wins; round 0
        always uses the default filter (``MyAvgAPI_7.py:242-247``)."""
        cid = jnp.int32(0)
        for i in reversed(range(len(self._mods))):
            cid = jnp.where(round_idx % self._mods[i] == 0, jnp.int32(i + 1), cid)
        return jnp.where(round_idx == 0, jnp.int32(0), cid)

    # ------------------------------------------------------------------
    def _make_round_fn(self):
        if not hasattr(self, "_agg_table"):
            # parent __init__ jits a round before the mask tables exist; that
            # placeholder is discarded and rebuilt at the end of __init__
            return super()._make_round_fn()
        algo = self.algorithm
        cfg = self.cfg
        n_total = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n_total)
        k_sel = min(self._topk, m)
        lo, hi = self._thresh
        agg_table = self._agg_table
        cka_flags = self._cka_flags
        treedef = jax.tree_util.tree_structure(self.global_vars)

        def partner_select(cka_row, i, weights):
            """Top-k + threshold partner weights for client i's row
            (``MyAvgAPI_7.py:398-408``): self always kept, subset re-weighted
            by sample counts."""
            _, top_idx = jax.lax.top_k(cka_row, k_sel)
            in_topk = jnp.zeros_like(cka_row).at[top_idx].set(1.0)
            ok = in_topk * (cka_row >= lo) * (cka_row <= hi)
            ok = ok.at[i].set(1.0)
            pw = weights * ok
            return pw / jnp.maximum(pw.sum(), 1e-12)

        m_pad = meshlib.round_up(m, self._lane_multiple)

        def round_fn(global_vars, server_state, client_states, counts, data_x,
                     data_y, round_idx, key, prev_delta):
            sampled = rng.sample_clients(key, round_idx, n_total, m)
            xs, ys, cnts, personal, rkey, keys = self._gather_round_inputs(
                sampled, m, m_pad, counts, data_x, data_y, client_states, key, round_idx
            )

            def one_client(pvars, x, y, cnt, k):
                out = algo.client_update(pvars, None, server_state, x, y, cnt, k)
                return out.contribution, out.metrics
            trained, metrics = jax.vmap(one_client)(personal, xs, ys, cnts, keys)
            # pad lanes carry client 0's redundant training — drop them so the
            # CKA gram, partner selection and aggregation stay exactly m x m
            trained = self._slice_lanes(trained, m)
            metrics = self._slice_lanes(metrics, m)

            weights = cnts[:m].astype(jnp.float32)
            # the clients' RETAINED local models: trust hooks transform only
            # the SHIPPED copy (LDP noise / defense clipping applies to the
            # transmitted update, never to client-local state — otherwise a
            # personal head that never aggregates would random-walk under a
            # fresh noise draw every sampled round)
            retained = trained
            if self.trust is not None:
                # same hook chain as the engine round (attack simulation +
                # LDP on the stacked trained models; defense before()
                # transforms deltas / reweights — the reweighted weights flow
                # into BOTH the global aggregate and the CKA partner weights,
                # so a zero-weighted byzantine client also loses its vote as
                # a personalization partner)
                trained, weights = self.trust.on_client_outputs(
                    trained, weights, sampled, global_vars, rkey
                )
                trained, weights, agg_override = self.trust.on_aggregation(
                    trained, weights, global_vars, rkey, prev_delta=prev_delta
                )
                if agg_override is not None:
                    # normally refused at __init__ (on_agg check); a pipeline
                    # installed post-construction must hit the same wall —
                    # silently discarding a defense's aggregate is worse
                    raise NotImplementedError(
                        "trust pipeline returned an aggregation override; "
                        "MyAvg needs per-client deltas (see __init__ refusal)"
                    )
            wnorm = weights / jnp.maximum(weights.sum(), 1e-12)
            cid = self._config_id(round_idx)

            g_leaves = jax.tree_util.tree_leaves(global_vars)
            t_leaves = jax.tree_util.tree_leaves(trained)
            r_leaves = jax.tree_util.tree_leaves(retained)
            new_g_leaves, new_p_leaves = [], []
            for li, (g, t, t_clean) in enumerate(zip(g_leaves, t_leaves, r_leaves)):
                agg_on = jnp.take(agg_table[li], cid)  # {0,1} this round
                delta = (t - g[None]).astype(jnp.float32)
                bshape = (m,) + (1,) * g.ndim
                g_all = jnp.tensordot(wnorm, delta, axes=1)  # weighted mean
                new_g = (g + agg_on * g_all).astype(g.dtype)

                if cka_flags[li] and g.ndim > 0:
                    def cka_personalize(delta, g_all, g=g):
                        rows = jax.vmap(_as_rows)(delta)
                        cka = linear_cka_matrix(rows)
                        pw = jax.vmap(partner_select, in_axes=(0, 0, None))(
                            cka, jnp.arange(m), weights
                        )  # [m, m] partner weights per client
                        g_cka = jnp.tensordot(pw, delta, axes=1)  # [m, ...]
                        if g.ndim >= 2:
                            # negative-projection correction + norm rescale
                            # (MyAvgAPI_7.py:410-434)
                            axes = tuple(range(1, g.ndim + 1))
                            a_n = jnp.sqrt((g_cka ** 2).sum(axis=axes))
                            gl_n = jnp.sqrt((g_all ** 2).sum())
                            a_hat = g_cka / jnp.maximum(a_n, 1e-12).reshape(bshape)
                            g_hat = g_all / jnp.maximum(gl_n, 1e-12)
                            b = (a_hat * g_hat[None]).sum(axis=axes)
                            a_opt = jnp.where(
                                (b < 0).reshape(bshape),
                                a_hat - b.reshape(bshape) * g_hat[None], a_hat,
                            )
                            g_cka = a_opt * ((a_n + gl_n) / 2.0).reshape(bshape)
                        return g_cka

                    # the result is discarded on rounds where the layer is
                    # gated off (agg_on == 0) — skip the gram/top-k work then
                    pers_delta = jax.lax.cond(
                        agg_on > 0, cka_personalize,
                        lambda d, a: jnp.zeros((m,) + g.shape, jnp.float32),
                        delta, g_all,
                    )
                else:
                    pers_delta = jnp.broadcast_to(g_all[None], (m,) + g.shape)

                # aggregated layers: personal <- old global + personalized
                # delta (server-computed from the SHIPPED updates — trust
                # transforms legitimately flow in here); unaggregated: the
                # client keeps its CLEAN locally trained leaf (strict=False
                # load semantics, MyAvgAPI_7.py:320-326)
                new_p = jnp.where(agg_on > 0, (g[None] + pers_delta).astype(t.dtype), t_clean)
                new_g_leaves.append(new_g)
                new_p_leaves.append(new_p)

            new_global = jax.tree_util.tree_unflatten(treedef, new_g_leaves)
            if self.trust is not None:
                # CDP clip+noise and defense post-processing on the GLOBAL
                # model only — personal models are the clients' own local
                # state and never leave the device in this simulation
                new_global = self.trust.on_after_aggregation(new_global, global_vars, rkey)
            new_personal = jax.tree_util.tree_unflatten(treedef, new_p_leaves)
            new_states = jax.tree_util.tree_map(
                lambda full, upd: full.at[sampled].set(upd.astype(full.dtype)),
                client_states, new_personal,
            )
            new_delta = prev_delta
            if prev_delta is not None:  # cross-round defense history
                new_flat, _ = pt.tree_flatten_to_vector(new_global)
                old_flat, _ = pt.tree_flatten_to_vector(global_vars)
                new_delta = new_flat - old_flat
            round_metrics = {k: jnp.mean(v) for k, v in metrics.items()}
            round_metrics["myavg_config_id"] = cid.astype(jnp.float32)
            return new_global, server_state, new_states, new_delta, round_metrics

        return round_fn

    # ------------------------------------------------------------------
    def evaluate(self) -> dict:
        """Global-model eval PLUS personalized-model eval: the personal
        models are what MyAvg optimizes (the reference's periodic test is
        per-client local models, ``MyAvgAPI_7.py:304-309``), so the run-loop
        history must carry both."""
        out = super().evaluate()
        out.update(self.evaluate_personalized())
        return out

    def evaluate_personalized(self) -> dict:
        """Mean/min test accuracy of the clients' PERSONAL models — the
        quantity MyAvg optimizes (the reference evaluates every client's local
        model, ``MyAvgAPI_7.py:487-520``).  With per-client test shards
        (``test_client_idx``) each personal model is scored on its own
        conditional; otherwise on the shared test set."""
        # pad rows hold untrained init weights — evaluate real clients only
        # (the min over clients would otherwise report the dummy rows)
        states = self._slice_lanes(self.client_states, self._n_real)
        if self._personal_test is not None:
            if getattr(self, "_personal_eval_fn_pc", None) is None:
                self._personal_eval_fn_pc = jax.jit(jax.vmap(
                    make_eval_fn(self.model, self.hp, batch_size=self._eval_bs),
                    in_axes=(0, 0, 0, 0),
                ))
            res = self._personal_eval_fn_pc(states, *self._personal_test)
        else:
            if getattr(self, "_personal_eval_fn", None) is None:
                self._personal_eval_fn = jax.jit(jax.vmap(
                    make_eval_fn(self.model, self.hp, batch_size=self._eval_bs),
                    in_axes=(0, None, None, None),
                ))
            res = self._personal_eval_fn(states, *self._test)
        return {
            "personalized_test_acc_mean": float(jnp.mean(res["test_acc"])),
            "personalized_test_acc_min": float(jnp.min(res["test_acc"])),
        }
