"""Asynchronous FedAvg — staleness-weighted server updates, no round barrier.

Reference: ``simulation/mpi/async_fedavg/`` (``AsyncFedAVGAggregator.py:14`` —
the server mixes each arriving client model with weight decayed by staleness;
staleness functions constant/polynomial/hinge as in FedAsync, Xie et al.).

Simulation model: server steps t = 0, 1, 2, ...; at each step one client
"arrives" having trained from the global model of version t - s (s = its
staleness, drawn from its speed profile).  A ring buffer of the last K global
models provides the stale starting points — all device-resident, the whole
step jitted.  Mixing: w_{t+1} = (1 - a_s) w_t + a_s w_client, with
a_s = alpha * staleness_func(s).
"""

from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import hparams_from_config
from ..arguments import Config
from ..core import pytree as pt, rng
from ..data.dataset import pad_eval_set, stack_clients
from ..fl.local_sgd import make_eval_fn, make_local_train_fn
from ..obs.metrics import MetricsLogger
from ..parallel import mesh as meshlib

HISTORY = 8  # ring buffer depth == max staleness


def staleness_factor(kind: str, s: jax.Array, alpha: float) -> jax.Array:
    s = s.astype(jnp.float32)
    if kind == "constant":
        return jnp.full_like(s, alpha)
    if kind == "polynomial":
        return alpha * (s + 1.0) ** -0.5
    if kind == "hinge":
        return alpha / (1.0 + jnp.maximum(s - 4.0, 0.0))
    raise ValueError(f"unknown staleness function {kind!r}")


class AsyncSimulator:
    def __init__(self, cfg: Config, dataset, model, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        stacked = stack_clients(dataset, multiple_of=cfg.batch_size)
        spe = max(1, math.ceil(stacked.capacity / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        self._local_train = make_local_train_fn(model, self.hp)
        self.mesh = mesh if mesh is not None else meshlib.mesh_from_config(cfg)

        k0 = rng.root_key(cfg.random_seed)
        sample_x = jnp.asarray(stacked.x[0, : cfg.batch_size])
        self.global_vars = model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            sample_x, train=True,
        )
        # ring buffer of past globals (for stale starting points)
        self.history = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (HISTORY,) + x.shape).copy(), self.global_vars
        )
        self._data = (jnp.asarray(stacked.x), jnp.asarray(stacked.y))
        self.counts = jnp.asarray(stacked.counts)
        self.root_key = k0
        self.step_idx = 0
        self.alpha = float(cfg.async_staleness_alpha)
        self.staleness_kind = cfg.async_staleness_func

        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self._eval_fn = jax.jit(make_eval_fn(model, self.hp, batch_size=eval_bs))
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        self._step_fn = jax.jit(self._make_step_fn())

    def _make_step_fn(self):
        n = self.dataset.n_clients
        alpha = self.alpha
        kind = self.staleness_kind

        def step_fn(global_vars, history, data_x, data_y, counts, step_idx, key):
            skey = rng.round_key(key, step_idx)
            # which client arrives, and how stale is it (slower clients -> staler)
            client = jax.random.randint(jax.random.fold_in(skey, 1), (), 0, n)
            staleness = jax.random.randint(
                jax.random.fold_in(skey, 2), (), 0, jnp.minimum(HISTORY, step_idx + 1)
            )
            start = jax.tree_util.tree_map(
                lambda h: jnp.take(h, (step_idx - staleness) % HISTORY, axis=0), history
            )
            x = jnp.take(data_x, client, axis=0)
            y = jnp.take(data_y, client, axis=0)
            c = jnp.take(counts, client)
            trained, metrics = self._local_train(start, x, y, c, rng.client_key(skey, client), None)
            a = staleness_factor(kind, staleness, alpha)
            new_global = jax.tree_util.tree_map(
                lambda g, t: ((1.0 - a) * g.astype(jnp.float32) + a * t.astype(jnp.float32)).astype(g.dtype),
                global_vars, trained,
            )
            new_history = jax.tree_util.tree_map(
                lambda h, g: h.at[(step_idx + 1) % HISTORY].set(g), history, new_global
            )
            metrics = dict(metrics)
            metrics["staleness"] = staleness.astype(jnp.float32)
            return new_global, new_history, metrics

        return step_fn

    def run_step(self) -> dict:
        self.global_vars, self.history, metrics = self._step_fn(
            self.global_vars, self.history, self._data[0], self._data[1],
            self.counts, jnp.int32(self.step_idx), self.root_key,
        )
        self.step_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self) -> dict:
        return {k: float(v) for k, v in self._eval_fn(self.global_vars, *self._test).items()}

    def run(self) -> list[dict]:
        """comm_round here counts server update steps (client arrivals)."""
        history = []
        for t in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_step()
            metrics.update(round=t, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (t + 1) % self.cfg.frequency_of_the_test == 0 or t == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
        return history
