"""FedSeg — federated semantic segmentation.

Reference: ``simulation/mpi/fedseg`` (``FedSegAggregator.py`` FedAvg over
DeepLab/UNet weights; ``utils.py:56`` ``EvaluationMetricsKeeper`` tracks
pixel accuracy / mIoU / FWIoU).

TPU-native form: a UNet (``models/segmentation.py``) trained with per-pixel
cross-entropy in one vmapped jitted client function; evaluation computes the
confusion-matrix metrics on device.  The data frame stores class labels, so
segmentation masks are synthesized deterministically from each image's class
(class-dependent quadrant layouts) when no real mask data is present —
mirroring the repo-wide synthetic-fallback policy (data/loader.py).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..arguments import Config
from ..core import rng
from ..core.flags import cfg_extra
from ..models.segmentation import UNet, segmentation_metrics
from ..obs.metrics import MetricsLogger


def synthesize_masks(x: np.ndarray, y: np.ndarray, num_classes: int, seed: int = 0) -> np.ndarray:
    """(n, H, W) int masks: the image's class paints a class-dependent
    quadrant; background is class 0.  Deterministic in (x, y, seed)."""
    n, h, w = x.shape[0], x.shape[1], x.shape[2]
    masks = np.zeros((n, h, w), np.int32)
    quad = np.asarray(y) % 4
    hh, ww = h // 2, w // 2
    for q in range(4):
        sel = np.flatnonzero(quad == q)
        r0 = (q // 2) * hh
        c0 = (q % 2) * ww
        for i in sel:
            masks[i, r0 : r0 + hh, c0 : c0 + ww] = int(y[i]) % num_classes
    return masks


class FedSegSimulator:
    def __init__(self, cfg: Config, dataset, mesh=None):
        self.cfg = cfg
        self.dataset = dataset
        self.num_classes = max(int(dataset.class_num), 2)
        self.model = UNet(num_classes=self.num_classes, base=int(cfg_extra(cfg, "seg_base")))
        k0 = rng.root_key(cfg.random_seed)
        feat = tuple(dataset.train_x.shape[1:])
        assert len(feat) == 3, "FedSeg needs (H, W, C) image data"
        x0 = jnp.zeros((2,) + feat, jnp.float32)
        self.variables = self.model.init({"params": k0}, x0)
        self.root_key = k0
        self.round_idx = 0
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)

        # real segmentation datasets (FeTS2021) carry their masks; others get
        # the deterministic synthesized quadrant masks
        if getattr(dataset, "masks", None) is not None:
            masks = np.asarray(dataset.masks, np.int32)
        else:
            masks = synthesize_masks(dataset.train_x, dataset.train_y, self.num_classes, cfg.random_seed)
        counts = np.array([len(ix) for ix in dataset.client_idx])
        cap = int(((counts.max() + cfg.batch_size - 1) // cfg.batch_size) * cfg.batch_size)
        xs = np.zeros((dataset.n_clients, cap) + feat, np.float32)
        ms = np.zeros((dataset.n_clients, cap) + feat[:2], np.int32)
        for i, ix in enumerate(dataset.client_idx):
            reps = np.resize(np.asarray(ix), cap)
            xs[i], ms[i] = dataset.train_x[reps], masks[reps]
        self._x, self._m = jnp.asarray(xs), jnp.asarray(ms)
        self.counts = jnp.asarray(counts, jnp.float32)
        self._client_fn = jax.jit(jax.vmap(self._local_train, in_axes=(None, 0, 0, 0)))

        if getattr(dataset, "test_masks", None) is not None:
            tmask = np.asarray(dataset.test_masks[:256], np.int32)
        else:
            tmask = synthesize_masks(dataset.test_x[:256], dataset.test_y[:256], self.num_classes, cfg.random_seed)
        self._test = (jnp.asarray(dataset.test_x[:256], jnp.float32), jnp.asarray(tmask))
        self._eval = jax.jit(self._eval_fn)

    def _local_train(self, variables, x, m, key):
        cfg = self.cfg
        bs = cfg.batch_size
        steps = max(1, x.shape[0] // bs) * max(1, cfg.epochs)
        opt = optax.sgd(cfg.learning_rate, momentum=0.9)
        state = opt.init(variables)

        def loss_fn(v, xb, mb):
            logits = self.model.apply(v, xb, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(logits, mb).mean()

        def step(carry, i):
            v, state, key = carry
            key, kb = jax.random.split(key)
            ix = jax.random.randint(kb, (bs,), 0, x.shape[0])
            loss, grads = jax.value_and_grad(loss_fn)(v, x[ix], m[ix])
            up, state = opt.update(grads, state, v)
            return (optax.apply_updates(v, up), state, key), loss

        (v, _, _), losses = jax.lax.scan(step, (variables, state, key), jnp.arange(steps))
        return v, losses.mean()

    def _eval_fn(self, variables):
        tx, tm = self._test
        logits = self.model.apply(variables, tx, train=False)
        return segmentation_metrics(logits, tm, self.num_classes)

    def run_round(self) -> dict:
        cfg = self.cfg
        r = self.round_idx
        n = self.dataset.n_clients
        m = min(cfg.client_num_per_round, n)
        sampled = np.asarray(rng.sample_clients(self.root_key, r, n, m))
        rkey = rng.round_key(self.root_key, r)
        keys = jnp.stack([rng.client_key(rkey, int(c)) for c in sampled])
        stacked, losses = self._client_fn(self.variables, self._x[sampled], self._m[sampled], keys)
        w = self.counts[sampled]
        w = w / w.sum()
        self.variables = jax.tree_util.tree_map(lambda s: jnp.tensordot(w, s, axes=1), stacked)
        self.round_idx += 1
        return {"train_loss": float(losses.mean())}

    def run(self) -> list[dict]:
        history = []
        cfg = self.cfg
        for r in range(cfg.comm_round):
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if cfg.frequency_of_the_test and (
                (r + 1) % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1
            ):
                metrics.update({k: float(v) for k, v in self._eval(self.variables).items()})
            self.logger.log(metrics)
            history.append(metrics)
        return history
