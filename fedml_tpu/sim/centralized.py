"""Centralized (non-FL) trainer for baselines.

Reference: ``python/fedml/centralized/centralized_trainer.py:9`` — plain
centralized training used as an accuracy baseline.  Here: the same jitted
local-SGD scan over the whole (un-partitioned) training set.
"""

from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import hparams_from_config
from ..arguments import Config
from ..core import rng
from ..data.dataset import pad_eval_set
from ..fl.local_sgd import make_eval_fn, make_local_train_fn
from ..obs.metrics import MetricsLogger


class CentralizedTrainer:
    def __init__(self, cfg: Config, dataset, model):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        n = dataset.train_x.shape[0]
        spe = max(1, math.ceil(n / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        self._train = jax.jit(make_local_train_fn(model, self.hp))
        eval_bs = min(256, max(32, cfg.test_batch_size))
        tx, ty, n_valid = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        self._eval = jax.jit(make_eval_fn(model, self.hp, batch_size=eval_bs))
        k0 = rng.root_key(cfg.random_seed)
        self.variables = model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            jnp.asarray(dataset.train_x[: cfg.batch_size]), train=True,
        )
        self.key = k0
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)

    def run(self):
        ds = self.dataset
        n_real = ds.train_x.shape[0]
        cap = self.hp.steps_per_epoch * self.cfg.batch_size
        reps = np.resize(np.arange(n_real), cap)  # cyclic tile to batch multiple
        x = jnp.asarray(ds.train_x[reps])
        y = jnp.asarray(ds.train_y[reps])
        n = jnp.int32(n_real)
        history = []
        for r in range(self.cfg.comm_round):
            t0 = time.perf_counter()
            self.variables, metrics = self._train(
                self.variables, x, y, n, rng.round_key(self.key, r), None
            )
            out = {k: float(v) for k, v in metrics.items()}
            out["round"] = r
            out["round_time_s"] = time.perf_counter() - t0
            ev = self._eval(self.variables, *self._test)
            out.update({k: float(v) for k, v in ev.items()})
            self.logger.log(out)
            history.append(out)
        return history
