"""FedNova — normalized averaging for heterogeneous local steps (Wang et al.).

Reference: ``simulation/sp/fednova`` / ``ml/trainer/fednova_trainer.py``
(normalized updates + tau; the FedNova branch of ``agg_operator.py`` passes
through pre-normalized updates).  Semantics:

  client i runs tau_i local steps; d_i = (x - y_i) / a_i
    plain SGD:      a_i = tau_i
    momentum rho:   a_i = (tau_i - rho(1-rho^tau_i)/(1-rho)) / (1-rho)
  server: x <- x - tau_eff * sum_i p_i d_i,  p_i = n_i/n,
          tau_eff = sum_i p_i a_i  (objective-consistent choice)

Heterogeneous tau_i is exactly what ``step_mode="match"`` produces on ragged
Dirichlet shards, so FedNova is the principled companion of the masked scan
(SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm
from ..fl.local_sgd import split_variables
from ..fl.types import ClientOutput


class FedNova(FedAlgorithm):
    name = "FedNova"

    def client_update(self, global_variables, client_state, server_state, x, y, count, key):
        new_vars, metrics = self._local_train(global_variables, x, y, count, key, None)
        g_params, _ = split_variables(global_variables)
        l_params, l_rest = split_variables(new_vars)
        bsz = self.hp.batch_size
        if self.hp.step_mode == "match":
            tau = (self.hp.epochs * ((count + bsz - 1) // bsz)).astype(jnp.float32)
        else:
            tau = jnp.float32(self.hp.local_steps)
        rho = self.hp.momentum
        if rho:
            a_i = (tau - rho * (1.0 - rho**tau) / (1.0 - rho)) / (1.0 - rho)
        else:
            a_i = tau
        d_i = jax.tree_util.tree_map(lambda gx, ly: (gx - ly) / a_i, g_params, l_params)
        contribution = {"d": d_i, "a": a_i, "rest": l_rest}
        return ClientOutput(contribution=contribution, client_state=client_state, metrics=metrics)

    def aggregate(self, stacked, weights):
        d_bar = pt.tree_weighted_mean(stacked["d"], weights)  # sum p_i d_i
        w = weights / jnp.maximum(weights.sum(), 1e-12)
        tau_eff = jnp.sum(w * stacked["a"])  # sum p_i a_i
        rest = pt.tree_weighted_mean(stacked["rest"], weights)
        return {"d": d_bar, "tau_eff": tau_eff, "rest": rest}

    def server_update(self, global_variables, server_state, agg, round_idx):
        g_params, _ = split_variables(global_variables)
        scale = agg["tau_eff"] * self.hp.server_lr
        new_params = jax.tree_util.tree_map(lambda x, d: x - scale * d, g_params, agg["d"])
        return {"params": new_params, **agg["rest"]}, server_state
