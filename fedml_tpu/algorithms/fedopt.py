"""FedOpt — server-side adaptive optimization (Reddi et al.).

Reference: ``simulation/sp/fedopt/fedopt_api.py`` — clients run FedAvg-style
local SGD; the server treats ``w_global - w_avg`` as a pseudo-gradient and
applies a torch optimizer (``optrepo.py`` lookup; sgd w/ momentum default).
Here the server optimizer is an optax transformation over the params pytree;
non-param collections (BN stats) are replaced by their weighted mean, matching
the reference which only optimizes named parameters.

Covers FedOpt/FedOpt_seq, FedAdam, FedYogi, FedAdagrad and FedAvgM (server
momentum) via ``server_optimizer``/``server_momentum`` config.
"""

from __future__ import annotations

import jax

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm, make_server_optimizer
from ..fl.local_sgd import split_variables


class FedOpt(FedAlgorithm):
    name = "FedOpt"

    def __init__(self, hp, cfg=None):
        super().__init__(hp, cfg)
        self._server_opt = make_server_optimizer(hp)

    def init_server_state(self, variables):
        return self._server_opt.init(variables["params"])

    def server_update(self, global_variables, server_state, agg, round_idx):
        g_params, _ = split_variables(global_variables)
        a_params, a_rest = split_variables(agg)
        # pseudo-gradient: descent direction toward the client average
        pseudo_grad = pt.tree_sub(g_params, a_params)
        updates, new_state = self._server_opt.update(pseudo_grad, server_state, g_params)
        import optax

        new_params = optax.apply_updates(g_params, updates)
        return {"params": new_params, **a_rest}, new_state


class FedOptSeq(FedOpt):
    name = "FedOpt_seq"
