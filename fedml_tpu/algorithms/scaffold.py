"""SCAFFOLD — control-variate corrected local SGD (Karimireddy et al.).

Reference: ``simulation/sp/scaffold`` and the SCAFFOLD branch of
``agg_operator.py`` (averages both params and control variates — the
"3-tuple agg" of SURVEY.md §2.3).  Semantics (option II of the paper):

  local step:   y <- y - lr * (g(y) - c_i + c)
  after K steps: c_i+ = c_i - c + (x - y) / (K * lr)
  server:       x <- x + lr_s * mean_S(y - x);  c <- c + (|S|/N) * mean_S(c_i+ - c_i)

Client state = c_i (pytree like params, stacked over all N clients, resident
on device).  Server state = c.  The gradient correction is a ``grad_hook``;
everything else reuses the shared local-SGD scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm
from ..fl.local_sgd import split_variables
from ..fl.types import ClientOutput


class Scaffold(FedAlgorithm):
    name = "SCAFFOLD"

    def grad_hook(self):
        def correct(grads, ctx):
            _, c_global, c_i = ctx
            return jax.tree_util.tree_map(lambda g, c, ci: g + c - ci, grads, c_global, c_i)

        return correct

    def init_server_state(self, variables):
        return pt.tree_zeros_like(variables["params"])

    def init_client_state(self, variables):
        return pt.tree_zeros_like(variables["params"])

    def make_ctx(self, global_variables, client_state, server_state):
        return (global_variables["params"], server_state, client_state)

    def client_update(self, global_variables, client_state, server_state, x, y, count, key):
        ctx = self.make_ctx(global_variables, client_state, server_state)
        new_vars, metrics = self._local_train(global_variables, x, y, count, key, ctx)
        g_params, _ = split_variables(global_variables)
        l_params, l_rest = split_variables(new_vars)
        bsz = self.hp.batch_size
        if self.hp.step_mode == "match":
            k_steps = self.hp.epochs * ((count + bsz - 1) // bsz)
        else:
            k_steps = jnp.int32(self.hp.local_steps)
        inv_klr = 1.0 / (k_steps.astype(jnp.float32) * self.hp.learning_rate)
        # c_i+ = c_i - c + (x - y)/(K lr)
        new_ci = jax.tree_util.tree_map(
            lambda ci, c, gx, ly: ci - c + (gx - ly) * inv_klr,
            client_state, server_state, g_params, l_params,
        )
        delta_c = pt.tree_sub(new_ci, client_state)
        contribution = {"variables": {"params": l_params, **l_rest}, "delta_c": delta_c}
        return ClientOutput(contribution=contribution, client_state=new_ci, metrics=metrics)

    def aggregate(self, stacked, weights):
        # params sample-weighted (reference SCAFFOLD branch averages both);
        # delta_c uniformly (paper: 1/|S| sum)
        agg_vars = pt.tree_weighted_mean(stacked["variables"], weights)
        uni = jnp.ones_like(weights)
        agg_dc = pt.tree_weighted_mean(stacked["delta_c"], uni)
        return {"variables": agg_vars, "delta_c": agg_dc}

    def server_update(self, global_variables, server_state, agg, round_idx):
        g_params, _ = split_variables(global_variables)
        a_params, a_rest = split_variables(agg["variables"])
        lr_s = self.hp.server_lr
        new_params = jax.tree_util.tree_map(lambda x, a: x + lr_s * (a - x), g_params, a_params)
        frac = (self.cfg.client_num_per_round / self.cfg.client_num_in_total) if self.cfg else 1.0
        new_c = pt.tree_axpy(frac, agg["delta_c"], server_state)
        return {"params": new_params, **a_rest}, new_c
