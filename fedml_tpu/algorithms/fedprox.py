"""FedProx — proximal-term local objective.

Reference: ``simulation/sp/fedprox`` / ``ml/trainer/fedprox_trainer.py`` add
``mu/2 * ||w - w_global||^2`` to the local loss; aggregation is FedAvg's
weighted mean (``agg_operator.py`` FedProx branch).  Here the proximal term is
a ``loss_extra`` hook — the global params ride in through the hook context, so
the same compiled local-SGD scan serves both FedAvg and FedProx.
"""

from __future__ import annotations

import jax

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm


class FedProx(FedAlgorithm):
    name = "FedProx"

    def loss_extra(self):
        mu = self.hp.fedprox_mu

        def prox(params, ctx):
            global_params = ctx
            return 0.5 * mu * pt.tree_sq_norm(pt.tree_sub(params, global_params))

        return prox

    def make_ctx(self, global_variables, client_state, server_state):
        return global_variables["params"]
