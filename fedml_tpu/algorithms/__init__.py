"""Algorithm registry.

Replaces the reference's dispatch in ``simulation/simulator.py:28-240``
(``federated_optimizer`` string -> per-backend API class) with one registry of
backend-agnostic algorithms: each runs unchanged on the sequential SP backend
and the sharded MESH backend because it is pure functions over pytrees.
"""

from __future__ import annotations

from .. import constants as C
from ..core.flags import cfg_extra
from ..fl.algorithm import FedAlgorithm
from ..fl.types import HParams
from .fedavg import FedAvg, FedAvgSeq
from .feddyn import FedDyn
from .fednova import FedNova
from .fedopt import FedOpt, FedOptSeq
from .fedprox import FedProx
from .fedsgd import FedSGD
from .mime import Mime
from .scaffold import Scaffold

_REGISTRY = {
    C.FEDERATED_OPTIMIZER_FEDAVG: FedAvg,
    C.FEDERATED_OPTIMIZER_FEDAVG_SEQ: FedAvgSeq,
    C.FEDERATED_OPTIMIZER_FEDOPT: FedOpt,
    C.FEDERATED_OPTIMIZER_FEDOPT_SEQ: FedOptSeq,
    C.FEDERATED_OPTIMIZER_FEDPROX: FedProx,
    C.FEDERATED_OPTIMIZER_FEDNOVA: FedNova,
    C.FEDERATED_OPTIMIZER_FEDDYN: FedDyn,
    C.FEDERATED_OPTIMIZER_SCAFFOLD: Scaffold,
    C.FEDERATED_OPTIMIZER_MIME: Mime,
    C.FEDERATED_OPTIMIZER_FEDSGD: FedSGD,
}


def names() -> list[str]:
    return sorted(_REGISTRY)


def create(cfg, hp: HParams = None) -> FedAlgorithm:
    """Build the algorithm named by ``cfg.federated_optimizer``."""
    if hp is None:
        hp = hparams_from_config(cfg)
    try:
        cls = _REGISTRY[cfg.federated_optimizer]
    except KeyError:
        raise ValueError(
            f"unknown federated_optimizer {cfg.federated_optimizer!r}; known: {names()}"
        ) from None
    return cls(hp, cfg)


def hparams_from_config(cfg, steps_per_epoch: int = 0) -> HParams:
    return HParams(
        epochs=cfg.epochs,
        batch_size=cfg.batch_size,
        learning_rate=cfg.learning_rate,
        momentum=cfg.momentum,
        weight_decay=cfg.weight_decay,
        client_optimizer=cfg.client_optimizer,
        server_optimizer=cfg.server_optimizer,
        server_lr=cfg.server_lr,
        server_momentum=cfg.server_momentum,
        fedprox_mu=cfg.fedprox_mu,
        feddyn_alpha=cfg.feddyn_alpha,
        mime_momentum=cfg.mime_momentum,
        steps_per_epoch=steps_per_epoch,
        step_mode=getattr(cfg, "step_mode", "match"),
        compute_dtype=cfg.compute_dtype,
        fused_blocks=bool(cfg_extra(cfg, "fused_blocks")),
    )
