"""MimeLite — server momentum applied inside local steps (Karimireddy et al.).

Reference: ``simulation/sp/mime`` (Mime branch of ``agg_operator.py`` averages
params and grads).  MimeLite semantics:

  local step uses the server momentum m (frozen during the round):
      d = (1 - beta) * g(y) + beta * m ;  y <- y - lr * d
  clients also report grad f_i(x) (full-batch at the global point)
  server:  x <- mean_S(y_i);  m <- (1 - beta) * mean_S(grad f_i(x)) + beta * m

Server state = m.  The momentum mix is a ``grad_hook``; the full-batch
gradient reuses ``make_full_grad_fn``'s batched scan.
"""

from __future__ import annotations

import jax

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm
from ..fl.local_sgd import make_full_grad_fn
from ..fl.types import ClientOutput


class Mime(FedAlgorithm):
    name = "Mime"

    def build(self, model):
        super().build(model)
        self._full_grad = make_full_grad_fn(model, self.hp)
        return self

    def grad_hook(self):
        beta = self.hp.mime_momentum

        def mix(grads, ctx):
            m = ctx
            return jax.tree_util.tree_map(lambda g, mi: (1 - beta) * g + beta * mi, grads, m)

        return mix

    def init_server_state(self, variables):
        return pt.tree_zeros_like(variables["params"])

    def make_ctx(self, global_variables, client_state, server_state):
        return server_state

    def client_update(self, global_variables, client_state, server_state, x, y, count, key):
        ctx = self.make_ctx(global_variables, client_state, server_state)
        new_vars, metrics = self._local_train(global_variables, x, y, count, key, ctx)
        gkey = jax.random.fold_in(key, 0x6D696D65)
        fg = self._full_grad(global_variables, x, y, count, gkey)
        return ClientOutput(
            contribution={"variables": new_vars, "full_grad": fg},
            client_state=client_state, metrics=metrics,
        )

    def aggregate(self, stacked, weights):
        return {
            "variables": pt.tree_weighted_mean(stacked["variables"], weights),
            "full_grad": pt.tree_weighted_mean(stacked["full_grad"], weights),
        }

    def server_update(self, global_variables, server_state, agg, round_idx):
        beta = self.hp.mime_momentum
        new_m = jax.tree_util.tree_map(
            lambda g, m: (1 - beta) * g + beta * m, agg["full_grad"], server_state
        )
        return agg["variables"], new_m
