"""FedAvg / FedAvg_seq.

The canonical algorithm: clients run local SGD from the global weights and the
server takes the sample-weighted mean — exactly the math of the reference's
``simulation/sp/fedavg/fedavg_api.py:144-159`` (``_aggregate``) and the
``FedAvg`` branch of ``ml/aggregator/agg_operator.py:33``.  The base
:class:`~fedml_tpu.fl.algorithm.FedAlgorithm` already implements it; these
classes exist to carry the registry names.

``FedAvg_seq`` in the reference differs only in worker scheduling (sequential
client simulation per GPU, ``simulation/mpi/fedavg_seq``); on the MESH backend
scheduling is the mesh sharding itself, so the algorithm math is identical.
"""

from __future__ import annotations

from ..fl.algorithm import FedAlgorithm


class FedAvg(FedAlgorithm):
    name = "FedAvg"


class FedAvgSeq(FedAlgorithm):
    name = "FedAvg_seq"
