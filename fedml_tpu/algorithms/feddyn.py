"""FedDyn — dynamic regularization (Acar et al.).

Reference: ``simulation/sp/feddyn`` (the FedDyn branch of ``agg_operator.py``
sums client weights).  Semantics:

  local objective: f_i(w) - <lambda_i, w> + (alpha/2)||w - x||^2
  after training:  lambda_i <- lambda_i - alpha (y_i - x)
  server:          h <- h - alpha (|S|/N) mean_S(y_i - x)
                   x <- mean_S(y_i) - h / alpha

Client state = lambda_i (per-client linear term), server state = h.
Both live as stacked device pytrees; the extra loss terms are a pure
``loss_extra`` hook over the shared scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm
from ..fl.local_sgd import split_variables
from ..fl.types import ClientOutput


class FedDyn(FedAlgorithm):
    name = "FedDyn"

    def loss_extra(self):
        alpha = self.hp.feddyn_alpha

        def extra(params, ctx):
            global_params, lam = ctx
            lin = pt.tree_dot(lam, params)
            prox = 0.5 * alpha * pt.tree_sq_norm(pt.tree_sub(params, global_params))
            return prox - lin

        return extra

    def init_server_state(self, variables):
        return pt.tree_zeros_like(variables["params"])

    def init_client_state(self, variables):
        return pt.tree_zeros_like(variables["params"])

    def make_ctx(self, global_variables, client_state, server_state):
        return (global_variables["params"], client_state)

    def client_update(self, global_variables, client_state, server_state, x, y, count, key):
        ctx = self.make_ctx(global_variables, client_state, server_state)
        new_vars, metrics = self._local_train(global_variables, x, y, count, key, ctx)
        g_params, _ = split_variables(global_variables)
        l_params, l_rest = split_variables(new_vars)
        alpha = self.hp.feddyn_alpha
        delta = pt.tree_sub(l_params, g_params)
        new_lam = pt.tree_axpy(-alpha, delta, client_state)
        contribution = {"variables": {"params": l_params, **l_rest}, "delta": delta}
        return ClientOutput(contribution=contribution, client_state=new_lam, metrics=metrics)

    def aggregate(self, stacked, weights):
        uni = jnp.ones_like(weights)  # FedDyn uses uniform client means
        return {
            "variables": pt.tree_weighted_mean(stacked["variables"], uni),
            "delta": pt.tree_weighted_mean(stacked["delta"], uni),
        }

    def server_update(self, global_variables, server_state, agg, round_idx):
        alpha = self.hp.feddyn_alpha
        frac = (self.cfg.client_num_per_round / self.cfg.client_num_in_total) if self.cfg else 1.0
        new_h = pt.tree_axpy(-alpha * frac, agg["delta"], server_state)
        a_params, a_rest = split_variables(agg["variables"])
        new_params = jax.tree_util.tree_map(lambda a, h: a - h / alpha, a_params, new_h)
        return {"params": new_params, **a_rest}, new_h
