"""FedSGD — one full-batch gradient per round, with optional compression.

Reference: ``sp_fedsgd_cifar10_resnet20_example`` recipe (BASELINE.md) — each
client reports grad f_i(x); the server takes the sample-weighted mean and does
one SGD step.  Compression (``topk | eftopk | quantize | qsgd``,
``ml/utils/compression.py``) applies per client on the flat gradient; EF-TopK
residuals are the per-client persistent state (explicit, device-resident),
replacing the reference's stateful host-side ``EFTopKCompressor`` object.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import pytree as pt
from ..fl.algorithm import FedAlgorithm, make_server_optimizer
from ..fl.local_sgd import make_full_grad_fn, split_variables
from ..fl.types import ClientOutput
from ..ops import compression as comp


class FedSGD(FedAlgorithm):
    name = "FedSGD"

    def __init__(self, hp, cfg=None):
        super().__init__(hp, cfg)
        self._server_opt = make_server_optimizer(hp)
        self.compression = getattr(cfg, "compression", "no") if cfg else "no"
        self.ratio = getattr(cfg, "compression_ratio", 0.01) if cfg else 0.01
        self.qlevel = getattr(cfg, "quantize_level", 8) if cfg else 8

    def build(self, model):
        super().build(model)
        self._full_grad = make_full_grad_fn(model, self.hp)
        return self

    def init_server_state(self, variables):
        return self._server_opt.init(variables["params"])

    def init_client_state(self, variables):
        if self.compression == "eftopk":
            flat, _ = pt.tree_flatten_to_vector(variables["params"])
            return jnp.zeros_like(flat)
        return None

    def client_update(self, global_variables, client_state, server_state, x, y, count, key):
        grad = self._full_grad(global_variables, x, y, count, key)
        new_state = client_state
        if self.compression != "no":
            flat, unravel = pt.tree_flatten_to_vector(grad)
            flat, new_state = comp.compress(
                self.compression, flat, key=jax.random.fold_in(key, 7),
                residual=client_state, ratio=self.ratio, quantize_level=self.qlevel,
            )
            grad = unravel(flat)
        metrics = {
            "train_loss": jnp.float32(0.0),
            "num_steps": jnp.float32(1.0),
            "num_samples": count.astype(jnp.float32),
        }
        return ClientOutput(contribution=grad, client_state=new_state, metrics=metrics)

    def server_update(self, global_variables, server_state, agg, round_idx):
        g_params, g_rest = split_variables(global_variables)
        updates, new_state = self._server_opt.update(agg, server_state, g_params)
        import optax

        new_params = optax.apply_updates(g_params, updates)
        return {"params": new_params, **g_rest}, new_state
