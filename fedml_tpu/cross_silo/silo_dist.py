"""A silo that spans processes — hierarchical cross-silo local training.

Parity with the reference's torchrun-DDP-in-silo machinery
(``cross_silo/client/client_launcher.py:46`` spawns one torchrun process
group per silo; ``process_group_manager.py:8`` builds the NCCL/gloo group;
``fedml_trainer_dist_adapter.py`` wraps the trainer in DDP): a silo's local
SGD runs data-parallel over EVERY process of the silo, while only the silo
master (process 0) speaks the FL protocol to the server.

TPU-native translation — no DDP wrapper, no process group objects:

- All silo processes share one ``jax.distributed`` runtime; the silo mesh is
  a ``data`` axis over the GLOBAL device set (multi-controller JAX).
- The local-SGD step is the SAME jitted program as the single-process
  trainer, with each minibatch sharding-constrained over the global ``data``
  axis — GSPMD partitions fwd/bwd per device and inserts the gradient
  all-reduce that DDP does with NCCL hooks.  Numerics are IDENTICAL to the
  1-process silo (asserted by test).
- The FL transport (INPROC/TCP/gRPC/MQTT) stays single-process on the
  master.  Followers run :func:`run_silo_follower`: a lockstep loop fed by
  ``multihost_utils.broadcast_one_to_all`` — the master broadcasts
  (command, round, client_idx) + the global params before each collective
  train call, which is the multi-controller invariant (every process issues
  the same programs in the same order).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..fl.local_sgd import make_local_train_fn
from ..parallel import mesh as meshlib, multihost
from .client import FedMLTrainer

log = logging.getLogger("fedml_tpu.cross_silo.silo_dist")

CMD_TRAIN = 1
CMD_FINISH = 2


def _global_data_mesh():
    devs = jax.devices()
    return meshlib.make_mesh((meshlib.AXIS_DATA,), (len(devs),), devs)


def _make_silo_train_fn(cfg, model, hp):
    """The shared jitted local-SGD program: batch constrained over the global
    ``data`` axis so every silo process computes a slice of each minibatch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    silo_mesh = _global_data_mesh()
    n = len(jax.devices())
    if cfg.batch_size % n != 0:
        raise ValueError(
            f"distributed silo needs batch_size ({cfg.batch_size}) divisible "
            f"by the global device count ({n})"
        )

    def batch_constraint(bx, by):
        cx = jax.lax.with_sharding_constraint(
            bx, NamedSharding(silo_mesh, P(meshlib.AXIS_DATA, *([None] * (bx.ndim - 1)))))
        cy = jax.lax.with_sharding_constraint(
            by, NamedSharding(silo_mesh, P(meshlib.AXIS_DATA, *([None] * (by.ndim - 1)))))
        return cx, cy

    return jax.jit(make_local_train_fn(model, hp, batch_constraint=batch_constraint))


class DistributedSiloTrainer(FedMLTrainer):
    """Silo-master trainer: same ``train()`` contract as FedMLTrainer, but
    each call first broadcasts (TRAIN, round, client_idx) + params so the
    follower processes join the collective program."""

    def __init__(self, cfg, model, x: np.ndarray, y: np.ndarray):
        super().__init__(cfg, model, x, y)
        if not multihost.is_multiprocess():
            raise RuntimeError(
                "DistributedSiloTrainer requires an initialized multi-process "
                "jax.distributed runtime (call multihost.ensure_initialized)"
            )
        # replace the local-devices program with the global-mesh program
        self._train = _make_silo_train_fn(cfg, model, self.hp)
        self.dp_active = True
        self._finished = False

    def train(self, global_vars, round_idx: int, seed_key, client_idx: int = 0) -> tuple:
        from jax.experimental import multihost_utils

        multihost_utils.broadcast_one_to_all(
            np.asarray([CMD_TRAIN, round_idx, client_idx], np.int32)
        )
        variables = jax.tree_util.tree_map(np.asarray, jax.device_get(global_vars))
        variables = multihost_utils.broadcast_one_to_all(variables)
        key = rng.client_key(rng.round_key(seed_key, round_idx), client_idx)
        new_vars, _metrics = self._train(variables, self.x, self.y, self.count, key, None)
        return jax.device_get(new_vars), float(self.count)

    def finish(self) -> None:
        """Release the followers (master-side, after the FL run ends).
        Idempotent: a second CMD_FINISH broadcast would block forever because
        the followers exited after the first."""
        if self._finished:
            return
        self._finished = True
        from jax.experimental import multihost_utils

        multihost_utils.broadcast_one_to_all(
            np.asarray([CMD_FINISH, 0, 0], np.int32)
        )


def run_silo_follower(cfg, model, x: np.ndarray, y: np.ndarray) -> int:
    """Follower-process loop (reference: the non-zero torchrun ranks running
    ``fedml_trainer_dist_adapter`` under DDP).  Executes the identical jitted
    train program in lockstep with the master until CMD_FINISH.  Returns the
    number of rounds trained."""
    from jax.experimental import multihost_utils

    trainer = FedMLTrainer.__new__(FedMLTrainer)
    FedMLTrainer.__init__(trainer, cfg, model, x, y)
    train_fn = _make_silo_train_fn(cfg, model, trainer.hp)
    seed_key = rng.root_key(cfg.random_seed)
    # params template for the broadcast collective: same deterministic init
    # as the server's (seeded), so shapes/dtypes match the master's broadcast
    template = _follower_params_template(cfg, model, x)
    rounds = 0
    while True:
        cmd = np.asarray(multihost_utils.broadcast_one_to_all(
            np.zeros(3, np.int32)
        ))
        if int(cmd[0]) == CMD_FINISH:
            log.info("silo follower: finish after %d rounds", rounds)
            return rounds
        round_idx, client_idx = int(cmd[1]), int(cmd[2])
        variables = multihost_utils.broadcast_one_to_all(template)
        key = rng.client_key(rng.round_key(seed_key, round_idx), client_idx)
        train_fn(variables, trainer.x, trainer.y, trainer.count, key, None)
        rounds += 1


def _follower_params_template(cfg, model, x):
    """Host-side zero pytree with the global model's structure (the broadcast
    collective needs matching shapes on every process)."""
    k0 = rng.root_key(cfg.random_seed)
    sample = jnp.asarray(x[: cfg.batch_size])
    variables = jax.eval_shape(
        lambda k: model.init({"params": jax.random.fold_in(k, 1),
                              "dropout": jax.random.fold_in(k, 2)}, sample, train=True),
        k0,
    )
    return jax.tree_util.tree_map(lambda s: np.zeros(s.shape, s.dtype), variables)
