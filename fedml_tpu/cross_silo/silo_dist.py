"""A silo that spans processes — hierarchical cross-silo local training.

Parity with the reference's torchrun-DDP-in-silo machinery
(``cross_silo/client/client_launcher.py:46`` spawns one torchrun process
group per silo; ``process_group_manager.py:8`` builds the NCCL/gloo group;
``fedml_trainer_dist_adapter.py`` wraps the trainer in DDP): a silo's local
SGD runs data-parallel over EVERY process of the silo, while only the silo
master (process 0) speaks the FL protocol to the server.

TPU-native translation — no DDP wrapper, no process group objects:

- All silo processes share one ``jax.distributed`` runtime; the silo mesh is
  a ``data`` axis over the GLOBAL device set (multi-controller JAX).
- The local-SGD step is the SAME jitted program as the single-process
  trainer, with each minibatch sharding-constrained over the global ``data``
  axis — GSPMD partitions fwd/bwd per device and inserts the gradient
  all-reduce that DDP does with NCCL hooks.  Numerics are IDENTICAL to the
  1-process silo (asserted by test).
- The FL transport (INPROC/TCP/gRPC/MQTT) stays single-process on the
  master.  Followers run :func:`run_silo_follower`: a lockstep loop fed by
  ``multihost_utils.broadcast_one_to_all`` — the master broadcasts
  (command, round, client_idx) + the global params before each collective
  train call, which is the multi-controller invariant (every process issues
  the same programs in the same order).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng
from ..parallel import mesh as meshlib, multihost
from .client import FedMLTrainer

log = logging.getLogger("fedml_tpu.cross_silo.silo_dist")

CMD_TRAIN = 1
CMD_FINISH = 2


class DistributedSiloTrainer(FedMLTrainer):
    """Silo-master trainer: same ``train()`` contract as FedMLTrainer, but
    each call first broadcasts (TRAIN, round, client_idx) + params so the
    follower processes join the collective program.  The jitted program is
    the base trainer's, with its minibatch constraint over the GLOBAL
    ``data`` mesh instead of the local device set."""

    def __init__(self, cfg, model, x: np.ndarray, y: np.ndarray):
        self._finished = False
        super().__init__(cfg, model, x, y)

    def _batch_constraint(self, cfg):
        if not multihost.is_multiprocess():
            raise RuntimeError(
                "DistributedSiloTrainer requires an initialized multi-process "
                "jax.distributed runtime (call multihost.ensure_initialized)"
            )
        devs = jax.devices()
        if cfg.batch_size % len(devs) != 0:
            raise ValueError(
                f"distributed silo needs batch_size ({cfg.batch_size}) "
                f"divisible by the global device count ({len(devs)})"
            )
        self.dp_active = True
        from .client import data_parallel_constraint

        return data_parallel_constraint(
            meshlib.make_mesh((meshlib.AXIS_DATA,), (len(devs),), devs)
        )

    def train(self, global_vars, round_idx: int, seed_key, client_idx: int = 0) -> tuple:
        from jax.experimental import multihost_utils

        multihost_utils.broadcast_one_to_all(
            np.asarray([CMD_TRAIN, round_idx, client_idx], np.int32)
        )
        variables = jax.tree_util.tree_map(np.asarray, jax.device_get(global_vars))
        variables = multihost_utils.broadcast_one_to_all(variables)
        key = rng.client_key(rng.round_key(seed_key, round_idx), client_idx)
        new_vars, _metrics = self._train(variables, self.x, self.y, self.count, key, None)
        return jax.device_get(new_vars), float(self.count)

    def finish(self) -> None:
        """Release the followers (master-side, after the FL run ends).
        Idempotent: a second CMD_FINISH broadcast would block forever because
        the followers exited after the first."""
        if self._finished:
            return
        self._finished = True
        from jax.experimental import multihost_utils

        multihost_utils.broadcast_one_to_all(
            np.asarray([CMD_FINISH, 0, 0], np.int32)
        )


def run_silo_follower(cfg, model, x: np.ndarray, y: np.ndarray) -> int:
    """Follower-process loop (reference: the non-zero torchrun ranks running
    ``fedml_trainer_dist_adapter`` under DDP).  Executes the identical jitted
    train program in lockstep with the master until CMD_FINISH.  Returns the
    number of rounds trained."""
    from jax.experimental import multihost_utils

    # same class as the master -> the identical jitted global-mesh program
    trainer = DistributedSiloTrainer(cfg, model, x, y)
    seed_key = rng.root_key(cfg.random_seed)
    # params template for the broadcast collective: shapes/dtypes must match
    # the master's broadcast (values are ignored on non-zero processes)
    template = _follower_params_template(cfg, model, x)
    rounds = 0
    while True:
        cmd = np.asarray(multihost_utils.broadcast_one_to_all(
            np.zeros(3, np.int32)
        ))
        if int(cmd[0]) == CMD_FINISH:
            log.info("silo follower: finish after %d rounds", rounds)
            return rounds
        round_idx, client_idx = int(cmd[1]), int(cmd[2])
        variables = multihost_utils.broadcast_one_to_all(template)
        key = rng.client_key(rng.round_key(seed_key, round_idx), client_idx)
        trainer._train(variables, trainer.x, trainer.y, trainer.count, key, None)
        rounds += 1


def _follower_params_template(cfg, model, x):
    """Host-side zero pytree with the global model's structure (the broadcast
    collective needs matching shapes on every process)."""
    k0 = rng.root_key(cfg.random_seed)
    sample = jnp.asarray(x[: cfg.batch_size])
    variables = jax.eval_shape(
        lambda k: model.init({"params": jax.random.fold_in(k, 1),
                              "dropout": jax.random.fold_in(k, 2)}, sample, train=True),
        k0,
    )
    return jax.tree_util.tree_map(lambda s: np.zeros(s.shape, s.dtype), variables)
