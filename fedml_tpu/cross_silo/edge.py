"""Hierarchical aggregation tree — edge aggregators over the real protocol.

Every upload used to terminate at ONE server, so at fleet scale the root is
a fan-in and WAN-byte bottleneck (the communication-practicality survey's
inter-tier bandwidth asymmetry; the reference's hierarchical cross-silo mode
says the fix is a tree).  This module adds the tree WITHOUT a new protocol:

    client -> edge aggregator -> (optional region) -> root

- :func:`build_topology` turns ``extra.hier_fanout`` / ``extra.hier_depth``
  (or an explicit ``extra.hier_topology`` dict) into a :class:`HierTopology`
  — a pure rank map shared by the root (dispatch routing), every client
  (upload destination), and the edge processes themselves.  Flags unset ->
  None, and every participant runs the flat protocol byte-identically.
- :class:`EdgeAggregatorManager` is a server-shaped relay: it registers the
  EXISTING message types, re-dispatches the global down its subtree, folds
  its children's model replies streaming (peak buffered <= 2: the running
  sum + the one in-flight decode), and ships ONE pre-folded weighted partial
  upward as a control-tagged upload (``MSG_ARG_KEY_HIER_PARTIAL``) carrying
  the folded sample mass.  Root fan-in drops from O(clients) connections and
  bytes to O(edges).
- :class:`EdgePartialFold` is the fold core, factored out of the manager so
  the bitwise pins (tree fold == flat streaming fold) and the sim parity
  bridge (``sim/hierarchical.py`` segment-sum group fold == protocol edge
  fold) can drive it without a transport.

Bitwise discipline (the reason ``supports_associative_fold`` gates this):
the edge computes ``sum_c f32(w_c) * f32(x_c)`` with EXACTLY the op sequence
the flat server fold runs (``parallel/stream_fold.py``), and the parent
folds an arriving partial with a direct add (``fold_partial_leaf``) — no
unit-weight multiply, no normalization at the edge — so the tree introduces
no arithmetic the flat fold didn't do.  A parent merging partial ``p`` after
prefix ``s`` computes ``s + p`` where the flat fold computed
``(..(s + a1) + a2..)`` over p's terms: identical bits whenever the adds are
exact or the subtree has a single term, and algebraically equal always.
The protocol pin test fixes arrival order and a topology whose op sequences
coincide; the full-tree pin uses exactly-representable payloads.

Per-hop composition with existing machinery: ``extra.hier_hop_codec``
re-encodes the shipped partial with qsgd8/topk (comm/codecs.py — the
edge->root hop then costs compressed bytes, measured by
``fedml_hier_hop_bytes_total``), chunked transport frames apply to both hops
(``extra.comm_chunk_bytes`` is honored by the same comm managers), uploads
carry exactly-once keys when journaled, and a per-node
:class:`~fedml_tpu.obs.health.ClientHealthLedger` attributes RTT/breaches to
THIS hop's children.  A SIGKILLed edge recovers from its own
:class:`~fedml_tpu.cross_silo.journal.ServerJournal` (partial sums + wire
template in the sidecar — no model needed to restore) and drains the child
uploads that queued while it was dead; the chaos soak closes the
zero-unaccounted-loss identity over it.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from ..comm import codecs, wire
from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from ..core.flags import cfg_extra
from ..obs import registry as obsreg
from . import message_define as md

log = logging.getLogger("fedml_tpu.cross_silo.edge")

__all__ = [
    "EdgeAggregatorManager",
    "EdgePartialFold",
    "HierTopology",
    "build_topology",
    "edge_fold_supported",
    "round_robin_groups",
]

# -- fedml_hier_* metric families (docs/METRICS.md "hier" section) -----------
HOP_BYTES = obsreg.REGISTRY.counter(
    "fedml_hier_hop_bytes_total",
    "Model-upload wire bytes by aggregation-tree hop: client_edge (child "
    "replies into an edge), edge_region (edge partials into a region tier), "
    "edge_root (partials into the root).  The tentpole quantity: edge_root "
    "per round is O(edges) where the flat protocol's root ingress was "
    "O(clients).",
    labels=("hop",),
)
PARTIALS_FOLDED = obsreg.REGISTRY.counter(
    "fedml_hier_partials_folded_total",
    "Pre-folded weighted partials merged by a parent (direct add — bitwise "
    "a continuation of the child's fold), by tier of the folding node.",
    labels=("tier",),
)
PARTIALS_SENT = obsreg.REGISTRY.counter(
    "fedml_hier_partials_sent_total",
    "Partials shipped upward by aggregator nodes (one per node per round "
    "when every child arrived; straggler timeouts ship what landed).",
)
EDGE_FOLDS = obsreg.REGISTRY.counter(
    "fedml_hier_edge_folds_total",
    "Child model replies folded streaming at aggregator nodes.",
)
EDGE_RELAYS = obsreg.REGISTRY.counter(
    "fedml_hier_edge_relays_total",
    "Child uploads store-and-forwarded unchanged to the parent (fold "
    "unsupported for the config, or a frame the template doesn't match).",
)
EDGE_DEDUPED = obsreg.REGISTRY.counter(
    "fedml_hier_edge_deduped_total",
    "Duplicate child uploads dropped at aggregator nodes (exactly-once "
    "keys, or a keyless redelivery within the round).",
)
TREE_DEPTH = obsreg.REGISTRY.gauge(
    "fedml_hier_tree_depth",
    "Aggregation tree depth (2 = client->edge->root, 3 adds regions; 1 "
    "when the hier flags are unset and the protocol is flat).",
)
TREE_FANOUT = obsreg.REGISTRY.gauge(
    "fedml_hier_tree_fanout",
    "Largest child count of any aggregation-tree node (root included).",
)
TREE_EDGES = obsreg.REGISTRY.gauge(
    "fedml_hier_tree_edges",
    "Aggregator nodes in the tree (edges + regions).",
)

#: idempotence keys remembered per child at an aggregator node — same bound
#: (and reasoning) as the root's table; defined locally because edge.py must
#: not import server.py (server.py imports the hop metrics from here)
DEDUP_KEYS_PER_CHILD = 16

_VALID_HOP_CODECS = ("qsgd8", "topk")


def round_robin_groups(n: int, groups: int) -> np.ndarray:
    """``(n,) int32`` member -> group map, round-robin: ``arange(n) % G``.

    THE shared group-map construction: ``sim/hierarchical.py`` uses it for
    its ``group_assignment=round_robin`` mode and :func:`build_topology`
    for the protocol tree, so the sim parity bridge compares the same
    partition on both sides.
    """
    return (np.arange(int(n)) % max(1, int(groups))).astype(np.int32)


class HierTopology:
    """Declarative aggregation tree over the cross-silo rank space.

    Ranks: root = 0, clients = 1..N (unchanged), edge aggregators =
    N+1..N+E, regions (depth 3) = N+E+1..N+E+R — every node on the same
    comm fabric, so no transport learns anything new.  ``edges`` is a list
    of client-rank groups (one per edge, every client in exactly one);
    ``regions`` optionally groups edge ORDINALS (0-based into ``edges``)
    under region nodes.
    """

    def __init__(self, n_clients: int, edges: list, regions: Optional[list] = None):
        n = int(n_clients)
        groups = [[int(c) for c in grp] for grp in edges]
        if any(not grp for grp in groups):
            raise ValueError("hier topology: empty edge group")
        flat = sorted(c for grp in groups for c in grp)
        if flat != list(range(1, n + 1)):
            raise ValueError(
                f"hier topology: edge groups must partition client ranks "
                f"1..{n} exactly once (got {flat})")
        self.n_clients = n
        E = len(groups)
        self.edge_ranks = [n + 1 + i for i in range(E)]
        self.children_of: dict[int, list[int]] = {
            self.edge_ranks[i]: groups[i] for i in range(E)}
        self.parent_of: dict[int, int] = {}
        for i, grp in enumerate(groups):
            for c in grp:
                self.parent_of[c] = self.edge_ranks[i]
        self.region_ranks: list[int] = []
        if regions:
            rgroups = [[int(o) for o in grp] for grp in regions]
            if any(not grp for grp in rgroups):
                raise ValueError("hier topology: empty region group")
            ords = sorted(o for grp in rgroups for o in grp)
            if ords != list(range(E)):
                raise ValueError(
                    f"hier topology: region groups must partition edge "
                    f"ordinals 0..{E - 1} exactly once (got {ords})")
            base = n + 1 + E
            self.region_ranks = [base + j for j in range(len(rgroups))]
            for j, grp in enumerate(rgroups):
                members = [self.edge_ranks[o] for o in grp]
                self.children_of[self.region_ranks[j]] = members
                for m in members:
                    self.parent_of[m] = self.region_ranks[j]
            for r in self.region_ranks:
                self.parent_of[r] = 0
        else:
            for e in self.edge_ranks:
                self.parent_of[e] = 0
        self.aggregator_ranks = self.edge_ranks + self.region_ranks
        #: the root's direct children (regions when present, else edges) —
        #: the O(edges) fan-in the flat protocol's O(clients) collapses to
        self.root_children = list(self.region_ranks or self.edge_ranks)
        self.depth = 3 if self.region_ranks else 2
        # group map shared with the sim (0-based client index -> edge ordinal)
        g = np.empty(n, np.int32)
        for i, grp in enumerate(groups):
            for c in grp:
                g[c - 1] = i
        self.group_of = g
        self.world_size = 1 + n + len(self.aggregator_ranks)

    def parent(self, rank: int) -> int:
        """Upload destination for ``rank`` (0 for root children and unknown
        ranks — the flat default)."""
        return self.parent_of.get(int(rank), 0)

    def max_fanout(self) -> int:
        fans = [len(self.root_children)]
        fans += [len(v) for v in self.children_of.values()]
        return max(fans)

    def export_gauges(self) -> None:
        TREE_DEPTH.set(self.depth)
        TREE_FANOUT.set(self.max_fanout())
        TREE_EDGES.set(len(self.aggregator_ranks))

    def dispatch_plan(self, selected, skip=()) -> dict:
        """Route one round's dispatch down the tree: ``{root_child_rank:
        HIER_CHILDREN spec}`` covering ``selected`` minus ``skip`` (clients
        whose fold the root already holds — mid-round journal resume).
        Edge-level spec: ``{"clients": {rank: client_index}}``; region-level:
        ``{"aggs": {edge_rank: edge_spec}}``.  Keys are strings — the spec
        rides the JSON control section.  Aggregators left with no wanted
        client are omitted entirely (no empty dispatch, no empty partial).
        """
        drop = set(int(s) for s in skip)
        per_edge: dict[int, dict] = {}
        for c in selected:
            c = int(c)
            if c in drop:
                continue
            e = self.parent_of[c]
            per_edge.setdefault(e, {"clients": {}})["clients"][str(c)] = c - 1
        if not self.region_ranks:
            return per_edge
        plan: dict[int, dict] = {}
        for e, spec in per_edge.items():
            rg = self.parent_of[e]
            plan.setdefault(rg, {"aggs": {}})["aggs"][str(e)] = spec
        return plan


def build_topology(cfg, n_clients: Optional[int] = None) -> Optional["HierTopology"]:
    """``extra.hier_*`` flags -> :class:`HierTopology`, or None when unset
    (every caller then runs the flat protocol, byte-identical to before the
    flags existed).  All participants call this with the same config, so
    root, clients, and edges agree on the tree without any wire exchange.

    Default construction from ``hier_fanout``: ``ceil(N / fanout)`` edges
    with round-robin membership (:func:`round_robin_groups` — the sim's
    partition), one more round-robin tier of regions at ``hier_depth`` 3.
    ``hier_topology`` overrides with explicit groups.
    """
    explicit = cfg_extra(cfg, "hier_topology")
    fanout = int(cfg_extra(cfg, "hier_fanout") or 0)
    if not explicit and fanout <= 0:
        return None
    if getattr(cfg, "enable_secagg", False) or getattr(cfg, "enable_fhe", False):
        raise NotImplementedError(
            "hierarchical aggregation does not compose with the secure-"
            "aggregation/FHE protocols yet (per-cohort SecAgg partial folds "
            "are a later scale item — see ROADMAP); unset hier_fanout/"
            "hier_topology for these modes")
    n = int(n_clients if n_clients is not None else cfg.client_num_in_total)
    if explicit:
        topo = HierTopology(n, explicit["edges"], explicit.get("regions"))
    else:
        depth = int(cfg_extra(cfg, "hier_depth") or 2)
        if depth not in (2, 3):
            raise ValueError(f"hier_depth must be 2 or 3, got {depth}")
        n_edges = max(1, math.ceil(n / fanout))
        g = round_robin_groups(n, n_edges)
        edges = [[i + 1 for i in range(n) if g[i] == e] for e in range(n_edges)]
        regions = None
        if depth == 3:
            n_regions = max(1, math.ceil(n_edges / fanout))
            rg = round_robin_groups(n_edges, n_regions)
            regions = [[e for e in range(n_edges) if rg[e] == r]
                       for r in range(n_regions)]
        topo = HierTopology(n, edges, regions)
    topo.export_gauges()
    return topo


def hop_codec_from_config(cfg) -> Optional[str]:
    """``extra.hier_hop_codec`` -> validated codec name or None (raw f32
    partial — the hop that keeps the tree fold bitwise the flat fold)."""
    name = str(cfg_extra(cfg, "hier_hop_codec") or "").strip().lower()
    if name in ("", "no", "off", "none", "raw"):
        return None
    if name not in _VALID_HOP_CODECS:
        raise ValueError(
            f"unknown hier_hop_codec {name!r}; known: {_VALID_HOP_CODECS}")
    return name


def edge_fold_supported(cfg) -> bool:
    """Whether aggregator nodes may FOLD child uploads for this config —
    the config-level mirror of the root's ``FedMLAggregator.stream_mode``
    gate (same three conditions, evaluated without a model).  The gates
    must agree: an edge that folded while the root buffered densely would
    hand the root a partial it can only treat as one client's model.
    False -> every aggregator store-and-forwards child uploads unchanged
    (the tree still thins root CONNECTIONS, not bytes)."""
    if not (codecs.codec_from_config(cfg)
            or cfg_extra(cfg, "streaming_aggregation")
            or cfg_extra(cfg, "async_aggregation")):
        return False
    from ..trust.pipeline import build_trust_pipeline

    trust = build_trust_pipeline(cfg)
    if trust is not None and not (hasattr(trust, "supports_streaming")
                                  and trust.supports_streaming()):
        return False
    from ..fl.algorithm import config_supports_associative_fold

    return config_supports_associative_fold(cfg)


class EdgePartialFold:
    """One aggregator node's per-round fold state, transport-free.

    Holds the wire template of the dispatched global (the same
    ``flatten_with_skeleton({MODEL_PARAMS: global})`` form the root's
    streaming fold checks against), folds child replies with the root's
    exact op sequence (``sums[i] += f32(w) * f32(x)`` — see
    ``parallel/stream_fold.py``'s bitwise-discipline note), merges child
    PARTIALS with direct adds, and produces the single weighted partial to
    ship upward.  Peak simultaneously buffered: the running sum + one
    in-flight decode — <= 2 regardless of children, tracked in
    ``peak_buffered``.
    """

    def __init__(self, host_global_tree=None, *,
                 template: Optional[list] = None, skeleton=None,
                 sums: Optional[list] = None):
        if template is None:
            skeleton, leaves = wire.flatten_with_skeleton(
                {md.MSG_ARG_KEY_MODEL_PARAMS: host_global_tree})
            template = [np.asarray(l) for l in leaves]
        self.tmpl = template
        self.skel = skeleton
        self._acc = None
        if sums is not None:
            from ..parallel.stream_fold import make_stream_accumulator

            self._acc = make_stream_accumulator(self.tmpl, sums=sums)
        self.w = 0.0
        self.w_delta = 0.0
        self.sources: dict[int, float] = {}
        self.folded = 0
        self.peak_buffered = 0

    # -- frame admission ------------------------------------------------------
    def _admit(self, msg):
        """(header, leaf_iter) when the message's still-unmaterialized tensor
        frame matches the template, else None (the caller relays instead)."""
        frame = msg.tensor_frame() if hasattr(msg, "tensor_frame") else None
        if frame is None:
            return None
        header, leaf_iter = frame
        specs = header["leaves"]
        if header["treedef"] != self.skel or len(specs) != len(self.tmpl):
            return None
        for spec, t in zip(specs, self.tmpl):
            if tuple(spec["shape"]) != t.shape:
                return None
        return header, leaf_iter

    def _ensure_acc(self):
        if self._acc is None:
            from ..parallel.stream_fold import make_stream_accumulator

            self._acc = make_stream_accumulator(self.tmpl)
        # buffered right now: the running sum (if any folds landed) + this
        # in-flight decode — the <= 2 per-hop acceptance bound
        n = (1 if self.folded else 0) + 1
        if n > self.peak_buffered:
            self.peak_buffered = n

    # -- folds ----------------------------------------------------------------
    def fold_child(self, child_rank: int, msg, sample_num: float,
                   is_delta: bool) -> bool:
        """Fold one child model reply with weight ``sample_num`` — leaf by
        leaf off the undecoded frame, dequantizing compressed leaves, the
        root fold's exact arithmetic.  False -> structure mismatch, relay."""
        admitted = self._admit(msg)
        if admitted is None:
            return False
        _, leaf_iter = admitted
        self._ensure_acc()
        w = float(sample_num)
        for i, _spec, arr in leaf_iter:
            self._acc.fold_leaf(i, w, arr)
        self.w += w
        if is_delta:
            self.w_delta += w
        self.sources[int(child_rank)] = w
        self.folded += 1
        return True

    def fold_partial(self, msg, sources: dict, w_delta: float) -> bool:
        """Merge a child aggregator's pre-folded partial: DIRECT adds
        (``fold_partial_leaf``), never a unit-weight multiply — the add is
        the bitwise continuation of the child's own fold.  ``sources`` maps
        client rank -> folded weight (string keys off the JSON control
        section are fine); the masses join this node's totals."""
        admitted = self._admit(msg)
        if admitted is None:
            return False
        _, leaf_iter = admitted
        self._ensure_acc()
        for i, _spec, arr in leaf_iter:
            self._acc.fold_partial_leaf(i, arr)
        for k, v in sources.items():
            self.sources[int(k)] = float(v)
            self.w += float(v)
        self.w_delta += float(w_delta)
        self.folded += 1
        return True

    # -- ship -----------------------------------------------------------------
    def partial_tree(self):
        """The accumulated weighted partial sums, restored into the model's
        tree shape (f32 leaves) — the MODEL_PARAMS payload of the upward
        ship.  Raw bits of the running sums: the parent's direct add
        continues this fold exactly."""
        if self._acc is None:
            sums = [np.zeros(np.shape(t), np.float32) for t in self.tmpl]
        else:
            sums = self._acc.host_sums()
        return wire.restore_skeleton(self.skel, sums)[md.MSG_ARG_KEY_MODEL_PARAMS]

    def control_tag(self) -> dict:
        """The ``MSG_ARG_KEY_HIER_PARTIAL`` control payload (string keys:
        it rides the JSON section)."""
        return {"sources": {str(k): float(v)
                            for k, v in sorted(self.sources.items())},
                "w_delta": float(self.w_delta)}

    # -- journal form ---------------------------------------------------------
    def export_state(self) -> tuple[dict, dict]:
        """(protocol-json, named-arrays) for the edge journal sidecar.  The
        TEMPLATE leaves ride along with the partial sums, so a restarted
        edge restores without any model object."""
        proto = {
            "skel": self.skel,
            "w": float(self.w),
            "w_delta": float(self.w_delta),
            "folded": int(self.folded),
            "peak": int(self.peak_buffered),
            "sources": {str(k): float(v) for k, v in sorted(self.sources.items())},
        }
        arrays = {f"tmpl_{i}": t for i, t in enumerate(self.tmpl)}
        if self._acc is not None:
            for i, s in enumerate(self._acc.host_sums()):
                arrays[f"sum_{i}"] = s
        return proto, arrays

    @classmethod
    def from_state(cls, proto: dict, arrays: dict) -> "EdgePartialFold":
        tmpl = []
        i = 0
        while f"tmpl_{i}" in arrays:
            tmpl.append(np.asarray(arrays[f"tmpl_{i}"]))
            i += 1
        sums = None
        if "sum_0" in arrays:
            sums = [np.asarray(arrays[f"sum_{j}"], np.float32)
                    for j in range(len(tmpl))]
        fold = cls(template=tmpl, skeleton=proto["skel"], sums=sums)
        fold.w = float(proto.get("w", 0.0))
        fold.w_delta = float(proto.get("w_delta", 0.0))
        fold.folded = int(proto.get("folded", 0))
        fold.peak_buffered = int(proto.get("peak", 0))
        fold.sources = {int(k): float(v)
                        for k, v in (proto.get("sources") or {}).items()}
        return fold


class EdgeAggregatorManager(FedMLCommManager):
    """A server-shaped relay at one aggregation-tree node (edge OR region —
    the tier only changes whether children are clients or other aggregators).

    Protocol: the parent's INIT/SYNC dispatch arrives with the global model
    and this node's subtree plan (``MSG_ARG_KEY_HIER_CHILDREN``); the node
    re-dispatches per child, folds the children's ``C2S_SEND_MODEL``
    replies streaming (or store-and-forwards them when the config doesn't
    support the fold), and ships one control-tagged partial upward once
    every expected child is accounted — or when its straggler timer fires
    (half the root's ``straggler_timeout_s``, so the partial beats the
    root's own quorum decision).

    Crash recovery mirrors the root's: with ``extra.server_journal_dir``
    set, the node journals its partial fold after every accepted child under
    ``<dir>/edge_<rank>`` and a restarted manager (same rank, same fabric)
    resumes the round — re-folding nothing (journaled dedup keys), losing
    nothing (the in-proc queue holds uploads that arrived while dead), and
    re-shipping idempotently (the partial's upload key dedups at the
    parent).
    """

    def __init__(self, cfg, topology: HierTopology, rank: int,
                 backend: Optional[str] = None, runtime=None):
        super().__init__(cfg, rank=rank, size=topology.world_size,
                         backend=backend)
        self.topology = topology
        self.parent_rank = topology.parent(rank)
        self.tier = "region" if rank in topology.region_ranks else "edge"
        self.done = threading.Event()
        self._lock = threading.Lock()
        from .runtime import ServerRuntime

        self._runtime = runtime if runtime is not None else ServerRuntime()
        self._owns_runtime = runtime is None
        # per-hop health attribution: THIS node's ledger covers only its
        # direct children (no comm tap: in-process trees run many nodes per
        # process and the process-wide sink would cross-pollinate hops)
        from ..obs.health import ClientHealthLedger

        self.health = ClientHealthLedger()
        self.relay_only = not edge_fold_supported(cfg)
        self.hop_codec = hop_codec_from_config(cfg)
        self._hop_ratio = float(cfg_extra(cfg, "comm_topk_ratio") or 0.01)
        self._hop_residuals = None
        self.straggler_timeout = float(
            cfg_extra(cfg, "straggler_timeout_s") or 0) * 0.5
        # round state (all guarded by _lock)
        self._fold: Optional[EdgePartialFold] = None
        self._round_idx: Optional[int] = None
        self._epoch = None
        self._expect: dict[int, object] = {}
        self._arrived: set[int] = set()
        self._shipped = False
        self._ship_attempted = False
        self._sent_at: dict[int, float] = {}
        self._folded_keys: dict[int, object] = {}
        self._attempts: dict[str, int] = {}
        # counters the soaks and dryrun assert over
        self.folds = 0
        self.relays = 0
        self.deduped_uploads = 0
        self.partials_sent = 0
        self.upload_ingress_bytes = 0
        #: True when construction restored a journal snapshot (soak_worker
        #: boot files report it, same field as ClientMasterManager)
        self.resumed_from_journal = False
        # flight recorder (ISSUE 18 satellite), gated on
        # extra.flight_recorder: edge nodes were the one fleet role without
        # a black box — a SIGKILLed edge left nothing for the postmortem to
        # stitch.  No comm tap here (same reasoning as the health ledger
        # above: in-process trees run many nodes per process and the
        # process-wide sink would cross-pollinate hops); signal handlers
        # are installed by soak_worker's edge role, where one process IS
        # one edge.
        from ..obs import flight as obsflight

        self.flight = obsflight.recorder_from_config(
            cfg, name=f"edge_{rank}",
            meta={"role": "edge", "rank": int(rank), "tier": self.tier})
        # own journal under <server_journal_dir>/edge_<rank>
        self.journal = None
        root_dir = cfg_extra(cfg, "server_journal_dir")
        if root_dir:
            from .journal import ServerJournal

            self.journal = ServerJournal(
                os.path.join(str(root_dir), f"edge_{rank}"),
                keep=int(cfg_extra(cfg, "server_journal_keep")))
            self._journal_recover()

    # -- protocol -------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            md.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_dispatch)
        self.register_message_receive_handler(
            md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_message_dispatch)
        self.register_message_receive_handler(
            md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.handle_message_child_upload)
        self.register_message_receive_handler(
            md.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def handle_message_dispatch(self, msg: Message) -> None:
        """Parent dispatch: install the round, relay the global to this
        node's subtree, arm the straggler timer."""
        with self._lock:
            round_idx = int(msg.get_control(md.MSG_ARG_KEY_ROUND_INDEX))
            epoch = msg.get_control(md.MSG_ARG_KEY_SESSION_EPOCH)
            plan = msg.get_control(md.MSG_ARG_KEY_HIER_CHILDREN) or {}
            same_round = (round_idx == self._round_idx
                          and epoch == self._epoch)
            if same_round and self._shipped:
                # duplicate dispatch of a round whose partial already went
                # up: re-ship idempotently (the upload key dedups) instead
                # of redoing the subtree
                self._ship_locked(resend=True)
                return
            if same_round and self._fold is not None and self._fold.folded:
                # journal-recovered mid-round re-dispatch: the partial sums
                # carry the already-folded children — re-relay only to the
                # rest, so no work is redone
                pending = {c: s for c, s in self._expect.items()
                           if c not in self._arrived}
                self._relay_dispatch(msg, pending, round_idx, epoch)
                self._arm_straggler()
                return
            params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
            self._round_idx = round_idx
            self._epoch = epoch
            self._expect = self._parse_plan(plan)
            self._arrived = set()
            self._shipped = False
            self._ship_attempted = False
            self._fold = (None if self.relay_only
                          else EdgePartialFold(params))
            self._sent_at.clear()
            self._relay_dispatch(msg, self._expect, round_idx, epoch,
                                 params=params)
            self._arm_straggler()

    def _parse_plan(self, plan: dict) -> dict:
        """HIER_CHILDREN control dict -> {child_rank: per-child spec}.
        Edge tier: spec is the client index; region tier: spec is the
        child edge's own ``{"clients": ...}`` dict, forwarded verbatim."""
        if "aggs" in plan:
            return {int(r): spec for r, spec in plan["aggs"].items()}
        return {int(r): int(idx) for r, idx in (plan.get("clients") or {}).items()}

    def _relay_dispatch(self, msg: Message, children: dict, round_idx: int,
                        epoch, params=None) -> None:  # graftlint: disable=GL004(caller holds _lock: handle_message_dispatch only)
        if not children:
            return
        if params is None:
            params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
        for child, spec in sorted(children.items()):
            relay = Message(msg.get_type(), self.rank, child)
            relay.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
            relay.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            if epoch is not None:
                relay.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(epoch))
            if self.tier == "region":
                relay.add_params(md.MSG_ARG_KEY_HIER_CHILDREN, spec)
            else:
                relay.add_params(md.MSG_ARG_KEY_CLIENT_INDEX, int(spec))
            try:
                self._sent_at[child] = time.perf_counter()
                self.send_message(relay)
            except Exception:
                # best-effort per child, like the root's broadcast: the
                # straggler timer owns progress for unreachable children
                self.health.record_comm_failure(child)
                log.warning("edge %d: dispatch to child %d failed; "
                            "continuing", self.rank, child, exc_info=True)

    def handle_message_child_upload(self, msg: Message) -> None:
        """One child's model reply (or, at a region, a child edge's
        partial): dedup, fold-or-relay, account, ship when complete.
        Mirrors the root's upload gate order so every transport behavior
        (redelivery, stale round, missing key) lands the same way."""
        with self._lock:
            sender = int(msg.get_sender_id())
            upload_key = msg.get_control(md.MSG_ARG_KEY_UPLOAD_KEY)
            if upload_key is not None and self._is_duplicate_upload(sender, upload_key):
                self.deduped_uploads += 1
                EDGE_DEDUPED.inc()
                if self.flight is not None:
                    self.flight.note("edge_dedup", sender=sender,
                                     round_idx=self._round_idx, keyed=True)
                return
            if self._round_idx is None or int(
                    msg.get_control(md.MSG_ARG_KEY_ROUND_INDEX, -1)) != self._round_idx:
                return  # stale round (post-timeout arrival) — root-identical
            epoch = msg.get_control(md.MSG_ARG_KEY_SESSION_EPOCH, self._epoch)
            if self._epoch is not None and epoch != self._epoch:
                return  # pre-restart dispatch's work; the root would reject it
            if sender in self._arrived:
                self.deduped_uploads += 1
                EDGE_DEDUPED.inc()
                if self.flight is not None:
                    self.flight.note("edge_dedup", sender=sender,
                                     round_idx=self._round_idx, keyed=False)
                return  # keyless redelivery within the round
            sent_at = self._sent_at.pop(sender, None)
            if sent_at is not None:
                self.health.observe_rtt(sender, time.perf_counter() - sent_at)
            nbytes = int(getattr(msg, "wire_nbytes", 0) or 0)
            self.upload_ingress_bytes += nbytes
            HOP_BYTES.inc(nbytes, hop=("edge_region" if self.tier == "region"
                                       else "client_edge"))
            child_tag = msg.get_control(md.MSG_ARG_KEY_HIER_PARTIAL)
            folded = False
            if self._fold is not None:
                if child_tag is not None:
                    folded = self._fold.fold_partial(
                        msg, child_tag.get("sources") or {},
                        float(child_tag.get("w_delta", 0.0)))
                    if folded:
                        PARTIALS_FOLDED.inc(tier=self.tier)
                else:
                    n_samples = float(msg.get(md.MSG_ARG_KEY_NUM_SAMPLES))
                    is_delta = bool(msg.get_control(
                        md.MSG_ARG_KEY_MODEL_IS_DELTA, False))
                    folded = self._fold.fold_child(
                        sender, msg, n_samples, is_delta)
                if folded:
                    self.folds += 1
                    EDGE_FOLDS.inc()
                    if self.flight is not None:
                        self.flight.note("edge_fold", sender=sender,
                                         round_idx=self._round_idx,
                                         partial=child_tag is not None)
            if not folded:
                self._relay_upload(msg, sender)
            self._note_upload_key(sender, upload_key)
            self._arrived.add(sender)
            self._journal_snapshot_locked()
            if set(self._expect) <= self._arrived:
                self._ship_locked()

    def _relay_upload(self, msg: Message, sender: int) -> None:  # graftlint: disable=GL004(caller holds _lock: handle_message_child_upload only)
        """Store-and-forward one child upload unchanged to the parent,
        preserving the child as wire sender so the root folds/buffers it
        under the right identity.  The fallback that keeps the tree correct
        for every config the fold doesn't cover."""
        fwd = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, sender,
                      self.parent_rank)
        fwd.add_params(md.MSG_ARG_KEY_MODEL_PARAMS,
                       msg.get(md.MSG_ARG_KEY_MODEL_PARAMS))
        for key in (md.MSG_ARG_KEY_NUM_SAMPLES, md.MSG_ARG_KEY_ROUND_INDEX,
                    md.MSG_ARG_KEY_SESSION_EPOCH, md.MSG_ARG_KEY_UPLOAD_KEY,
                    md.MSG_ARG_KEY_MODEL_IS_DELTA, md.MSG_ARG_KEY_HIER_PARTIAL):
            val = msg.get_control(key)
            if val is not None:
                fwd.add_params(key, val)
        self.relays += 1
        EDGE_RELAYS.inc()
        if self.flight is not None:
            self.flight.note("edge_relay", sender=sender,
                             round_idx=self._round_idx)
        try:
            self.send_message(fwd)
        except Exception:
            log.warning("edge %d: relay of child %d upload failed",
                        self.rank, sender, exc_info=True)

    def _ship_locked(self, resend: bool = False) -> None:  # graftlint: disable=GL004(callers hold _lock: handle_message_child_upload, handle_message_dispatch, and the straggler timeout)
        """Ship THE one pre-folded weighted partial upward (or nothing, in
        relay mode — every upload already went up individually)."""
        self._runtime.cancel(self, "straggler")
        if self._fold is None:
            self._shipped = True
            return
        if self._shipped and not resend:
            return
        if not self._fold.folded:
            # nothing landed (every child relayed or timed out): no partial
            self._shipped = True
            return
        tree = self._fold.partial_tree()
        if self.hop_codec is not None:
            # per-hop re-encode (comm/codecs.py): the upward hop costs
            # compressed bytes; EF residuals carry per node across rounds.
            # Convergence-approximate by construction — the raw hop is the
            # bitwise one (hier_hop_codec doc).
            # low-rank floor, not the model-scale default: the partial is
            # this subtree's ENTIRE upward traffic for the round, so even
            # sub-1024-element leaves are worth encoding (qsgd8 shrinks any
            # f32 leaf above ~260 elements — comm/codecs.py floor note)
            tree, self._hop_residuals, _stats = codecs.compress_pytree(
                tree, self.hop_codec, residuals=self._hop_residuals,
                ratio=self._hop_ratio,
                min_elems=codecs.LOW_RANK_MIN_COMPRESS_ELEMS)
        up = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                     self.parent_rank)
        up.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, tree)
        up.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(self._fold.w))
        up.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self._round_idx)
        if self._epoch is not None:
            up.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(self._epoch))
        up.add_params(md.MSG_ARG_KEY_HIER_PARTIAL, self._fold.control_tag())
        if self.journal is not None:
            # exactly-once upward: attempt is journaled (inside the same
            # snapshot cadence) before the send, so a crash-resend of this
            # partial dedups at the parent instead of double-folding
            attempt = self._next_attempt_locked(resend=resend)
            up.add_params(
                md.MSG_ARG_KEY_UPLOAD_KEY,
                f"edge{self.rank}:{self._round_idx}:"
                f"{-1 if self._epoch is None else int(self._epoch)}:{attempt}")
        self._shipped = True
        self._ship_attempted = True
        self._journal_snapshot_locked()
        self.partials_sent += 1
        PARTIALS_SENT.inc()
        if self.flight is not None:
            self.flight.note("edge_partial_ship", round_idx=self._round_idx,
                             children=len(self._arrived),
                             expected=len(self._expect), resend=resend)
        try:
            self.send_message(up)
        except Exception:
            log.warning("edge %d: partial ship to %d failed", self.rank,
                        self.parent_rank, exc_info=True)

    def _next_attempt_locked(self, resend: bool) -> int:  # graftlint: disable=GL004(callers hold _lock: _ship_locked only)
        k = f"{self._round_idx}:{-1 if self._epoch is None else int(self._epoch)}"
        if resend:
            # same bytes, same key: the duplicate dispatch path re-ships the
            # journaled attempt so the parent's dedup recognizes it
            return max(0, self._attempts.get(k, 1) - 1)
        n = self._attempts.get(k, 0)
        self._attempts[k] = n + 1
        while len(self._attempts) > 64:
            self._attempts.pop(next(iter(self._attempts)))
        return n

    # -- straggler ------------------------------------------------------------
    def _arm_straggler(self) -> None:  # graftlint: disable=GL004(caller holds _lock: handle_message_dispatch only)
        if self.straggler_timeout <= 0:
            return
        self._runtime.arm(self, "straggler", self.straggler_timeout,
                          self._on_straggler_timeout)

    def _on_straggler_timeout(self) -> None:
        with self._lock:
            if self._shipped or self._fold is None:
                return
            if self._fold.folded:
                for child in sorted(set(self._expect) - self._arrived):
                    self.health.record_deadline_breach(child)
                log.warning(
                    "edge %d round %s: straggler timeout, shipping partial "
                    "with %d/%d children", self.rank, self._round_idx,
                    len(self._arrived), len(self._expect))
                self._ship_locked()
            else:
                self._arm_straggler()  # nothing folded yet: keep waiting

    # -- dedup ----------------------------------------------------------------
    def _is_duplicate_upload(self, sender: int, key: str) -> bool:  # graftlint: disable=GL004(caller holds _lock: receive-handler gate)
        dq = self._folded_keys.get(sender)
        return dq is not None and key in dq

    def _note_upload_key(self, sender: int, key: Optional[str]) -> None:  # graftlint: disable=GL004(caller holds _lock: receive-handler accept path)
        if key is None:
            return
        dq = self._folded_keys.get(sender)
        if dq is None:
            dq = self._folded_keys[sender] = deque(maxlen=DEDUP_KEYS_PER_CHILD)
        dq.append(key)

    # -- recovery journal ------------------------------------------------------
    def _journal_snapshot_locked(self) -> None:  # graftlint: disable=GL004(callers hold _lock: upload accept path and _ship_locked)
        """Commit the round's partial fold after every accepted child (and
        at ship).  Model-less sidecar: the template leaves ride with the
        partial sums, so restore needs no model object — an edge process is
        stateless between rounds by design."""
        if self.journal is None or self._round_idx is None:
            return
        proto = {
            "kind": "edge",
            "rank": int(self.rank),
            "round_idx": int(self._round_idx),
            "epoch": None if self._epoch is None else int(self._epoch),
            "expect": {str(c): s for c, s in sorted(self._expect.items())},
            "arrived": sorted(self._arrived),
            "shipped": bool(self._shipped),
            "folds": int(self.folds),
            "relays": int(self.relays),
            "deduped": int(self.deduped_uploads),
            "attempts": dict(self._attempts),
            "folded_keys": {str(c): list(dq)
                            for c, dq in sorted(self._folded_keys.items())},
        }
        arrays = {}
        if self._fold is not None:
            fold_proto, arrays = self._fold.export_state()
            proto["fold"] = fold_proto
        self.journal.snapshot(self._round_idx, proto, arrays)

    def _journal_recover(self) -> None:  # graftlint: disable=GL004(construction-time: runs from __init__ before the receive loop or any timer thread exists)
        """Resume the interrupted round from the newest intact sidecar: the
        partial sums, folded-child set, dedup keys, and ship state come
        back; child uploads that landed while dead are still queued on the
        fabric and drain through the normal handler (their keys dedup any
        the partial already contains)."""
        snap = self.journal.restore(model_template=None)
        if snap is None:
            return
        self.resumed_from_journal = True
        proto = snap["protocol"]
        self._round_idx = int(proto["round_idx"])
        self._epoch = proto.get("epoch")
        self._expect = {int(c): s for c, s in (proto.get("expect") or {}).items()}
        self._arrived = set(int(c) for c in proto.get("arrived") or [])
        self._shipped = bool(proto.get("shipped"))
        self.folds = int(proto.get("folds", 0))
        self.relays = int(proto.get("relays", 0))
        self.deduped_uploads = int(proto.get("deduped", 0))
        self._attempts = {str(k): int(v)
                          for k, v in (proto.get("attempts") or {}).items()}
        for c, keys in (proto.get("folded_keys") or {}).items():
            self._folded_keys[int(c)] = deque(
                [str(k) for k in keys], maxlen=DEDUP_KEYS_PER_CHILD)
        if proto.get("fold") is not None:
            self._fold = EdgePartialFold.from_state(proto["fold"], snap["arrays"])
        log.info("edge %d: recovered journal step %d (round %d, %d/%d "
                 "children folded, shipped=%s)", self.rank, snap["step"],
                 self._round_idx, len(self._arrived), len(self._expect),
                 self._shipped)

    def recovery_resume(self) -> None:
        """Post-restart nudge (called after the receive loop is up): if the
        recovered round was complete-but-unshipped — the crash hit between
        the last fold and the ship — ship now; queued uploads need no nudge,
        they drain through the handler."""
        with self._lock:
            if self.flight is not None:
                self.flight.note("recovery_resume", round_idx=self._round_idx,
                                 shipped=self._shipped,
                                 arrived=len(self._arrived),
                                 expected=len(self._expect),
                                 resumed=self.resumed_from_journal)
            if (self._round_idx is not None and not self._shipped
                    and self._fold is not None
                    and set(self._expect) <= self._arrived):
                self._ship_locked()
            elif self._round_idx is not None and not self._shipped:
                self._arm_straggler()

    # -- lifecycle -------------------------------------------------------------
    def handle_message_finish(self, msg: Message) -> None:
        self.done.set()
        self.finish()

    def hard_kill(self) -> None:  # graftlint: disable=GL008(crash simulation: deliberately lock-free — a SIGKILL takes no locks either; the restarted manager rebuilds every invariant from the journal under its own lock),GL004(same: the flight trigger reads _round_idx/_shipped racily on purpose — a best-effort snapshot at the kill instant, never a consistency source)
        """SIGKILL simulation for the chaos soak: stop the receive loop and
        timers abruptly — no ship, no journal write, no teardown.  Whatever
        the per-fold journal cadence already committed survives; everything
        since is lost, exactly like a real kill."""
        if self.flight is not None:
            # the black box outlives the kill: one atomic bundle with the
            # ring's folds/relays/dedups, stitchable by `obs postmortem`
            self.flight.trigger("hard_kill", rank=int(self.rank),
                                round_idx=self._round_idx,
                                shipped=self._shipped)
            self.flight.close()
        self._runtime.cancel(self)
        self.com_manager.stop_receive_message()

    def finish(self) -> None:  # graftlint: disable=GL004(teardown: done has latched and the receive loop is quiescing — the flight trigger's counter reads are a final best-effort snapshot),GL008(same single-quiescent-reader argument for folds/relays)
        if self.flight is not None and not self.flight._closed:
            self.flight.trigger("finish", rank=int(self.rank),
                                round_idx=self._round_idx,
                                folds=self.folds, relays=self.relays)
            self.flight.close()
        self._runtime.cancel(self)
        super().finish()
        if self._owns_runtime:
            self._runtime.close()
