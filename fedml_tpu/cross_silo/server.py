"""Cross-silo FL server.

Parity with ``cross_silo/server/fedml_server_manager.py:15`` +
``fedml_aggregator.py:13``: the event loop is

  connection_ready -> check client status -> all ONLINE -> send_init
  -> on each client model: add, check_whether_all_receive -> aggregate
  -> test -> client_selection -> sync model out -> ... -> finish

with one deliberate improvement (SURVEY.md §5 flags the gap): **bounded-wait
straggler handling** — if ``straggler_timeout_s`` is set and a quorum
fraction of models has arrived when the timer fires, the round proceeds with
the received subset reweighted, instead of stalling forever on a lost client.

Aggregation reuses the same pure ``FedAlgorithm.aggregate``/``server_update``
and TrustPipeline hooks as the simulation engine — one algorithm codebase
for both platforms.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import create as create_algorithm, hparams_from_config
from ..analysis import tracesan
from ..comm import codecs, wire
from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from ..core import aot as aotlib, pytree as pt, rng
from ..core.flags import cfg_extra
from ..data.dataset import pad_eval_set
from ..fl.local_sgd import make_eval_fn
from ..obs import registry as obsreg, trace as obstrace
from ..obs.metrics import MetricsLogger
from . import message_define as md
from .edge import (
    EDGE_DEDUPED as HIER_EDGE_DEDUPED,
    EDGE_FOLDS as HIER_EDGE_FOLDS,
    EDGE_RELAYS as HIER_EDGE_RELAYS,
    HOP_BYTES as HIER_HOP_BYTES,
    PARTIALS_SENT as HIER_PARTIALS_SENT,
    TREE_DEPTH as HIER_TREE_DEPTH,
    TREE_EDGES as HIER_TREE_EDGES,
    TREE_FANOUT as HIER_TREE_FANOUT,
    build_topology,
)

log = logging.getLogger("fedml_tpu.cross_silo.server")

# straggler attribution: how long between the server's model broadcast and
# each client's trained-model reply, per client
CLIENT_ROUND_TRIP = obsreg.REGISTRY.histogram(
    "fedml_crosssilo_client_round_trip_seconds",
    "Broadcast-to-model-reply round trip, by client rank.",
    labels=("client",),
)
ROUND_TIME = obsreg.REGISTRY.histogram(
    "fedml_crosssilo_round_seconds",
    "Cross-silo round wall time (broadcast to aggregated).",
)
AGGREGATE_TIME = obsreg.REGISTRY.histogram(
    "fedml_crosssilo_aggregate_seconds",
    "Server-side aggregation wall time per round.",
)
BUFFERED_PEAK = obsreg.REGISTRY.gauge(
    "fedml_crosssilo_buffered_updates_peak",
    "Peak client updates simultaneously buffered on the server (streaming "
    "aggregation holds ~2 regardless of clients-per-round).",
)
REJECTED_STALE = obsreg.REGISTRY.counter(
    "fedml_crosssilo_stale_rejected_total",
    "Uploads rejected deterministically after a server recovery, by reason "
    "(epoch = produced by a pre-crash dispatch with no surviving in-flight "
    "slot — folding it would double-count work already in the journal).",
    labels=("reason",),
)
DEDUPED_UPLOADS = obsreg.REGISTRY.counter(
    "fedml_crosssilo_uploads_deduped_total",
    "Uploads dropped by the idempotence-key dedup (ISSUE 13): a redelivery "
    "of bytes already folded — chaos duplicate, reconnect resend, or a "
    "client crash-resend of a journaled attempt.",
)

#: idempotence keys remembered per client for the exactly-once dedup — small
#: (duplicates arrive close to their original; the journal carries the table
#: across a server crash so pre-crash folds still dedup after recovery)
DEDUP_KEYS_PER_CLIENT = 16


def _apply_delta(global_leaf, delta_leaf):
    """global + delta per leaf, mirroring the client's ``_leaf_delta``
    (f32 math for float leaves, native add for integers)."""
    g, d = np.asarray(global_leaf), np.asarray(delta_leaf)
    if g.dtype.kind in "fc":
        return (g.astype(np.float32) + d.astype(np.float32)).astype(g.dtype)
    return g + d


def provisional_steps_per_epoch(cfg) -> int:
    """Config-derived steps/epoch guess used before real per-client sample
    counts arrive in the protocol (MSG_ARG_KEY_NUM_SAMPLES); only seeds
    round 0's server-side schedule."""
    return max(1, math.ceil(
        getattr(cfg, "synthetic_train_size", 1024) / max(cfg.client_num_in_total, 1) / cfg.batch_size
    ))


class FedMLAggregator:
    """Server-side state: per-round model buffer + the algorithm frame
    (reference ``FedMLAggregator`` ``fedml_aggregator.py``)."""

    # class-level defaults for the streaming-aggregation machinery so that
    # subclasses which deliberately skip __init__ (LoRAAggregator builds its
    # own adapter-tree state, then opts back in via _init_stream_mode)
    # inherit the safe exact-mode behavior
    stream_mode = False
    _shard_fold = False
    _mesh = None
    _np_global = None
    _stream_tmpl = None
    _stream_acc = None
    _stream_w = 0.0
    _stream_w_delta = 0.0
    _stream_folded = 0
    peak_buffered_updates = 0

    def __init__(self, cfg, model, sample_x, test_arrays, trust=None,
                 mesh=None):
        self.cfg = cfg
        self._model = model
        # externally supplied mesh (a submesh LEASE under the device-slot
        # scheduler): the sharded stream fold resolves its NamedShardings
        # against it instead of the full default mesh; None = unchanged
        self._mesh = mesh
        # _calibrate_schedule replaces the guess with protocol truth at
        # first aggregation
        self.hp = hparams_from_config(cfg, steps_per_epoch=provisional_steps_per_epoch(cfg))
        self.algorithm = create_algorithm(cfg, self.hp).build(model)
        self._schedule_calibrated = False
        k0 = rng.root_key(cfg.random_seed)
        self.global_vars = model.init(
            {"params": jax.random.fold_in(k0, 1), "dropout": jax.random.fold_in(k0, 2)},
            jnp.asarray(sample_x), train=True,
        )
        self.server_state = self.algorithm.init_server_state(self.global_vars)
        self.trust = trust
        self.root_key = k0
        self.model_dict: dict[int, object] = {}
        self.sample_num_dict: dict[int, float] = {}
        self.flag_client_model_uploaded: dict[int, bool] = {}
        tx, ty, n_valid = test_arrays
        self._test = (jnp.asarray(tx), jnp.asarray(ty), jnp.int32(n_valid))
        # server eval step through the AOT program store (extra.aot_programs):
        # a redeployed/preempted server deserializes the exported eval program
        # instead of re-tracing it; flag unset -> the exact old jit
        eval_fn = make_eval_fn(model, self.hp, batch_size=min(256, max(32, cfg.test_batch_size)))
        self._aot = aotlib.store_from_config(cfg)
        self._program_items: list = []
        if self._aot is not None:
            eval_key = aotlib.program_key(
                "cross_silo.eval",
                trees={"args": (self.global_vars, *self._test)},
                hparams=self.hp,
                config=aotlib.config_signature(cfg))
            self._eval_fn = self._aot.cached_jit(
                eval_fn, (self.global_vars, *self._test), key=eval_key)
            self._program_items.append((eval_key, lambda: aotlib.export_program(
                jax.jit(eval_fn), (self.global_vars, *self._test))))
        else:
            self._eval_fn = jax.jit(eval_fn)
        self._init_stream_mode(cfg)

    def _init_stream_mode(self, cfg) -> None:
        """Engage the streaming accumulator: fold each arriving update into a
        running weighted sum as it lands (overlapping aggregation with the
        network tail; peak host memory ~2x model instead of N x model).
        Engaged only when compression / extra.streaming_aggregation / the
        buffered-async server asks for it AND the algorithm declares its
        aggregate a weight-associative fold AND no trust pipeline needs the
        stacked client models — otherwise the exact buffer-all path stays
        reference-bit-exact.  A trust pipeline that only adds CENTRAL DP
        (``TrustPipeline.supports_streaming``, ISSUE 15) no longer forces
        exact mode: its one hook fires at finalize, on the aggregate the
        fold already produced bitwise.  Attack/defense/LDP configurations
        (and the FHE/SecAgg aggregator subclasses, which pin stream_mode
        False) still buffer exactly.  Shared by the base __init__ and
        subclasses that skip it (LoRAAggregator); requires
        ``self.algorithm``/``self.trust`` to be set."""
        trust_streams = self.trust is None or (
            hasattr(self.trust, "supports_streaming")
            and self.trust.supports_streaming())
        self.stream_mode = bool(
            (codecs.codec_from_config(cfg) or cfg_extra(cfg, "streaming_aggregation")
             or cfg_extra(cfg, "async_aggregation"))
            and trust_streams
            and self.algorithm.supports_associative_fold()
        )
        # sharded fold (extra.server_shard_fold): the accumulator (and the
        # finalized global) live under parallel/mesh NamedShardings — each
        # arriving leaf folds on its shard-owning devices under jit
        self._shard_fold = self.stream_mode and bool(
            cfg_extra(cfg, "server_shard_fold"))
        self._np_global = None      # host copy of global_vars, per round
        self._stream_tmpl = None    # (template leaves, wire skeleton), per round
        self._stream_acc = None     # parallel.stream_fold accumulator, per round
        self._stream_w = 0.0
        self._stream_w_delta = 0.0
        self._stream_folded = 0
        #: high-water mark of client updates simultaneously buffered (the
        #: streaming acceptance bound: <= 2 regardless of clients-per-round)
        self.peak_buffered_updates = 0

    # -- receive-side bookkeeping -------------------------------------------
    def _host_global(self):
        if self._np_global is None:
            self._np_global = jax.device_get(self.global_vars)  # graftlint: disable=GL010(wire-ingest boundary: delta uploads reconstruct against a host copy of the global, cached once per round — one device_get per round, not per client)
        return self._np_global

    def _stream_template(self):
        if self._stream_tmpl is None:
            skel, leaves = wire.flatten_with_skeleton(
                {md.MSG_ARG_KEY_MODEL_PARAMS: self._host_global()}
            )
            self._stream_tmpl = ([np.asarray(l) for l in leaves], skel)
        return self._stream_tmpl

    def _note_buffered(self, inflight: int = 0) -> None:
        n = len(self.model_dict) + inflight + (1 if self._stream_acc is not None else 0)
        if n > self.peak_buffered_updates:
            self.peak_buffered_updates = n

    def has_received(self, client_idx: int) -> bool:
        return client_idx in self.flag_client_model_uploaded

    def add_local_trained_result(self, client_idx: int, params, sample_num: float,
                                 is_delta: bool = False) -> None:
        if is_delta:
            params = jax.tree_util.tree_map(_apply_delta, self._host_global(), params)
        self.model_dict[client_idx] = params
        self.sample_num_dict[client_idx] = sample_num
        self.flag_client_model_uploaded[client_idx] = True
        self._note_buffered()

    def fold(self, client_idx: int, msg, sample_num: float, is_delta: bool,
             scale: float = 1.0) -> bool:
        """THE associative-fold entry point: fold one model reply's
        still-unmaterialized tensor frame straight into the running weighted
        sum with effective weight ``sample_num * scale``, leaf by leaf
        (dequantizing compressed leaves, whether the frame arrived whole or
        as chunk-decoded leaves).  ``scale`` carries the async server's
        staleness decay; the synchronous path passes 1.0, whose multiply is
        bitwise the unscaled fold.  Gated on the algorithm's
        ``supports_associative_fold`` (via ``stream_mode``).  Returns False
        when this update must take the dense-buffered path instead (stream
        mode off, tensors already materialized, or a frame whose structure
        doesn't match the model) — it performs NO duplicate filtering, since
        a buffered-async client may legitimately contribute twice in one
        virtual round (``ingest_streaming`` adds the sync-path dedup)."""
        if not self.stream_mode:
            return False
        frame = msg.tensor_frame() if hasattr(msg, "tensor_frame") else None
        if frame is None:
            return False
        header, leaf_iter = frame
        tmpl, skel = self._stream_template()
        specs = header["leaves"]
        if header["treedef"] != skel or len(specs) != len(tmpl):
            log.warning("client %d frame structure mismatch; buffering densely", client_idx)
            return False
        for spec, t in zip(specs, tmpl):
            if tuple(spec["shape"]) != t.shape:
                log.warning("client %d leaf shape mismatch; buffering densely", client_idx)
                return False
        if self._stream_acc is None:
            from ..parallel.stream_fold import make_stream_accumulator

            self._stream_acc = make_stream_accumulator(
                tmpl, sharded=self._shard_fold, mesh=self._mesh)
        # buffered right now: the accumulator + this in-flight decode (+ any
        # dense fallbacks) — the quantity the <=2 acceptance bound tracks
        self._note_buffered(inflight=1)
        w = float(sample_num) * float(scale)
        with tracesan.allow("fold_ingest"):
            # wire hands numpy views: each fold_leaf is a legitimate
            # (annotated) host->device upload of one decoded leaf
            for i, _spec, arr in leaf_iter:
                self._stream_acc.fold_leaf(i, w, arr)
        self._stream_w += w
        if is_delta:
            self._stream_w_delta += w
        self._stream_folded += 1
        self.sample_num_dict[client_idx] = sample_num
        return True

    def ingest_streaming(self, client_idx: int, msg, sample_num: float,
                         is_delta: bool) -> bool:
        """Synchronous-round wrapper over :meth:`fold`: one contribution per
        client per round.  Returns False when this update must take the
        buffered path instead."""
        if not self.stream_mode:
            return False
        if client_idx in self.flag_client_model_uploaded:
            # duplicate delivery (at-least-once transports redeliver): the
            # dict-overwrite of the buffered path was naturally idempotent,
            # a second fold would double-count — swallow it
            return True
        if not self.fold(client_idx, msg, sample_num, is_delta):
            return False
        self.flag_client_model_uploaded[client_idx] = True
        return True

    def fold_partial(self, msg, sources: dict, w_delta: float) -> bool:
        """Fold an edge aggregator's pre-folded weighted partial (the
        hierarchical tree's control-tagged upload — ``cross_silo/edge.py``).
        MODEL_PARAMS carries ``sum_c w_c * x_c`` over the edge's children,
        so each leaf merges with a DIRECT add (``fold_partial_leaf``) — no
        unit-weight multiply, keeping the tree fold bitwise a continuation
        of the flat fold — and the per-source sample masses land in the
        same ledgers the flat path maintains (``sample_num_dict`` /
        ``flag_client_model_uploaded``), so quorum accounting, reweighting,
        and ``check_whether_all_receive`` are unchanged.  Returns False
        when stream mode is off or the frame doesn't match the model (a
        partial has no dense fallback — the caller drops and counts it)."""
        if not self.stream_mode:
            return False
        frame = msg.tensor_frame() if hasattr(msg, "tensor_frame") else None
        if frame is None:
            return False
        header, leaf_iter = frame
        tmpl, skel = self._stream_template()
        specs = header["leaves"]
        if header["treedef"] != skel or len(specs) != len(tmpl):
            log.warning("edge partial frame structure mismatch; dropping")
            return False
        for spec, t in zip(specs, tmpl):
            if tuple(spec["shape"]) != t.shape:
                log.warning("edge partial leaf shape mismatch; dropping")
                return False
        fresh = {int(k): float(v) for k, v in sources.items()
                 if int(k) not in self.flag_client_model_uploaded}
        if not fresh:
            return True  # every source already accounted (redelivery)
        if len(fresh) != len(sources):
            # partial overlap (an edge re-ship racing its own relayed
            # children) cannot be split apart — the sums are already merged
            log.warning("edge partial overlaps %d already-folded sources; "
                        "dropping", len(sources) - len(fresh))
            return False
        if self._stream_acc is None:
            from ..parallel.stream_fold import make_stream_accumulator

            self._stream_acc = make_stream_accumulator(
                tmpl, sharded=self._shard_fold, mesh=self._mesh)
        self._note_buffered(inflight=1)
        with tracesan.allow("fold_ingest"):
            for i, _spec, arr in leaf_iter:
                self._stream_acc.fold_partial_leaf(i, arr)
        self._stream_w += sum(fresh.values())
        self._stream_w_delta += float(w_delta)
        self._stream_folded += 1
        for cid, w in fresh.items():
            self.sample_num_dict[cid] = w
            self.flag_client_model_uploaded[cid] = True
        return True

    def received_count(self) -> int:
        # flag_client_model_uploaded is the one ledger every upload path
        # maintains (dense buffer, streaming fold, and the secure-agg
        # subclasses' masked/ciphertext uploads)
        return len(self.flag_client_model_uploaded)

    def check_whether_all_receive(self, expected: int) -> bool:
        return self.received_count() >= expected

    def _calibrate_schedule(self) -> None:
        """Rebuild the server-side algorithm schedule from the ACTUAL sample
        counts the clients reported in the protocol (the reference servers
        receive them the same way); runs once, at first aggregation."""
        if self._schedule_calibrated or not self.sample_num_dict:
            return
        self._schedule_calibrated = True
        mean_samples = float(np.mean(list(self.sample_num_dict.values())))
        spe = max(1, math.ceil(mean_samples / self.cfg.batch_size))
        if spe == self.hp.steps_per_epoch:
            return
        self.hp = hparams_from_config(self.cfg, steps_per_epoch=spe)
        old_state = self.server_state
        self.algorithm = create_algorithm(self.cfg, self.hp).build(self._model)
        fresh = self.algorithm.init_server_state(self.global_vars)
        # keep accumulated state when the pytree shape is unchanged (it is —
        # only the schedule constants differ); fall back to fresh otherwise
        if jax.tree_util.tree_structure(old_state) == jax.tree_util.tree_structure(fresh):
            self.server_state = old_state
        else:
            self.server_state = fresh

    def aggregate(self, round_idx: int):
        self._calibrate_schedule()
        if self._stream_folded:
            return self._aggregate_streaming(round_idx)
        ids = sorted(self.model_dict.keys())
        trees = [jax.tree_util.tree_map(jnp.asarray, self.model_dict[i]) for i in ids]
        stacked = pt.tree_stack(trees)
        weights = jnp.asarray([self.sample_num_dict[i] for i in ids], jnp.float32)
        rkey = rng.round_key(self.root_key, round_idx)
        if self.trust is not None:
            sampled = jnp.asarray(ids, jnp.int32)
            stacked, weights = self.trust.on_client_outputs(
                stacked, weights, sampled, self.global_vars, rkey
            )
            stacked, weights, agg_override = self.trust.on_aggregation(
                stacked, weights, self.global_vars, rkey
            )
        else:
            agg_override = None
        agg = agg_override if agg_override is not None else self.algorithm.aggregate(stacked, weights)
        new_global, self.server_state = self.algorithm.server_update(
            self.global_vars, self.server_state, agg, round_idx
        )
        if self.trust is not None:
            new_global = self.trust.on_after_aggregation(new_global, self.global_vars, rkey)
        self.global_vars = new_global
        self._reset_round()
        return self.global_vars

    def _aggregate_streaming(self, round_idx: int):
        """Finalize the running weighted sum: most of the aggregation work
        already happened as updates landed (overlapping the network tail);
        what's left is one divide + the algorithm's server step."""
        tmpl, skel = self._stream_template()
        # dense-buffered stragglers (structure-mismatch fallbacks) fold now;
        # add_local_trained_result already reconstructed full params
        for cid in sorted(self.model_dict):
            w = float(self.sample_num_dict[cid])
            _, leaves = wire.flatten_with_skeleton(
                {md.MSG_ARG_KEY_MODEL_PARAMS: self.model_dict[cid]}
            )
            for i, leaf in enumerate(leaves):
                self._stream_acc.fold_leaf(i, w, leaf)
            self._stream_w += w
        tot = max(self._stream_w, 1e-12)
        # normalize (+ delta-sender base add-back) on the accumulator's home:
        # host numpy by default, the shard-owning devices under jit when
        # server_shard_fold placed the sums there — bitwise-identical math
        out = self._stream_acc.finalize(tmpl, self._stream_w_delta, tot)
        agg_np = wire.restore_skeleton(skel, out)[md.MSG_ARG_KEY_MODEL_PARAMS]
        agg = jax.tree_util.tree_map(jnp.asarray, agg_np)
        new_global, self.server_state = self.algorithm.server_update(
            self.global_vars, self.server_state, agg, round_idx
        )
        if self.trust is not None:
            # trust on the fast path (ISSUE 15): a streaming-compatible
            # pipeline (central DP only) fires its finalize hook ONCE here,
            # with the same round key the buffer-all path uses — clip +
            # noise land on an aggregate the fold produced bitwise, so
            # streaming-CDP == exact-CDP bitwise
            rkey = rng.round_key(self.root_key, round_idx)
            new_global = self.trust.on_after_aggregation(
                new_global, self.global_vars, rkey)
        self.global_vars = new_global
        self._reset_round()
        return self.global_vars

    def _reset_round(self) -> None:
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self.flag_client_model_uploaded.clear()
        self._stream_acc = None
        self._stream_w = 0.0
        self._stream_w_delta = 0.0
        self._stream_folded = 0
        # the global model changed: host copy + leaf template are stale
        self._np_global = None
        self._stream_tmpl = None

    # -- recovery-journal state (cross_silo/journal.py) -----------------------
    def model_state(self) -> dict:
        """The round-resumable model tree (also the restore template):
        global variables + the algorithm's server state."""
        return {"global_vars": self.global_vars, "server_state": self.server_state}

    def restore_model_state(self, state: dict) -> None:
        """Install a journaled :meth:`model_state` snapshot (recovery path);
        invalidates the host copy + stream template the old tree seeded."""
        self.global_vars = jax.tree_util.tree_map(jnp.asarray, state["global_vars"])
        self.server_state = jax.tree_util.tree_map(jnp.asarray, state["server_state"])
        self._np_global = None
        self._stream_tmpl = None

    def export_stream_state(self) -> tuple[dict, dict]:
        """(protocol-json, named-arrays) of the streaming accumulator for the
        recovery journal.  At round boundaries this is empty (the fold buffer
        resets on aggregate); mid-round snapshots carry the partial sums so
        nothing folded is lost."""
        proto = {
            "stream_w": float(self._stream_w),
            "stream_w_delta": float(self._stream_w_delta),
            "stream_folded": int(self._stream_folded),
            "stream_samples": {str(k): float(v)
                               for k, v in sorted(self.sample_num_dict.items())},
            # the clients whose folds the partial sums already contain —
            # dense-buffered stragglers (model_dict) are NOT listed: their
            # trees are not in the sidecar, so a mid-round resume re-collects
            # them while the stream-folded contributions stay folded
            "stream_clients": sorted(
                set(self.flag_client_model_uploaded) - set(self.model_dict)),
        }
        sums = self._stream_acc.host_sums() if self._stream_acc is not None else []
        arrays = {f"stream_sum_{i}": a for i, a in enumerate(sums)}
        return proto, arrays

    def restore_stream_state(self, proto: dict, arrays: dict) -> None:
        """Inverse of :meth:`export_stream_state` — call after
        :meth:`restore_model_state` (the template must match the restored
        global tree)."""
        if not proto.get("stream_folded"):
            return
        tmpl, _ = self._stream_template()
        try:
            sums = [np.asarray(arrays[f"stream_sum_{i}"], np.float32)
                    for i in range(len(tmpl))]
        except KeyError:
            log.warning("journal: streaming partials incomplete — restarting "
                        "the fold buffer empty")
            return
        from ..parallel.stream_fold import make_stream_accumulator

        self._stream_acc = make_stream_accumulator(
            tmpl, sharded=self._shard_fold, mesh=self._mesh, sums=sums)
        self._stream_w = float(proto.get("stream_w", 0.0))
        self._stream_w_delta = float(proto.get("stream_w_delta", 0.0))
        self._stream_folded = int(proto.get("stream_folded", 0))
        for k, v in (proto.get("stream_samples") or {}).items():
            self.sample_num_dict[int(k)] = float(v)
        # mid-round resume (ISSUE 13): the already-folded clients are marked
        # received, so the resumed round neither re-dispatches to them nor
        # double-folds a re-sent upload (pre-ISSUE-13 snapshots lack the key
        # and restore with an empty set — the round simply redoes everyone)
        for c in proto.get("stream_clients") or []:
            self.flag_client_model_uploaded[int(c)] = True

    def test_on_server(self) -> dict:
        return {k: float(v) for k, v in self._eval_fn(self.global_vars, *self._test).items()}

    def warm_programs(self) -> Optional[dict]:
        """Resolve every AOT-stored server program before round 0
        (``ProgramStore.warm``): a redeployed/preempted async server pays
        its deserialize/build cost at startup, never on the first virtual
        round's eval.  None when ``extra.aot_programs`` is unset."""
        if self._aot is None:
            return None
        return self._aot.warm(self._program_items)

    def client_selection(self, round_idx: int, client_ids: list[int], per_round: int,
                         health=None) -> list[int]:
        """Reference ``client_selection`` (:139) semantics on real ranks.

        With a :class:`~fedml_tpu.obs.health.ClientHealthLedger` (gated on
        ``extra.health_aware_selection`` by the server manager), degraded
        ranks are deprioritized: the round samples from the healthy pool
        first and only fills remaining slots with the least-degraded ranks.
        When everyone fits, everyone still participates (reference
        semantics); without a ledger the sampling is bit-identical to the
        reference's round-seeded ``np.random.choice``."""
        if per_round >= len(client_ids):
            return list(client_ids)
        pool = list(client_ids)
        if health is not None:
            healthy, degraded = health.partition(pool)
            if len(healthy) >= per_round:
                pool = healthy
            else:
                pool = healthy + degraded[: per_round - len(healthy)]
        if per_round >= len(pool):
            return list(pool)
        idx = rng.sample_clients_np(round_idx, len(pool), per_round)
        return [pool[i] for i in idx]

    def data_silo_selection(self, round_idx: int, data_silo_num_in_total: int,
                            client_num_in_total: int) -> list[int]:
        """Reference ``data_silo_selection`` (``fedml_aggregator.py:113``)
        bit-parity: each participating client draws a DISTINCT data-silo
        index (round-seeded ``np.random.choice`` without replacement);
        identity when the counts match; more clients than silos is rejected
        exactly as upstream's assert does."""
        if data_silo_num_in_total < client_num_in_total:
            raise ValueError(
                f"data_silo_num_in_total ({data_silo_num_in_total}) must be "
                f">= client_num_in_total ({client_num_in_total})"
            )
        if data_silo_num_in_total == client_num_in_total:
            return list(range(data_silo_num_in_total))
        r = np.random.RandomState(round_idx)
        return r.choice(data_silo_num_in_total, client_num_in_total, replace=False).tolist()


class FedMLServerManager(FedMLCommManager):
    def __init__(self, cfg, aggregator: FedMLAggregator, backend: Optional[str] = None,
                 logger: Optional[MetricsLogger] = None, runtime=None):
        super().__init__(cfg, rank=0, size=cfg.client_num_in_total + 1, backend=backend)
        self.aggregator = aggregator
        self.round_idx = 0
        self.comm_round = cfg.comm_round
        self.client_ids = list(range(1, cfg.client_num_in_total + 1))
        self.per_round = min(cfg.client_num_per_round, len(self.client_ids))
        self.active_clients: set[int] = set()
        self.selected: list[int] = []
        # hierarchical aggregation tree (cross_silo/edge.py): non-None flips
        # dispatch to per-aggregator subtree plans and accepts control-tagged
        # pre-folded partials on the upload path; None (hier flags unset) is
        # the flat protocol, byte-identical to before the tree existed
        self.topology = build_topology(cfg)
        #: wire bytes of model uploads arriving AT THIS NODE, cumulative —
        #: the tentpole quantity (O(edges) with the tree vs O(clients) flat);
        #: _round_payload_bytes is its per-round obs-trail sibling
        self.upload_ingress_bytes = 0
        self.done = threading.Event()
        self.history: list[dict] = []
        self.logger = logger or MetricsLogger(cfg.metrics_jsonl_path or None)
        # bounded-wait straggler handling
        self.straggler_timeout = float(cfg_extra(cfg, "straggler_timeout_s") or 0)
        self.quorum_frac = float(cfg_extra(cfg, "straggler_quorum_frac") or 0.5)
        # event-driven runtime (cross_silo/runtime.py): ONE timer wheel +
        # dispatch loop replaces the per-deadline threading.Timer threads
        # (straggler, status re-probe, async watchdog).  The multi-tenant
        # control plane passes a SHARED runtime so N tenants ride one loop;
        # a manager built without one owns its own (single extra thread,
        # started lazily, timer semantics unchanged).
        from .runtime import ServerRuntime

        self._runtime = runtime if runtime is not None else ServerRuntime()
        self._owns_runtime = runtime is None
        # round-boundary gang gate (sched/multi_tenant.py GangScheduler):
        # None = the single-job path, broadcasts run inline exactly as they
        # always did — bit-identical by construction
        self.round_gate = None
        self._agg_lock = threading.Lock()
        self._init_sent = False
        # set by handlers/timers when the run cannot make progress; surfaced
        # as an exception by run_until_done instead of a silent timeout
        self.failed: Optional[str] = None
        # remote observability (reference mlops_metrics over MQTT): telemetry
        # rides THIS comm manager — client shippers target rank 0
        self.obs_collector = None
        # OTLP egress (obs/otlp.py): gated on extra.otlp_endpoint — unset
        # means no exporter object, no worker thread, default path unchanged
        from ..obs import otlp as obsotlp

        self.otlp = obsotlp.exporter_from_config(cfg)
        if cfg_extra(cfg, "enable_remote_obs") or self.otlp is not None:
            from ..obs.remote import ObsCollector

            # the exporter tees on collector ingest, so rank 0 exports the
            # whole distributed round tree (its own spans + every
            # client-shipped span under one trace_id per round).  Under the
            # multi-tenant control plane every record is stamped with the
            # job id so N tenants' trails stay distinct series downstream
            # instead of collapsing by metric name.
            mt_job = cfg_extra(cfg, "mt_job_id")
            self.obs_collector = ObsCollector(
                cfg_extra(cfg, "obs_jsonl_path") or None, otlp=self.otlp,
                stamp={"job": str(mt_job)} if mt_job else None,
            ).attach(self)
        # per-client health ledger (obs/health.py): EWMA RTT, deadline
        # breaches, comm failures -> fedml_client_health_* gauges.  Always
        # maintained (same always-on stance as the RTT histogram); consulted
        # by client_selection only behind extra.health_aware_selection.
        from ..obs.health import ClientHealthLedger

        self.health = ClientHealthLedger().attach_comm()
        self.health_aware = bool(cfg_extra(cfg, "health_aware_selection"))
        # distributed round tracing: one trace per round, stamped on every
        # broadcast so client train spans join it (obs.trace module doc)
        self._round_span: Optional[obstrace.Span] = None
        self._sent_at: dict[int, float] = {}
        self._round_rtts: dict[int, float] = {}
        # wire bytes of this round's model uploads (obs-trail record)
        self._round_payload_bytes = 0
        # Prometheus exposition, gated on extra['metrics_port']
        self.metrics_server = obsreg.maybe_start_metrics_server(cfg)
        # flight recorder (ISSUE 16), gated on extra.flight_recorder: a
        # bounded black-box ring of recent spans, comm events, metric deltas,
        # and journal/epoch transitions, dumped atomically on hard_kill /
        # finish / unhandled exception / SIGTERM / SLO breach — the input to
        # `fedml-tpu obs postmortem`
        from ..obs import flight as obsflight

        self.flight = obsflight.recorder_from_config(
            cfg, name="server", meta={"role": "server"})
        if self.flight is not None:
            self.flight.attach_comm()
            self.flight.install_signal_handlers()
        # SLO watchdog (ISSUE 16), gated on extra.slo_specs: declarative
        # specs evaluated on registry snapshots via THIS manager's timer
        # wheel (no new threads); breaches land in the collector trail,
        # fedml_slo_breaches_total, and (optionally) a flight dump
        from ..obs import slo as obsslo

        self.slo = obsslo.engine_from_config(
            cfg, runtime=self._runtime, collector=self.obs_collector,
            otlp=self.otlp, flight=self.flight)
        if self.slo is not None:
            self.slo.start()
        # performance timeline (ISSUE 18), gated on extra.perf_timeline:
        # periodic registry-snapshot samples on THIS manager's timer wheel
        # into a bounded ring + atomic segment files, plus the convergence
        # series tee'd from _finish_round — the input to `fedml-tpu obs dash`
        from ..obs import timeline as obstimeline

        self.timeline = obstimeline.timeline_from_config(
            cfg, name="server", runtime=self._runtime,
            meta={"role": "server"})
        if self.timeline is not None:
            self.timeline.start()
        # durable recovery journal (cross_silo/journal.py), gated on
        # extra.server_journal_dir: snapshot full protocol state at round
        # boundaries, recover on restart under a bumped session epoch.
        # Unset -> journal None, epoch never stamped, wire + aggregation
        # byte/bit-identical to before the flag existed.
        from .journal import journal_from_config

        self.journal = journal_from_config(cfg)
        # continuous model publication for the serving fleet (ISSUE 11),
        # gated on extra.model_publish_dir: every version bump atomically
        # writes a version-stamped params file + manifest that serving
        # workers hot-swap from.  Unset -> None, zero publish writes,
        # serving-free runs bit-identical to before the flag existed.
        from ..serving.publisher import publisher_from_config

        self.publisher = publisher_from_config(cfg)
        self.session_epoch = 0
        #: step the journal resumed from (None = fresh start) — the chaos
        #: harness asserts version continuity through it
        self.recovered_step: Optional[int] = None
        self.rejected_stale = 0
        self._journal_every = max(1, int(
            cfg_extra(cfg, "server_journal_every_rounds"))) if self.journal else 1
        # exactly-once uploads (ISSUE 13): recently folded idempotence keys
        # per client + the dedup counter; journaled, so redeliveries of
        # pre-crash folds dedup after recovery too.  Keys only exist when the
        # CLIENT journal stamps them — key-less uploads take the historical
        # path untouched.
        self._folded_keys: dict[int, object] = {}
        self.deduped_uploads = 0
        # mid-round journaling (ISSUE 13, sync server): snapshot the partial
        # streaming fold every N folds so a crash between folds resumes the
        # round's partial sum; the sidecar references the newest boundary
        # model step instead of rewriting the model tree
        self._journal_every_folds = max(0, int(
            cfg_extra(cfg, "server_journal_every_folds"))) if self.journal else 0
        self._last_model_step: Optional[int] = None
        if not getattr(type(self), "_journal_recover_deferred", False):
            self._journal_recover()

    # -- protocol ------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(md.MSG_TYPE_C2S_CLIENT_STATUS, self.handle_message_client_status)
        self.register_message_receive_handler(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.handle_message_receive_model)
        self.register_message_receive_handler(md.MSG_TYPE_C2S_FINISHED, self.handle_message_client_finished)
        # ALWAYS accept OBS batches: a client configured with
        # enable_remote_obs against a server without it must not crash the
        # receive loop (KeyError on unhandled type) — telemetry is
        # best-effort on BOTH ends, so without a collector it is dropped
        from ..obs.remote import MSG_TYPE_C2S_OBS

        if MSG_TYPE_C2S_OBS not in self.message_handler_dict:
            self.register_message_receive_handler(MSG_TYPE_C2S_OBS, lambda _msg: None)

    def start(self) -> None:
        """Ask every client for status (reference connection_ready path).

        Sends are best-effort per client and a re-probe timer retries the
        ranks still missing: one unreachable/lossy peer (an injected chaos
        fault, a client mid-reconnect after a server restart) must delay
        discovery, not deadlock it."""
        for cid in self.client_ids:
            msg = Message(md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, 0, cid)
            try:
                self.send_message(msg)
            except Exception:
                log.warning("status probe to client %d failed; re-probe "
                            "timer retries", cid, exc_info=True)
        self._arm_status_reprobe()

    def _arm_status_reprobe(self, attempt: int = 0) -> None:
        from ..comm.base import BACKOFF_PURPOSE_STATUS_PROBE, backoff_delay

        # capped exponential from a small base (deterministic jitter, its own
        # purpose stream): a probe lost to a flaky wire re-fires in ~100ms, a
        # genuinely slow fleet is re-probed at a gentle 1s cadence.  The
        # attempt counter rides the timer-wheel closure (no shared handle, no
        # shared counter — the state the old per-Timer shape had to suppress
        # GL008 over).
        self._runtime.arm(
            self, "status_probe",
            backoff_delay(attempt, base=0.1, cap=1.0,
                          purpose=BACKOFF_PURPOSE_STATUS_PROBE),
            lambda: self._on_status_reprobe(attempt))

    def _on_status_reprobe(self, attempt: int = 0) -> None:
        """Retry CHECK_CLIENT_STATUS for ranks that never answered (their
        probe or reply was lost on the wire); disarms once the round starts."""
        with self._agg_lock:
            if self._init_sent or self.done.is_set():
                return
            missing = [c for c in self.client_ids if c not in self.active_clients]
        for cid in missing:
            try:
                self.send_message(Message(md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, 0, cid))
            except Exception:
                log.warning("status re-probe to client %d failed", cid,
                            exc_info=True)
        self._arm_status_reprobe(attempt + 1)

    def handle_message_client_status(self, msg: Message) -> None:
        ready = False
        if msg.get(md.MSG_ARG_KEY_CLIENT_STATUS) == md.CLIENT_STATUS_ONLINE:
            with self._agg_lock:
                self.active_clients.add(msg.get_sender_id())
                ready = len(self.active_clients) == len(self.client_ids)
        else:
            with self._agg_lock:
                ready = len(self.active_clients) == len(self.client_ids)
        if ready:
            self.send_init_msg()

    def send_init_msg(self) -> None:
        """Reference ``send_init_msg`` (:48): global model + per-client index.

        Runs under ``_agg_lock`` — the broadcast rewrites round state
        (``selected``, ``_sent_at``, ``_round_payload_bytes``) that the
        receive and straggler-timer threads touch under the same lock, and
        the ``_init_sent`` check makes the call idempotent: a status reply
        arriving mid-run (e.g. a liveness probe answer from a cross-device
        fleet) must not re-fire round 0.

        A recovered server (``recovered_step`` set) re-enters here with
        ``round_idx`` already at the interrupted round: the broadcast simply
        re-issues that round under the new session epoch — the reconnect/
        resume handshake from the clients' side is just answering the status
        check and training on the re-dispatched global."""
        with self._agg_lock:
            if self._init_sent:
                return
            self._init_sent = True
            if self.round_idx >= self.comm_round:
                # crash landed after the final round's snapshot but before
                # the FINISH broadcast: nothing left to train
                self.send_finish()
                return
            # bootstrap publication: serving workers can come up on the
            # initial (or journal-recovered) global before round 1 closes
            self._publish_model()
            self._gated_broadcast(md.MSG_TYPE_S2C_INIT_CONFIG)

    def _gated_broadcast(self, msg_type: int) -> None:  # graftlint: disable=GL004(callers hold _agg_lock: send_init_msg and _finish_round),GL007(round-boundary broadcast: every selected client is idle until the new global arrives, so the host fetch under _agg_lock serializes nothing that could otherwise progress)
        """Start this round's broadcast NOW (single-job path: ``round_gate``
        is None, the call is exactly the historical inline broadcast), or
        queue for the gang scheduler's mesh slot and broadcast on grant
        (multi-tenant path; the grant callback runs on the control plane's
        shared runtime loop, never on a sibling tenant's thread)."""
        if self.round_gate is None:
            self._broadcast_model(msg_type)
            return
        self.round_gate.request(self, lambda: self._granted_broadcast(msg_type))

    def _granted_broadcast(self, msg_type: int) -> None:  # graftlint: disable=GL007(grant callback: the round starts here, so the host fetch under _agg_lock serializes nothing — every selected client is idle until this broadcast lands)
        """Gang-scheduler grant callback: the mesh slot is ours — broadcast
        the round.  Runs on the runtime's dispatch loop."""
        with self._agg_lock:
            if self.done.is_set():
                self.round_gate.release(self)
                return
            self._broadcast_model(msg_type)

    def _candidate_ids(self) -> list[int]:
        """The candidate set for this round's selection — subclasses narrow
        it (cross-device liveness) without mutating shared state."""
        return self.client_ids

    def handle_message_receive_model(self, msg: Message) -> None:
        with self._agg_lock:
            sender = int(msg.get_sender_id())
            # exactly-once (ISSUE 13): a key the server already folded is a
            # redelivery of the same bytes (chaos duplicate, reconnect
            # resend, crash-resend of a journaled attempt) — dropped and
            # counted BEFORE any other gate, since the journaled key table
            # outlives both the round and a server crash
            upload_key = msg.get_control(md.MSG_ARG_KEY_UPLOAD_KEY)
            if upload_key is not None and self._is_duplicate_upload(sender, upload_key):
                self.deduped_uploads += 1
                DEDUPED_UPLOADS.inc()
                return
            if self.journal is not None:
                # session-epoch fence (recovery): an upload produced by a
                # pre-crash dispatch is rejected deterministically — the
                # recovered server re-broadcasts the interrupted round and
                # the client redoes it under the new epoch, so accepting the
                # old reply could double-count the same work
                epoch = int(msg.get_control(
                    md.MSG_ARG_KEY_SESSION_EPOCH, self.session_epoch))
                if epoch != self.session_epoch:
                    self.rejected_stale += 1
                    REJECTED_STALE.inc(reason="epoch")
                    log.info("rejecting stale-epoch upload from client %s "
                             "(epoch %d, current %d)",
                             msg.get_sender_id(), epoch, self.session_epoch)
                    return
            if msg.get(md.MSG_ARG_KEY_ROUND_INDEX) != self.round_idx:
                return  # stale round (post-timeout arrival)
            sent_at = self._sent_at.pop(sender, None)
            if sent_at is not None:
                rtt = time.perf_counter() - sent_at
                CLIENT_ROUND_TRIP.observe(rtt, client=str(sender))
                self.health.observe_rtt(sender, rtt)
                self._round_rtts[sender] = rtt
            nbytes = int(getattr(msg, "wire_nbytes", 0) or 0)
            self._round_payload_bytes += nbytes
            self.upload_ingress_bytes += nbytes
            hier_tag = msg.get_control(md.MSG_ARG_KEY_HIER_PARTIAL)
            if hier_tag is not None:
                # hierarchical tree: ONE pre-folded weighted partial stands
                # in for an edge's whole subtree.  Direct-add fold; the
                # per-source masses land in the same ledgers, so the
                # all-receive check below counts clients exactly as flat.
                HIER_HOP_BYTES.inc(nbytes, hop="edge_root")
                if not self.aggregator.fold_partial(
                        msg, hier_tag.get("sources") or {},
                        float(hier_tag.get("w_delta", 0.0))):
                    # a partial has no dense fallback: unfoldable means a
                    # protocol bug or a config split-brain — drop loudly
                    log.warning("dropping unfoldable edge partial from %d "
                                "(round %d)", sender, self.round_idx)
                    return
                self._note_upload_key(sender, upload_key)
                if (self._journal_every_folds
                        and self.aggregator._stream_folded
                        and self.aggregator._stream_folded
                        % self._journal_every_folds == 0):
                    self._journal_midround_snapshot()
                if self.aggregator.check_whether_all_receive(len(self.selected)):
                    self._finish_round()
                return
            n_samples = float(msg.get(md.MSG_ARG_KEY_NUM_SAMPLES))
            # control-only read: raw (non-delta) uploads carry no delta flag,
            # and a plain get() of the missing key would materialize the
            # tensor section — silently demoting the streaming fold to the
            # dense buffer-all path
            is_delta = bool(msg.get_control(md.MSG_ARG_KEY_MODEL_IS_DELTA, False))
            # streaming path first: fold the still-undecoded frame into the
            # running weighted sum so aggregation overlaps the network tail;
            # falls back to the buffer-all (reference-bit-exact) path
            if not self.aggregator.ingest_streaming(sender, msg, n_samples, is_delta):
                params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
                if is_delta:
                    self.aggregator.add_local_trained_result(
                        sender, params, n_samples, is_delta=True)
                else:
                    # positional, delta-free call: secure-agg subclasses
                    # (masked/ciphertext uploads) override this method with
                    # the historical 3-arg signature
                    self.aggregator.add_local_trained_result(sender, params, n_samples)
            self._note_upload_key(sender, upload_key)
            # mid-round durability (ISSUE 13): every N streaming folds the
            # partial sums go to the journal, so a crash between folds
            # resumes the round's fold instead of redoing it
            if (self._journal_every_folds
                    and self.aggregator._stream_folded
                    and self.aggregator._stream_folded
                    % self._journal_every_folds == 0):
                self._journal_midround_snapshot()
            if self.aggregator.check_whether_all_receive(len(self.selected)):
                self._finish_round()

    def _arm_straggler_timer(self) -> None:
        if self.straggler_timeout <= 0:
            return
        # re-arming the same (owner, name) supersedes the previous deadline
        # atomically on the wheel — the cancel+create dance the raw Timer
        # handle needed is gone, and so is the handle
        self._runtime.arm(self, "straggler", self.straggler_timeout,
                          self._on_straggler_timeout)

    def _on_straggler_timeout(self) -> None:
        with self._agg_lock:
            need = max(1, int(math.ceil(self.quorum_frac * len(self.selected))))
            if self.aggregator.received_count() >= need:
                log.warning(
                    "round %d: straggler timeout, aggregating %d/%d clients",
                    self.round_idx, self.aggregator.received_count(), len(self.selected),
                )
                # the round proceeds without them: every selected-but-missing
                # rank breached the deadline — the health ledger remembers,
                # and (behind extra.health_aware_selection) later rounds
                # deprioritize repeat offenders
                for cid in self.selected:
                    if not self.aggregator.has_received(cid):
                        self.health.record_deadline_breach(cid)
                self._finish_round()
            else:
                self._arm_straggler_timer()  # keep waiting for quorum

    def _finish_round(self) -> None:
        """Aggregate, eval, and either sync the next round or finish.
        Caller holds _agg_lock."""
        self._runtime.cancel(self, "straggler")
        received = self.aggregator.received_count()
        with obstrace.traced("aggregate", parent=self._round_span,
                             round_idx=self.round_idx,
                             clients_received=received) as agg_span:
            self.aggregator.aggregate(self.round_idx)
        AGGREGATE_TIME.observe(agg_span.duration_s)
        BUFFERED_PEAK.set(self.aggregator.peak_buffered_updates)
        metrics = {"round": self.round_idx}
        eval_span = None
        if self.cfg.frequency_of_the_test and (
            (self.round_idx + 1) % self.cfg.frequency_of_the_test == 0
            or self.round_idx == self.comm_round - 1
        ):
            with obstrace.traced("eval", parent=self._round_span,
                                 round_idx=self.round_idx) as eval_span:
                metrics.update(self.aggregator.test_on_server())
        self._close_round_trace(agg_span, eval_span)
        self.logger.log(metrics)
        self.history.append(metrics)
        if self.timeline is not None:
            # convergence tee: (round_idx, test_acc, wall) becomes timeline
            # data + the rounds-to-target gauge
            self.timeline.note_round(round_idx=self.round_idx,
                                     test_acc=metrics.get("test_acc"))
        self.round_idx += 1
        self._journal_snapshot()
        self._publish_model()
        if self.round_gate is not None:
            # round boundary: the aggregate is committed — give the mesh
            # slot back so sibling tenants can interleave their rounds
            self.round_gate.release(self)
        if self.round_idx >= self.comm_round:
            self.send_finish()
            return
        self._gated_broadcast(md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)

    def _close_round_trace(self, *child_spans) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: _finish_round and the async server's _close_virtual_round call this)
        """End the round span, record its duration, and persist the server's
        half of the round trace (spans + per-client round trips) into the
        same collector trail the clients ship to."""
        round_span = self._round_span
        if round_span is None:
            return
        round_span.end()
        ROUND_TIME.observe(round_span.duration_s)
        if self.obs_collector is not None:
            records = [s.to_record() for s in child_spans if s is not None]
            records.append(round_span.to_record())
            records += [
                {"kind": "metric", "metric": "client_round_trip_s",
                 "client": cid, "value": rtt, "round_idx": self.round_idx,
                 "trace_id": round_span.trace_id, "ts": time.time()}
                for cid, rtt in sorted(self._round_rtts.items())
            ]
            # per-round wire bytes of model uploads (compression shows up
            # here as the raw-vs-compressed byte trajectory across rounds)
            records.append(
                {"kind": "metric", "metric": "comm_payload_bytes",
                 "value": int(self._round_payload_bytes),
                 "round_idx": self.round_idx,
                 "trace_id": round_span.trace_id, "ts": time.time()}
            )
            # health trajectory rides the same trail: one client_health
            # record per known client, per round (obs report renders it)
            records += self.health.records(trace_id=round_span.trace_id)
            if self.topology is not None:
                # hierarchy trajectory: cumulative tree counters per round
                # (INPROC edges share this process's registry) — obs report's
                # hierarchy section differences consecutive records
                records.append(
                    {"kind": "metric", "metric": "hier_tree",
                     "round_idx": self.round_idx,
                     "trace_id": round_span.trace_id, "ts": time.time(),
                     "hop_bytes": {
                         hop: int(HIER_HOP_BYTES.value(hop=hop))
                         for hop in ("client_edge", "edge_region", "edge_root")
                     },
                     "folds": int(HIER_EDGE_FOLDS.value()),
                     "relays": int(HIER_EDGE_RELAYS.value()),
                     "deduped": int(HIER_EDGE_DEDUPED.value()),
                     "partials_sent": int(HIER_PARTIALS_SENT.value()),
                     "depth": int(HIER_TREE_DEPTH.value()),
                     "fanout": int(HIER_TREE_FANOUT.value()),
                     "edges": int(HIER_TREE_EDGES.value())}
                )
            self.obs_collector.ingest(0, records)
        if self.flight is not None:
            for s in child_spans:
                if s is not None:
                    self.flight.span_sink(s.to_record())
            self.flight.span_sink(round_span.to_record())
            self.flight.record_metric_deltas()
        self._round_rtts.clear()
        self._round_span = None

    def _broadcast_model(self, msg_type: int) -> None:  # graftlint: disable=GL004(callers hold _agg_lock: send_init_msg and _finish_round)
        """Select clients, send them the global model for this round, arm the
        straggler timer — shared by round 0 (INIT) and later rounds (SYNC)."""
        self.selected = self.aggregator.client_selection(
            self.round_idx, self._candidate_ids(), self.per_round,
            health=self.health if self.health_aware else None,
        )
        # one fresh trace per round: every broadcast carries its header, so
        # each client's train span lands in this round's span tree
        self._round_span = obstrace.Span(
            "round", round_idx=self.round_idx, clients=len(self.selected)
        )
        self._round_rtts.clear()
        self._round_payload_bytes = 0
        params = jax.device_get(self.aggregator.global_vars)
        if self.topology is not None:
            self._broadcast_model_hier(msg_type, params)
            return
        for cid in self.selected:
            if self.aggregator.has_received(cid):
                # mid-round journal resume (ISSUE 13): this client's fold is
                # already in the restored partial sums — it stays selected
                # (the all-receive count includes it) but is not re-asked to
                # redo work the journal kept.  Empty outside recovery: flags
                # reset at every aggregate.
                continue
            msg = Message(msg_type, 0, cid)
            msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
            msg.add_params(md.MSG_ARG_KEY_CLIENT_INDEX, cid - 1)
            msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            if self.journal is not None:
                # recovery fence: clients echo this epoch in their reply so a
                # restarted server can tell pre-crash work from current work
                msg.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, self.session_epoch)
            obstrace.inject(msg, self._round_span)
            try:
                self._sent_at[cid] = time.perf_counter()
                self.send_message(msg)
            except Exception:
                # best-effort per client: one unreachable peer must not kill
                # the receive/timer thread mid-broadcast and hang the run —
                # quorum + straggler handling own progress for missing clients
                self.health.record_comm_failure(cid)
                log.warning("broadcast to client %d failed; continuing", cid, exc_info=True)
        self._arm_straggler_timer()

    def _broadcast_model_hier(self, msg_type: int, params) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: _broadcast_model only)
        """Tree dispatch: ONE message per direct-child aggregator, carrying
        the global plus that subtree's routing plan (HIER_CHILDREN) — root
        egress connections drop from O(clients) to O(root children), the
        downlink mirror of the uplink fan-in win.  Clients whose fold the
        journal already holds are excluded from the plan (the edge never
        re-asks them); the straggler timer + quorum math are unchanged
        because the partial's sources land in the same per-client ledgers."""
        skip = [cid for cid in self.selected
                if self.aggregator.has_received(cid)]
        plan = self.topology.dispatch_plan(self.selected, skip=skip)
        for agg_rank, spec in sorted(plan.items()):
            msg = Message(msg_type, 0, agg_rank)
            msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
            msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            msg.add_params(md.MSG_ARG_KEY_HIER_CHILDREN, spec)
            if self.journal is not None:
                msg.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, self.session_epoch)
            obstrace.inject(msg, self._round_span)
            try:
                # per-hop RTT attribution: the pop on the partial's arrival
                # observes THIS hop (root<->aggregator), not a client's
                self._sent_at[agg_rank] = time.perf_counter()
                self.send_message(msg)
            except Exception:
                self.health.record_comm_failure(agg_rank)
                log.warning("hier dispatch to aggregator %d failed; "
                            "continuing", agg_rank, exc_info=True)
        self._arm_straggler_timer()

    # -- model publication (ISSUE 11) -----------------------------------------
    def _publish_model(self) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: send_init_msg and the round-boundary finalizers)
        """Atomically publish the current global as version ``round_idx``
        (the async subclass's version counter mirrors into ``round_idx`` at
        every bump, so one site serves both servers).  Publication is
        best-effort by construction — ``ModelPublisher.publish`` logs and
        skips on failure, never costing the round."""
        if self.publisher is None:
            return
        self.publisher.publish(
            self.round_idx, self.aggregator._host_global(),
            meta={"model": self.cfg.model,
                  "run_id": str(getattr(self.cfg, "run_id", "0")),
                  "session_epoch": self.session_epoch})

    # -- exactly-once upload dedup (ISSUE 13) ---------------------------------
    def _is_duplicate_upload(self, sender: int, key: str) -> bool:  # graftlint: disable=GL004(caller holds _agg_lock: receive-handler gate)
        dq = self._folded_keys.get(sender)
        return dq is not None and key in dq

    def _note_upload_key(self, sender: int, key: Optional[str]) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: receive-handler accept path)
        """Remember a folded upload's idempotence key (bounded per client)."""
        if key is None:
            return
        from collections import deque

        dq = self._folded_keys.get(sender)
        if dq is None:
            dq = self._folded_keys[sender] = deque(maxlen=DEDUP_KEYS_PER_CLIENT)
        dq.append(key)

    def _export_folded_keys(self) -> dict:  # graftlint: disable=GL004(caller holds _agg_lock: journal snapshot sites)
        return {str(c): list(dq) for c, dq in sorted(self._folded_keys.items())}

    def _restore_folded_keys(self, proto: dict) -> None:  # graftlint: disable=GL004(construction-time: runs from _journal_recover before any thread exists)
        from collections import deque

        for c, keys in (proto.get("folded_keys") or {}).items():
            self._folded_keys[int(c)] = deque(
                [str(k) for k in keys], maxlen=DEDUP_KEYS_PER_CLIENT)
        self.deduped_uploads = int(proto.get("deduped", 0))

    # -- recovery journal -----------------------------------------------------
    def _journal_recover(self) -> None:  # graftlint: disable=GL004(construction-time: runs from __init__ before the receive loop or any timer thread exists)
        """Install the newest intact journal snapshot (construction-time):
        round index, model/server-state tree, streaming partials (including
        a MID-ROUND partial fold — the round then resumes instead of
        redoing), folded-key dedup table, health scores; resume under a
        bumped session epoch so pre-crash uploads are recognizable."""
        if self.journal is None:
            return
        snap = self.journal.restore(model_template=self.aggregator.model_state())
        if snap is None:
            return
        proto = snap["protocol"]
        self.session_epoch = int(proto.get("session_epoch", 0)) + 1
        self.round_idx = int(proto.get("round_idx", 0))
        self.recovered_step = int(snap["step"])
        self._last_model_step = snap.get("model_step")
        if snap["model"] is not None:
            self.aggregator.restore_model_state(snap["model"])
        self.aggregator.restore_stream_state(proto, snap["arrays"])
        self._restore_folded_keys(proto)
        self.health.import_state(proto.get("health") or {})
        if self.flight is not None:
            self.flight.note("epoch", event="recovery", step=self.recovered_step,
                             round_idx=self.round_idx, epoch=self.session_epoch)
        log.info("recovered from journal step %d (round %d, session epoch %d, "
                 "%d folds carried)", self.recovered_step, self.round_idx,
                 self.session_epoch, self.aggregator._stream_folded)

    def _journal_protocol_state(self) -> dict:  # graftlint: disable=GL004(caller holds _agg_lock: _journal_snapshot runs at locked round boundaries)
        return {"kind": "sync", "session_epoch": self.session_epoch,
                "round_idx": self.round_idx,
                "rejected_stale": self.rejected_stale,
                "deduped": self.deduped_uploads,
                "folded_keys": self._export_folded_keys(),
                "health": self.health.export_state()}

    def _journal_snapshot(self) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: round-boundary sites only)
        """Commit the full protocol state at a round boundary (cadence:
        ``server_journal_every_rounds``; the final round always commits)."""
        if self.journal is None:
            return
        step = self.round_idx
        if (step % self._journal_every) and step < self.comm_round:
            return
        stream_proto, arrays = self.aggregator.export_stream_state()
        self.journal.snapshot(
            step, {**self._journal_protocol_state(), **stream_proto},
            arrays, model_state=self.aggregator.model_state())
        self._last_model_step = step
        if self.flight is not None:
            self.flight.note("journal", event="snapshot", step=step,
                             epoch=self.session_epoch)

    def _journal_midround_snapshot(self) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: receive-handler fold-cadence site)
        """Commit the in-progress round's partial streaming fold (ISSUE 13):
        the sidecar carries the accumulator partials + the folded-client set
        and REFERENCES the boundary step whose model checkpoint holds this
        round's starting global (``model_step``) — no model rewrite, so the
        cadence stays cheap.  Atomically overwrites this round's sidecar
        with more progress each time."""
        stream_proto, arrays = self.aggregator.export_stream_state()
        self.journal.snapshot(
            self.round_idx, {**self._journal_protocol_state(), **stream_proto},
            arrays, model_step=self._last_model_step)

    def hard_kill(self) -> None:  # graftlint: disable=GL008(crash simulation: deliberately lock-free — a SIGKILL takes no locks either; every surviving thread re-checks state under _agg_lock and exits)
        """Crash simulation for the chaos harness (sync server): stop the
        receive loop and all timers ABRUPTLY — no FINISH broadcast, no
        journal write, no teardown bookkeeping.  Everything not already
        committed to the journal (including a mid-round partial fold past
        the last fold-cadence snapshot) is lost, exactly like a SIGKILL;
        only the process stays alive for the test to inspect."""
        if self.flight is not None:
            # the black-box moment: what was in flight when the axe fell
            # (racy reads by design — a SIGKILL takes no locks either)
            self.flight.trigger(
                "hard_kill", round_idx=self.round_idx,
                epoch=self.session_epoch,
                awaiting=[c for c in self.selected
                          if not self.aggregator.has_received(c)])
        self._runtime.cancel(self)
        self.com_manager.stop_receive_message()

    def send_finish(self) -> None:
        ranks = list(self.client_ids)
        if self.topology is not None:
            # aggregator nodes shut down on the same terminal broadcast
            ranks += self.topology.aggregator_ranks
        for cid in ranks:
            try:
                self.send_message(Message(md.MSG_TYPE_S2C_FINISH, 0, cid))
            except Exception:
                # best-effort terminal broadcast: one unreachable peer must
                # not strand the rest of the fleet without FINISH or leave
                # done unset (the run DID complete)
                log.warning("FINISH to client %d failed", cid, exc_info=True)
        self.done.set()
        self._prune_retired_client_journals()
        self.finish()

    def _prune_retired_client_journals(self) -> None:
        """Run-complete housekeeping (ISSUE 14 satellite): reclaim the
        per-rank journal dirs of clients no longer in this fleet's live set
        — bounded by ``client_journal_keep_retired``, best-effort (a prune
        failure never costs the run)."""
        root = cfg_extra(self.cfg, "client_journal_dir")
        if not root:
            return
        from .client_journal import prune_retired_client_dirs

        try:
            prune_retired_client_dirs(
                root, self.client_ids,
                keep=int(cfg_extra(self.cfg, "client_journal_keep_retired")))
        except Exception:
            log.warning("retired-client journal prune failed", exc_info=True)

    def handle_message_client_finished(self, msg: Message) -> None:
        pass  # bookkeeping only

    def finish(self) -> None:  # graftlint: disable=GL008(teardown: finish can race the straggler timer's finish, but every resource close here is idempotent and metrics_server flips non-None->None exactly once per object)
        self._runtime.cancel(self)
        if self.round_gate is not None:
            # never strand a held mesh slot on an abnormal teardown
            self.round_gate.release(self)
        if self.slo is not None:
            self.slo.stop()
        if self.timeline is not None:
            # final sample + segment flush, then the timer is released
            # (close latches, so the timeout-path double finish is safe)
            self.timeline.close()
        if self.flight is not None and not self.flight._closed:
            # one terminal bundle per run (close() latches, so the racing
            # straggler-timer finish can't dump twice)
            self.flight.trigger("finish", round_idx=self.round_idx,
                                epoch=self.session_epoch, failed=self.failed)
            self.flight.close()
        super().finish()
        if self.obs_collector is not None:
            self.obs_collector.close()  # release the JSONL append handle
        if self.otlp is not None:
            # drain queued spans + ship the final registry snapshot (close
            # is idempotent — finish can run twice on the timeout path)
            self.otlp.close()
        self.health.detach_comm()
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self._owns_runtime:
            self._runtime.close()

    # -- runner API ----------------------------------------------------------
    def run_until_done(self, timeout: float = 600.0) -> list[dict]:  # graftlint: disable=GL008(reads after done.wait() are ordered by the Event (set after the last locked write); the round_idx read in the timeout message is an intentionally racy diagnostic)
        thread = self.run_in_thread()
        self.start()
        if not self.done.wait(timeout):
            self.finish()
            raise TimeoutError(f"cross-silo run did not finish in {timeout}s (round {self.round_idx})")
        thread.join(timeout=5.0)
        if self.failed:
            if self.flight is not None:
                self.flight.trigger("run_failed", reason=self.failed)
            raise RuntimeError(f"cross-silo run failed: {self.failed}")
        return self.history
