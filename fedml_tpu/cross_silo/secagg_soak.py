"""Simulated-cohort soak for streaming secure aggregation (ISSUE 15).

Drives the SAME server-side machinery the Shamir protocol rides —
:class:`~fedml_tpu.trust.secagg.stream.StreamingMaskedSum` over the
``FieldStreamAccumulator``, :func:`~fedml_tpu.trust.secagg.shamir.
masked_input` masking, seed-reconstructed unmask at finalize — at cohort
sizes no thread-per-client harness reaches (the 10k-cohort population
rounds the buffer-all gate used to exclude from secure aggregation).

Mask topology: the full N^2 pairwise graph of the cross-silo protocol is
O(N^2 * d) PRG work — at 10k clients that is the simulation's wall, not the
server's.  The soak uses the k-regular ring topology of scalable SecAgg
(Bell et al., CCS'20: each client pair-masks with k neighbors per side),
which changes NOTHING server-side — the fold is the fold, and unmask just
receives fewer pair seeds.  Dropout reconstruction is exercised both ways:
``drop_before`` clients complete setup but never upload (their orphaned
pair masks are cancelled from reconstructed seeds), ``drop_after`` clients
upload but vanish before the reveal phase (their self-masks come out of
OTHER clients' Shamir shares — the harness models the reconstruction as
having succeeded, which is exactly what the real reveal flow yields).

Each client's "local training" is a deliberately cheap deterministic proxy
(one (PROXY_HIDDEN x d) matvec): the on/off throughput ratio is an OVERHEAD
bound — real local training is orders of magnitude heavier, so the measured
ratio is a floor on what a deployment would see.  What the soak asserts
hard is the headline: peak buffered <= 2 at any cohort, and the streamed
masked sum == the exact unmasked sum of the quantized updates, as an
INTEGER identity (mod-field exactness, no FMA tolerance).
"""

from __future__ import annotations

import time

import numpy as np

from ..trust.secagg import stream as secagg_stream
from ..trust.secagg.field import quantize_to_field
from .secagg_shamir import derive_round_seed

__all__ = ["run_secagg_stream_soak"]

#: hidden width / step count of the proxy local train (see module
#: docstring): 16 matvec steps ~ 0.5 ms/client — still orders of magnitude
#: below real local training, so the measured on/off ratio UNDERSTATES a
#: deployment's
PROXY_HIDDEN = 64
PROXY_STEPS = 16


def _neighbors(u: int, cohort: int, k: int) -> list[int]:
    """k-regular ring neighborhood of client ``u`` (1-based ids)."""
    out = []
    for off in range(1, k + 1):
        out.append((u - 1 + off) % cohort + 1)
        out.append((u - 1 - off) % cohort + 1)
    return sorted(set(out) - {u})


def _pair_seed(u: int, v: int, round_idx: int) -> int:
    lo, hi = min(u, v), max(u, v)
    return derive_round_seed(lo * 1_000_003 + hi, round_idx)


def _self_seed(u: int, round_idx: int) -> int:
    return derive_round_seed(0xB00000 + u, round_idx)


def _proxy_update(w: np.ndarray, u: int, round_idx: int, dim: int,
                  seed: int) -> np.ndarray:
    """Deterministic stand-in for a client's local delta: a short loop of
    matvec steps so both the secure and plain paths carry the per-client
    compute cost every real round has."""
    rng = np.random.default_rng([seed, round_idx, u])
    x = rng.standard_normal(dim).astype(np.float32)
    d = np.zeros(dim, np.float32)
    for _ in range(PROXY_STEPS):
        h = np.tanh(w @ (x + d))
        d = d + 0.01 * (w.T @ h) / PROXY_HIDDEN
    return (d + 0.001 * x).astype(np.float32)


def run_secagg_stream_soak(cohort: int = 10_000, dim: int = 4096,
                           rounds: int = 2, neighbors: int = 2,
                           codec: str = "qsgd8", frac_bits: int = 7,
                           q_bits: int = 16, drop_before_frac: float = 0.001,
                           drop_after_frac: float = 0.001,
                           seed: int = 0) -> dict:
    """One soak: ``rounds`` streamed secure rounds at ``cohort`` clients vs
    the same rounds with SecAgg off (plain f32 streaming fold of the same
    proxy updates).  Returns the measured dict (see bench.py secagg)."""
    ring = secagg_stream.ring_for(
        codec if codec == "qsgd8" else None, cohort,
        q_bits=q_bits, q8_frac_bits=frac_bits)
    id_rng = np.random.default_rng([seed, 0xD07])
    ids = np.arange(1, cohort + 1)
    n_db = int(round(cohort * drop_before_frac))
    n_da = int(round(cohort * drop_after_frac))
    struck = id_rng.choice(ids, size=n_db + n_da, replace=False)
    drop_before = set(int(u) for u in struck[:n_db])
    drop_after = set(int(u) for u in struck[n_db:])
    w_proxy = np.random.default_rng([seed, 0x17]).standard_normal(
        (PROXY_HIDDEN, dim)).astype(np.float32) / np.sqrt(dim)

    def quantize(x: np.ndarray, u: int, r: int) -> np.ndarray:
        if ring.codec == "qsgd8":
            q = secagg_stream.quantize_stochastic_int8(
                x, ring.frac_bits, [seed, r, u])
            return np.mod(q, ring.modulus)
        return quantize_to_field(x, p=ring.modulus, bits=ring.frac_bits)

    secure_s = 0.0
    plain_s = 0.0
    peak = 0
    bitwise = True
    uploaded: list[int] = []
    for r in range(rounds):
        # ---- SecAgg ON: quantize -> mask -> streamed fold -> unmask ----
        msum = secagg_stream.StreamingMaskedSum(dim, ring)
        expect = np.zeros(dim, np.int64)  # oracle, untimed
        uploaded = [int(u) for u in ids if u not in drop_before]
        t0 = time.perf_counter()
        for u in uploaded:
            upd = _proxy_update(w_proxy, u, r, dim, seed)
            xf = quantize(upd, u, r)
            peers = {v: _pair_seed(u, v, r)
                     for v in _neighbors(u, cohort, neighbors)}
            masked = secagg_stream.mask_vector(xf, u, peers, _self_seed(u, r),
                                               ring.modulus)
            msum.fold(masked)
            t_oracle = time.perf_counter()
            expect += xf
            t0 += time.perf_counter() - t_oracle  # oracle time excluded
        self_seeds = {u: _self_seed(u, r) for u in uploaded}
        dropped_pairs = {
            (u, v): _pair_seed(u, v, r)
            for u in drop_before
            for v in _neighbors(u, cohort, neighbors) if v not in drop_before
        }
        total = msum.finalize(self_seeds, dropped_pairs)
        secure_s += time.perf_counter() - t0
        peak = max(peak, msum.peak_buffered)
        half = ring.modulus // 2
        exp_mod = np.mod(expect, ring.modulus)
        exp_signed = np.where(exp_mod > half, exp_mod - ring.modulus, exp_mod)
        bitwise = bitwise and bool(np.array_equal(total, exp_signed))

        # ---- SecAgg OFF: the same updates through the plain f32 fold ----
        from ..parallel.stream_fold import HostStreamAccumulator

        acc = HostStreamAccumulator([np.zeros(dim, np.float32)])
        t0 = time.perf_counter()
        for u in uploaded:
            upd = _proxy_update(w_proxy, u, r, dim, seed)
            acc.fold_leaf(0, 1.0, upd)
        acc.finalize([np.zeros(dim, np.float32)], 0.0, float(len(uploaded)))
        plain_s += time.perf_counter() - t0

    versions_on = rounds / max(secure_s, 1e-9)
    versions_off = rounds / max(plain_s, 1e-9)
    bytes_round = ring.wire_nbytes(dim) * len(uploaded)
    dense_ring = secagg_stream.ring_for(None, cohort, q_bits=q_bits,
                                        q8_frac_bits=frac_bits)
    return {
        "cohort": int(cohort),
        "dim": int(dim),
        "rounds": int(rounds),
        "codec": ring.codec,
        "ring_bits": int(ring.bits),
        "neighbors": int(neighbors),
        "dropped_before": len(drop_before),
        "dropped_after": len(drop_after),
        "peak_buffered": int(peak),
        "bitwise_identity": bool(bitwise),
        "versions_per_sec_on": round(versions_on, 3),
        "versions_per_sec_off": round(versions_off, 3),
        "throughput_ratio": round(versions_on / max(versions_off, 1e-9), 3),
        "bytes_per_round": int(bytes_round),
        "bytes_per_round_dense_mask": int(dense_ring.wire_nbytes(dim)
                                          * len(uploaded)),
        "bytes_per_round_legacy_int64": int(8 * dim * len(uploaded)),
    }
