"""Cross-silo message protocol constants.

Exact parity with ``cross_silo/server/message_define.py:7-19`` /
``cross_silo/client/message_define.py`` so wire traces are comparable:
"""

MSG_TYPE_CONNECTION_IS_READY = 0
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
MSG_TYPE_C2S_CLIENT_TEST_INFO = 4
MSG_TYPE_C2S_CLIENT_STATUS = 5
MSG_TYPE_S2C_CHECK_CLIENT_STATUS = 6
MSG_TYPE_S2C_FINISH = 7
MSG_TYPE_C2S_FINISHED = 8

MSG_ARG_KEY_MODEL_PARAMS = "model_params"
# TPU-native extension: True when MODEL_PARAMS carries the delta vs the
# global model the client received (the compressed-upload path) rather than
# full weights — rides the JSON control section so every transport keeps it
MSG_ARG_KEY_MODEL_IS_DELTA = "model_is_delta"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_CLIENT_STATUS = "client_status"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"
MSG_ARG_KEY_CLIENT_OS = "client_os"
# TPU-native extension: the server's crash-recovery session epoch (ISSUE 10).
# Stamped into every dispatch when extra.server_journal_dir is set and echoed
# back in the client's model reply, so a recovered server can tell uploads
# produced by pre-crash dispatches from current-epoch work and fold or reject
# them deterministically (never double-folded).  Absent when the journal is
# off — the wire stays byte-identical to the journal-free protocol.
MSG_ARG_KEY_SESSION_EPOCH = "session_epoch"
# TPU-native extension: upload idempotence key (ISSUE 13).  Stamped as
# "<rank>:<round>:<epoch>:<attempt>" on every model reply when the client's
# crash-recovery journal (extra.client_journal_dir) is on; the client
# journals the attempt counter BEFORE the send, so every distinct piece of
# work carries a distinct key and any wire-level redelivery (chaos duplicate,
# reconnect resend, crash-resend of an unjournaled attempt) is recognizable —
# the servers fold each key at most once and count the rest as deduped.
# Absent when client journaling is off: wire byte-identical to before.
MSG_ARG_KEY_UPLOAD_KEY = "upload_key"
# TPU-native extension: hierarchical aggregation tree (cross_silo/edge.py).
# HIER_PARTIAL rides the control section of an edge aggregator's upload to
# its parent and marks MODEL_PARAMS as a PRE-FOLDED weighted partial sum
# (sum_c w_c * x_c over the edge's children) rather than one client's model:
# {"sources": {client_rank: weight}, "w_delta": delta_mass}.  The parent
# folds it with unit weight — IEEE-exact, so the tree fold stays bitwise the
# flat fold.  HIER_CHILDREN rides the root's dispatch to an aggregator and
# names the subtree to relay to: {"clients": {rank: client_index}} at an
# edge, {"aggs": {edge_rank: <edge-level dict>}} at a region.  Both keys are
# absent in the flat protocol — wire byte-identical to before they existed.
MSG_ARG_KEY_HIER_PARTIAL = "hier_partial"
MSG_ARG_KEY_HIER_CHILDREN = "hier_children"

CLIENT_STATUS_ONLINE = "ONLINE"
CLIENT_OS_PYTHON = "python"
