"""Durable client recovery journal — survivable cross-silo clients (ISSUE 13).

PR 10 made the *server* crash-safe; a killed client still lost everything it
owned: its error-feedback residuals (silently corrupting the qsgd8/topk
compression contract — the dropped top-k mass is supposed to be re-injected
next round, and a cold rejoin throws it away), its last-seen session epoch,
and its upload bookkeeping (a reconnecting client could re-send an upload the
server already folded).  The communication-perspective FL survey (PAPERS.md
2405.20431) names exactly this client churn the dominant reality practical
deployments must absorb, so client state gets the same treatment the server
got:

- :class:`ClientJournal` — per-client, step-addressed snapshots in the
  ``MAGIC + json meta + npz`` envelope with the tmp+``os.replace``+fsync+
  flock discipline proven by :class:`~fedml_tpu.cross_silo.journal.
  ServerJournal` and the AOT store (it *is* a ``ServerJournal`` pointed at
  ``<root>/client_<rank>``; the model checkpointer half simply stays unused).
- **Snapshot-before-send is the exactly-once protocol.**  The client commits
  ``(residuals, round/version, epoch, attempt)`` durably and only THEN sends
  the upload carrying the idempotence key ``<rank>:<round>:<epoch>:<attempt>``
  — so every distinct piece of work ships under a distinct key, and any
  redelivery of the same bytes (a chaos duplicate, a reconnect resend, a
  crash-resend of an attempt whose snapshot committed) reuses the same key
  and is deduped by the server.  A crash BETWEEN snapshot and send just burns
  an attempt number; a crash before the snapshot re-trains deterministically
  (same round, same rng stream) and re-sends under the same key, which the
  server folds at most once either way.
- **Residual durability is bitwise.**  The journal stores the leaf-aligned
  error-feedback residual list exactly as the codec returned it, so a
  restarted client's next compressed upload is bit-identical to the upload an
  uncrashed client would have produced (proven by the crash-parity test).

Gated entirely on ``extra.client_journal_dir``: unset means
:func:`client_journal_from_config` returns ``None``, no key header is ever
stamped, and the client's wire bytes stay byte-identical to the journal-free
protocol.

Thread model (GL008-audited): one journal belongs to ONE client manager and
every snapshot/restore runs on that manager's receive-loop thread (handlers)
or at construction — the journal itself is lock-free; the inherited flock is
CROSS-process (a not-yet-dead predecessor vs the restarted client).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import numpy as np

from ..core.flags import cfg_extra
from ..obs import registry as obsreg
from .journal import ServerJournal

log = logging.getLogger("fedml_tpu.cross_silo.client_journal")

__all__ = ["ClientJournal", "client_journal_from_config",
           "pack_client_state", "unpack_client_state",
           "prune_retired_client_dirs"]

CLIENT_RESUMES = obsreg.REGISTRY.counter(
    "fedml_client_journal_resumes_total",
    "Client-journal restore attempts at client construction, by result "
    "(resumed = state applied, cold = no intact step found).",
    labels=("result",),
)

#: upload-attempt entries retained per client — bounded: only the current
#: (round, epoch) can be re-dispatched, older entries exist purely so a
#: late redelivery of a previous round's key still reads as intentional
MAX_ATTEMPT_ENTRIES = 8


class ClientJournal(ServerJournal):
    """Per-client recovery journal: the :class:`ServerJournal` envelope and
    atomicity, scoped to ``<root>/client_<rank>`` with a local monotonic
    step sequence (async dispatches can repeat a server version, so the
    version is state *inside* the snapshot, not its address)."""

    def __init__(self, root: str, rank: int, keep: int = 2):
        super().__init__(os.path.join(str(root), f"client_{int(rank)}"),
                         keep=keep)
        self.rank = int(rank)
        steps = self.steps()
        self._seq = steps[-1] if steps else 0

    def snapshot_state(self, protocol: dict,
                       arrays: Optional[dict] = None) -> None:
        """Commit the next step in this client's local sequence."""
        self._seq += 1
        self.snapshot(self._seq, protocol, arrays)

    def restore_state(self) -> Optional[dict]:
        """Newest intact snapshot (``{"step", "protocol", "arrays", ...}``)
        or None; advances the local sequence past it so post-restore
        snapshots never rewind."""
        snap = self.restore()
        if snap is not None:
            self._seq = max(self._seq, int(snap["step"]))
        return snap


def pack_client_state(*, rank: int, round_idx: Optional[int],
                      session_epoch: Optional[int], rounds_trained: int,
                      server_restarts_seen: int, upload_attempts: dict,
                      residuals: Optional[list],
                      trainer_state: Any = None) -> tuple[dict, dict]:
    """Client protocol state -> (json protocol, named numpy arrays).

    ``residuals`` is the codec's leaf-aligned error-feedback list (entries
    may be None — qsgd8 carries none, topk skips small/raw leaves); the
    arrays store only the present entries and the protocol records the list
    length + indices so :func:`unpack_client_state` reconstructs the exact
    shape.  ``trainer_state`` (optional: optimizer/LoRA local state) is any
    pytree — flattened through the wire skeleton so the arrays stay named
    and the structure rides the JSON side."""
    from ..comm import wire

    proto: dict = {
        "kind": "client",
        "rank": int(rank),
        "round_idx": None if round_idx is None else int(round_idx),
        "session_epoch": None if session_epoch is None else int(session_epoch),
        "rounds_trained": int(rounds_trained),
        "server_restarts_seen": int(server_restarts_seen),
        "upload_attempts": {str(k): int(v) for k, v in upload_attempts.items()},
    }
    arrays: dict = {}
    if residuals is not None:
        idx = [i for i, r in enumerate(residuals) if r is not None]
        proto["residual_len"] = len(residuals)
        proto["residual_idx"] = idx
        for i in idx:
            arrays[f"resid_{i}"] = np.asarray(residuals[i])
    if trainer_state is not None:
        skel, leaves = wire.flatten_with_skeleton(trainer_state)
        proto["trainer_skel"] = skel
        for i, leaf in enumerate(leaves):
            arrays[f"local_{i}"] = np.asarray(leaf)
    return proto, arrays


def unpack_client_state(snap: dict) -> dict:
    """Inverse of :func:`pack_client_state` over a journal snapshot dict."""
    from ..comm import wire

    proto, arrays = snap["protocol"], snap["arrays"]
    residuals = None
    if proto.get("residual_len") is not None:
        residuals = [None] * int(proto["residual_len"])
        for i in proto.get("residual_idx") or []:
            residuals[int(i)] = np.asarray(arrays[f"resid_{int(i)}"])
    trainer_state = None
    if proto.get("trainer_skel") is not None:
        n = len([k for k in arrays if k.startswith("local_")])
        leaves = [arrays[f"local_{i}"] for i in range(n)]
        trainer_state = wire.restore_skeleton(proto["trainer_skel"], leaves)
    return {
        "round_idx": proto.get("round_idx"),
        "session_epoch": proto.get("session_epoch"),
        "rounds_trained": int(proto.get("rounds_trained", 0)),
        "server_restarts_seen": int(proto.get("server_restarts_seen", 0)),
        "upload_attempts": {str(k): int(v) for k, v in
                            (proto.get("upload_attempts") or {}).items()},
        "residuals": residuals,
        "trainer_state": trainer_state,
    }


def prune_retired_client_dirs(root: str, live_ranks, keep: int = 8) -> list[int]:
    """Reclaim per-rank journal directories of long-RETIRED clients
    (ISSUE 14 satellite: before this, ``client_journal_dir`` grew one
    ``client_<rank>`` directory per rank ever seen and nothing ever deleted
    them — a fleet that cycles through ephemeral ranks leaks disk forever).

    A rank is *retired* when it is not in ``live_ranks``; the newest
    ``keep`` retired directories (by most recent journal-step mtime, so a
    recently crashed-but-replaceable client keeps its resume state) are
    kept and every older one is removed.  Live ranks are NEVER touched,
    whatever ``keep`` says.  Returns the pruned rank list."""
    import re
    import shutil

    live = {int(r) for r in live_ranks}
    retired: list[tuple[float, int, str]] = []
    try:
        names = os.listdir(str(root))
    except OSError:
        return []
    for name in names:
        m = re.fullmatch(r"client_(\d+)", name)
        if not m or int(m.group(1)) in live:
            continue
        path = os.path.join(str(root), name)
        try:
            mtimes = [os.path.getmtime(os.path.join(path, f))
                      for f in os.listdir(path)] or [os.path.getmtime(path)]
        except OSError:
            continue
        retired.append((max(mtimes), int(m.group(1)), path))
    retired.sort(reverse=True)  # newest first
    pruned: list[int] = []
    for _mtime, rank, path in retired[max(0, int(keep)):]:
        try:
            shutil.rmtree(path)
            pruned.append(rank)
        except OSError as e:
            log.warning("client journal: could not prune retired rank %d "
                        "(%s)", rank, e)
    if pruned:
        log.info("client journal: pruned %d retired rank dir(s) under %s",
                 len(pruned), root)
    return pruned


def client_journal_from_config(cfg: Any, rank: int) -> Optional[ClientJournal]:
    """The one gate: ``extra.client_journal_dir`` unset/falsy → ``None``
    (no journal object, no key header, wire byte-identical)."""
    if cfg is None or not cfg_extra(cfg, "client_journal_dir"):
        return None
    root = cfg_extra(cfg, "client_journal_dir")
    keep = int(cfg_extra(cfg, "client_journal_keep"))
    try:
        return ClientJournal(str(root), rank, keep=keep)
    except OSError as e:
        log.warning("client journal: directory %s unusable (%s) — running "
                    "without crash recovery", root, e)
        return None
