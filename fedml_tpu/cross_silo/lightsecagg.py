"""LightSecAgg cross-silo protocol — masked aggregation over the wire.

Parity with ``cross_silo/lightsecagg/lsa_fedml_server_manager.py:15`` /
``lsa_fedml_client_manager.py:21`` / ``lsa_fedml_aggregator.py:19`` (~1.7k
LoC in the reference).  The message flow (reference ``lsa_message_define.py``
docstring) is:

    INIT(global)                                  server -> all clients
    ENCODED_MASK share for peer j                 client i -> server -> j
    --- all N shares held: client trains ---
    masked model  (field vector + z_i)            client -> server
    ACTIVE_CLIENTS(first-round survivors)         server -> survivors
    aggregate encoded mask over survivors         client -> server
    --- >= U aggregates held: server decodes sum-of-masks, unmasks ---
    SYNC(new global)                              server -> clients

The server only ever sees ``quantize(x_i) + z_i  (mod p)`` — individual
updates never appear unmasked; the sum of masks is reconstructed in ONE shot
from any U survivors' Lagrange-coded aggregates (``trust/secagg/lightsecagg``,
the math mirror of reference ``core/mpc/lightsecagg.py``).

Design notes (TPU-world divergences, all documented):
- Message-type integers extend this repo's ``message_define`` numbering
  (10-13) instead of reusing the reference's overlapping LSA numbering —
  one flat protocol namespace so a single comm manager can serve both.
- The reference averages uniformly (``lsa_fedml_aggregator.py:164``:
  ``w = 1/len(active_clients)``) because sample-weighted sums would leak
  weights; we keep that semantic.
- Masks are drawn fresh per round from the client's seeded field RNG; the
  Lagrange encode/decode is int64 modular matmul (exact, no MXU needed —
  bandwidth-bound host math, SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import logging
import math
import os
import threading
from typing import Optional

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from ..comm.message import Message
from ..core.flags import cfg_extra
from ..trust.secagg.field import DEFAULT_PRIME, dequantize_from_field, quantize_to_field
from ..trust.secagg.lightsecagg import LightSecAggProtocol
from ..trust.secagg.stream import DENSE_RING_BITS, pack_ring, unpack_ring
from . import message_define as md
from .client import ClientMasterManager, FedMLTrainer
from .server import FedMLAggregator, FedMLServerManager

log = logging.getLogger("fedml_tpu.cross_silo.lightsecagg")

# protocol constants — extend the flat cross-silo namespace (0-8 in
# message_define.py); reference uses its own overlapping numbering
MSG_TYPE_C2S_SEND_ENCODED_MASK = 10   # ref MSG_TYPE_C2S_SEND_ENCODED_MASK_TO_SERVER = 5
MSG_TYPE_S2C_ENCODED_MASK = 11        # ref MSG_TYPE_S2C_ENCODED_MASK_TO_CLIENT = 2
MSG_TYPE_S2C_ACTIVE_CLIENTS = 12      # ref MSG_TYPE_S2C_SEND_TO_ACTIVE_CLIENT = 4
MSG_TYPE_C2S_SEND_AGG_MASK = 13       # ref MSG_TYPE_C2S_SEND_MASK_TO_SERVER = 7

MSG_ARG_KEY_ENCODED_MASK = "encoded_mask"
MSG_ARG_KEY_AGG_ENCODED_MASK = "aggregate_encoded_mask"
MSG_ARG_KEY_MASK_SOURCE = "client_id"
MSG_ARG_KEY_ACTIVE_CLIENTS = "active_clients"
#: control-plane descriptor of a ring-packed masked upload (ISSUE 17
#: satellite): ``{"ring_bits", "length"}``.  M31 field elements fit 31 bits,
#: so the wire carries little-endian u32 (4 B/elem) instead of the int64
#: tensor codec's 8 B/elem — absent meta means a legacy raw int64 upload,
#: which the server still accepts bit-identically.
MSG_ARG_KEY_MASKED_RING = "masked_ring"


def secagg_params(cfg):
    """(T, U, q_bits) from config — defaults follow the reference
    (``lsa_fedml_aggregator.py:60``: T = floor(N/2); U = T + 1 is the
    minimum reconstruction threshold)."""
    n = cfg.client_num_in_total
    t = int(cfg_extra(cfg, "secagg_privacy_t", max(1, n // 2)))
    u = int(cfg_extra(cfg, "secagg_target_u", t + 1))
    q_bits = int(cfg_extra(cfg, "secagg_q_bits"))
    if not (0 < t < u <= n):
        raise ValueError(f"LightSecAgg needs 0 < T({t}) < U({u}) <= N({n})")
    # trust features that inspect or transform individual updates cannot run
    # on masked field vectors — refuse loudly instead of silently no-opping
    # (the contract stated in runner._check_unimplemented_flags)
    incompatible = [
        f for f in ("enable_attack", "enable_defense", "enable_dp", "enable_contribution", "enable_fhe")
        if getattr(cfg, f, False)
    ]
    if incompatible:
        raise NotImplementedError(
            f"trust features {incompatible} operate on individual client "
            "updates, which LightSecAgg hides from the server by design; "
            "disable them or disable enable_secagg"
        )
    if getattr(cfg, "federated_optimizer", "FedAvg") not in ("FedAvg", "fedavg", "FedAvg_seq"):
        raise NotImplementedError(
            "LightSecAgg reconstruction yields only the uniform mean of the "
            "survivors' updates (reference lsa_fedml_aggregator.py:164); "
            f"server optimizer {cfg.federated_optimizer!r} needs per-client "
            "updates — use FedAvg with enable_secagg"
        )
    from ..fl.algorithm import config_supports_associative_fold

    if not config_supports_associative_fold(cfg):
        # the masked field total is an associative fold — same protocol gate
        # as the f32 streaming accumulator (fl/algorithm.py, ISSUE 15)
        raise NotImplementedError(
            "LightSecAgg's masked sum is a weight-associative fold; the "
            "configured algorithm overrides aggregate() and does not "
            "declare supports_associative_fold"
        )
    return t, u, q_bits


class LSAAggregator(FedMLAggregator):
    """Server-side LightSecAgg state: masked field vectors instead of
    plaintext models; reconstruction replaces plaintext aggregation."""

    def __init__(self, cfg, model, sample_x, test_arrays, trust=None):
        super().__init__(cfg, model, sample_x, test_arrays, trust=trust)
        # masked field vectors are not foldable f32 trees: the associative
        # streaming path must NEVER engage here, whatever the comm flags say
        self.stream_mode = False
        self._shard_fold = False
        t, u, self.q_bits = secagg_params(cfg)
        self.protocol = LightSecAggProtocol(cfg.client_num_in_total, t, u)
        flat, self._unravel = jax.flatten_util.ravel_pytree(self.global_vars)
        self.model_dim = int(flat.size)
        self.d_pad = self.protocol.pad_len(self.model_dim)
        self.agg_mask_dict: dict[int, np.ndarray] = {}
        # streaming masked folds (ISSUE 15, extra.secagg_stream): the masked
        # model vectors — the O(cohort * d) half of the server state — fold
        # one at a time into a field total; only the aggregate encoded masks
        # (the protocol's decode inputs, U vectors of d/(U-T)) stay buffered.
        # Flag unset -> the historical buffer-all path, bit-identical.
        self.field_stream = bool(cfg_extra(cfg, "secagg_stream"))
        self._facc = None
        self._facc_folded = 0

    def add_local_trained_result(self, client_idx: int, masked_vec, sample_num: float) -> None:
        vec = np.asarray(masked_vec, dtype=np.int64)
        if vec.shape != (self.d_pad,):
            raise ValueError(f"masked vector shape {vec.shape} != ({self.d_pad},)")
        if self.field_stream:
            from ..parallel.stream_fold import FieldStreamAccumulator

            if self._facc is None:
                self._facc = FieldStreamAccumulator(
                    [np.zeros(self.d_pad, np.int64)], self.protocol.p)
            # buffered right now: the running total (once anything folded)
            # plus this in-flight vector — the <= 2 acceptance bound
            self.peak_buffered_updates = max(
                self.peak_buffered_updates, (1 if self._facc_folded else 0) + 1)
            self._facc.fold_leaf(0, vec)
            self._facc_folded += 1
            self.sample_num_dict[client_idx] = sample_num
            self.flag_client_model_uploaded[client_idx] = True
            return
        super().add_local_trained_result(client_idx, vec, sample_num)

    def survivor_ids(self) -> list[int]:
        """Clients whose masked vector is in this round's sum — maintained
        by both the buffer-all and streaming paths."""
        return sorted(self.flag_client_model_uploaded)

    def add_aggregate_encoded_mask(self, client_idx: int, agg_mask) -> None:
        self.agg_mask_dict[client_idx] = np.asarray(agg_mask, dtype=np.int64)

    def mask_count(self) -> int:
        return len(self.agg_mask_dict)

    def aggregate(self, round_idx: int):
        """Reference ``aggregate_model_reconstruction`` (:132): field-sum the
        survivors' masked vectors, decode the sum of their masks from the
        aggregate encoded masks, subtract, dequantize, uniform-average.

        Under ``extra.secagg_stream`` the field sum already happened fold by
        fold as uploads arrived; mod-field exactness makes the streamed
        total BITWISE the buffer-all total."""
        active = self.survivor_ids()
        p = self.protocol.p
        if self._facc is not None:
            total = self._facc.host_sums()[0]
        else:
            total = np.zeros(self.d_pad, dtype=np.int64)
            for i in active:
                total = (total + self.model_dict[i]) % p
        # aggregate encoded masks are indexed by 0-based client index
        agg_shares = {cid - 1: v for cid, v in self.agg_mask_dict.items()}
        mask_sum = self.protocol.decode_aggregate_mask(agg_shares, self.d_pad)
        unmasked = (total - mask_sum) % p
        avg = dequantize_from_field(unmasked[: self.model_dim], len(active), bits=self.q_bits)
        avg = avg / max(len(active), 1)
        self.global_vars = self._unravel(jnp.asarray(avg, jnp.float32))
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self.flag_client_model_uploaded.clear()
        self.agg_mask_dict.clear()
        self._facc = None
        self._facc_folded = 0
        return self.global_vars


class LSAServerManager(FedMLServerManager):
    """Reference ``LightSecAggServerManager``: relays encoded-mask shares,
    collects masked models, asks first-round survivors for aggregate masks,
    reconstructs when >= U arrive."""

    def __init__(self, cfg, aggregator: LSAAggregator, backend: Optional[str] = None, logger=None):
        super().__init__(cfg, aggregator, backend=backend, logger=logger)
        if self.per_round != len(self.client_ids):
            raise ValueError(
                "LightSecAgg requires full participation per round "
                f"(client_num_per_round={self.per_round} != N={len(self.client_ids)}); "
                "the mask-share topology is over all N clients"
            )
        self.active_first: list[int] = []
        self._phase = "model"  # model -> mask

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(MSG_TYPE_C2S_SEND_ENCODED_MASK, self.handle_message_encoded_mask)
        self.register_message_receive_handler(MSG_TYPE_C2S_SEND_AGG_MASK, self.handle_message_agg_mask)

    def handle_message_encoded_mask(self, msg: Message) -> None:
        """Relay a mask share from its source client to its destination
        (reference ``handle_message_receive_encoded_mask_from_client`` :131)."""
        dest = int(msg.get(md.MSG_ARG_KEY_CLIENT_INDEX))
        relay = Message(MSG_TYPE_S2C_ENCODED_MASK, 0, dest)
        relay.add_params(MSG_ARG_KEY_ENCODED_MASK, msg.get(MSG_ARG_KEY_ENCODED_MASK))
        relay.add_params(MSG_ARG_KEY_MASK_SOURCE, msg.get_sender_id())
        relay.add_params(md.MSG_ARG_KEY_ROUND_INDEX, msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        self.send_message(relay)

    def handle_message_receive_model(self, msg: Message) -> None:
        with self._agg_lock:
            if msg.get(md.MSG_ARG_KEY_ROUND_INDEX) != self.round_idx or self._phase != "model":
                return
            vec = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
            meta = msg.get_control(MSG_ARG_KEY_MASKED_RING)
            if meta is not None:
                # ring-packed wire (u32): exact inverse of the client's
                # pack_ring; no meta -> legacy raw int64, accepted as before
                vec = unpack_ring(np.asarray(vec), int(meta["ring_bits"]),
                                  int(meta["length"]))
            self.aggregator.add_local_trained_result(
                msg.get_sender_id(), vec,
                float(msg.get(md.MSG_ARG_KEY_NUM_SAMPLES)),
            )
            if self.aggregator.check_whether_all_receive(len(self.selected)):
                self._request_aggregate_masks()

    def _request_aggregate_masks(self) -> None:
        """All (or quorum of) masked models in: freeze the first-round active
        set and ask those survivors for their aggregate encoded masks
        (reference ``send_message_to_active_client`` :277). Caller holds
        _agg_lock."""
        self._runtime.cancel(self, "straggler")
        self._phase = "mask"
        self.active_first = self.aggregator.survivor_ids()
        for cid in self.active_first:
            msg = Message(MSG_TYPE_S2C_ACTIVE_CLIENTS, 0, cid)
            msg.add_params(MSG_ARG_KEY_ACTIVE_CLIENTS, [int(c) for c in self.active_first])
            msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(msg)
        self._arm_straggler_timer()

    def handle_message_agg_mask(self, msg: Message) -> None:
        with self._agg_lock:
            if msg.get(md.MSG_ARG_KEY_ROUND_INDEX) != self.round_idx or self._phase != "mask":
                return
            self.aggregator.add_aggregate_encoded_mask(
                msg.get_sender_id(), msg.get(MSG_ARG_KEY_AGG_ENCODED_MASK)
            )
            if self.aggregator.mask_count() >= len(self.active_first):
                self._phase = "model"
                self._finish_round()

    def _on_straggler_timeout(self) -> None:
        """Bounded-wait in both phases: model phase advances with a quorum of
        masked models; mask phase reconstructs as soon as >= U aggregates
        arrived (U is the hard decode threshold)."""
        with self._agg_lock:
            if self._phase == "model":
                need = max(
                    self.aggregator.protocol.u,
                    int(math.ceil(self.quorum_frac * len(self.selected))),
                )
                if self.aggregator.received_count() >= need:
                    log.warning(
                        "round %d: straggler timeout, proceeding with %d/%d masked models",
                        self.round_idx, self.aggregator.received_count(), len(self.selected),
                    )
                    self._request_aggregate_masks()
                    return
            else:
                if self.aggregator.mask_count() >= self.aggregator.protocol.u:
                    log.warning(
                        "round %d: mask-phase timeout, decoding from %d/%d aggregates",
                        self.round_idx, self.aggregator.mask_count(), len(self.active_first),
                    )
                    self._phase = "model"
                    self._finish_round()
                    return
            self._arm_straggler_timer()


class LSAClientManager(ClientMasterManager):
    """Reference ``LightSecAggClientManager``: offline mask exchange, then
    train, then upload ``quantize(x) + z (mod p)``."""

    def __init__(self, cfg, trainer: FedMLTrainer, rank: int, backend: Optional[str] = None):
        super().__init__(cfg, trainer, rank=rank, backend=backend)
        t, u, self.q_bits = secagg_params(cfg)
        self.n = cfg.client_num_in_total
        # Masks MUST come from OS entropy, never from the shared run config:
        # a seed derivable from cfg lets the server replay the RNG stream and
        # unmask individual updates, defeating the protocol.  256 bits so the
        # seed space cannot be brute-forced (a 32-bit seed would be
        # enumerable: regenerate z, subtract, keep the candidate that looks
        # like a model update).  The masks cancel exactly in the aggregate,
        # so non-determinism never affects results.
        self.protocol = LightSecAggProtocol(
            self.n, t, u, seed=int.from_bytes(os.urandom(32), "little")
        )
        self.encoded_mask_dict: dict[int, np.ndarray] = {}
        self._early_shares: dict[tuple[int, int], np.ndarray] = {}  # (round, src)
        self._share_round = -1
        self._mask: Optional[np.ndarray] = None
        self._pending_msg: Optional[Message] = None
        self._lock = threading.Lock()

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(MSG_TYPE_S2C_ENCODED_MASK, self.handle_message_encoded_mask)
        self.register_message_receive_handler(MSG_TYPE_S2C_ACTIVE_CLIENTS, self.handle_message_active_clients)

    # -- phase 1: offline mask exchange --------------------------------------
    def _train_and_send(self, msg: Message) -> None:
        """INIT/SYNC received: instead of training immediately (plaintext
        path), enter the offline phase — draw z_i, Lagrange-encode, ship one
        share per peer through the server (reference ``__offline`` :215)."""
        round_idx = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        with self._lock:
            self._pending_msg = msg
            self._share_round = round_idx
            self.encoded_mask_dict.clear()
            # adopt shares that raced ahead of this INIT/SYNC (possible under
            # reordering transports like MQTT); purge stale past-round shares
            # so straggler-heavy long runs don't leak buffered vectors
            for (r, src), v in list(self._early_shares.items()):
                if r == round_idx:
                    self.encoded_mask_dict[src] = v
                    del self._early_shares[(r, src)]
                elif r < round_idx:
                    del self._early_shares[(r, src)]
            params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
            flat, _ = jax.flatten_util.ravel_pytree(params)
            self._mask = self.protocol.gen_mask(int(flat.size))
            encoded = self.protocol.encode_mask(self._mask)  # (N, s) row j -> peer j+1
        for j in range(1, self.n + 1):
            share = Message(MSG_TYPE_C2S_SEND_ENCODED_MASK, self.rank, 0)
            share.add_params(md.MSG_ARG_KEY_CLIENT_INDEX, j)  # destination rank
            share.add_params(MSG_ARG_KEY_ENCODED_MASK, encoded[j - 1])
            share.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            self.send_message(share)

    def handle_message_encoded_mask(self, msg: Message) -> None:
        with self._lock:
            src = int(msg.get(MSG_ARG_KEY_MASK_SOURCE))
            share = np.asarray(msg.get(MSG_ARG_KEY_ENCODED_MASK), dtype=np.int64)
            r = msg.get(md.MSG_ARG_KEY_ROUND_INDEX)
            if r is not None and int(r) != self._share_round:
                self._early_shares[(int(r), src)] = share
                return
            self.encoded_mask_dict[src] = share
            ready = len(self.encoded_mask_dict) == self.n and self._pending_msg is not None
        if ready:
            self._train_masked()

    # -- phase 2: train + masked upload --------------------------------------
    def _train_masked(self) -> None:
        with self._lock:
            msg = self._pending_msg
            self._pending_msg = None
            mask = self._mask
        if msg is None:
            return
        round_idx = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
        client_idx = int(msg.get(md.MSG_ARG_KEY_CLIENT_INDEX, self.rank - 1))
        new_vars, n_samples = self.trainer.train(params, round_idx, self.seed_key, client_idx)
        self.rounds_trained += 1
        flat, _ = jax.flatten_util.ravel_pytree(new_vars)
        field_vec = quantize_to_field(np.asarray(flat), bits=self.q_bits)
        padded = np.zeros(self.protocol.pad_len(flat.size), dtype=np.int64)
        padded[: flat.size] = field_vec
        masked = (padded + mask) % self.protocol.p
        reply = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        # halve the masked-upload wire: field elements < 2^31 ride as u32
        # (trust/secagg/stream.pack_ring), declared in control meta so the
        # server can tell packed from legacy int64; unpack is exact, so the
        # protocol math downstream is BITWISE the unpacked wire's
        reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS,
                         pack_ring(masked, DENSE_RING_BITS))
        reply.add_params(MSG_ARG_KEY_MASKED_RING,
                         {"ring_bits": DENSE_RING_BITS,
                          "length": int(masked.size)})
        reply.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, n_samples)
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        self.send_message(reply)

    # -- phase 3: one-shot aggregate mask ------------------------------------
    def handle_message_active_clients(self, msg: Message) -> None:
        """Sum the held encoded sub-masks of the surviving sources and send
        ONE aggregate (reference ``handle_message_receive_active_from_server``
        :132)."""
        active = [int(c) for c in msg.get(MSG_ARG_KEY_ACTIVE_CLIENTS)]
        with self._lock:
            shares = [self.encoded_mask_dict[c] for c in active if c in self.encoded_mask_dict]
        if len(shares) != len(active):
            log.warning("client %d missing shares for active set %s", self.rank, active)
            return
        agg = LightSecAggProtocol.aggregate_encoded_masks(shares)
        reply = Message(MSG_TYPE_C2S_SEND_AGG_MASK, self.rank, 0)
        reply.add_params(MSG_ARG_KEY_AGG_ENCODED_MASK, agg)
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX)))
        self.send_message(reply)


# -- builders (mirror cross_silo/__init__ plaintext builders) ----------------

def build_lsa_server(cfg, dataset, model, backend: Optional[str] = None) -> LSAServerManager:
    from ..data.dataset import pad_eval_set

    eval_bs = min(256, max(32, cfg.test_batch_size))
    test_arrays = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
    aggregator = LSAAggregator(cfg, model, dataset.train_x[: cfg.batch_size], test_arrays)
    return LSAServerManager(cfg, aggregator, backend=backend)


def build_lsa_client(cfg, dataset, model, rank: int, backend: Optional[str] = None) -> LSAClientManager:
    ix = dataset.client_idx[rank - 1]
    trainer = FedMLTrainer(cfg, model, dataset.train_x[ix], dataset.train_y[ix])
    return LSAClientManager(cfg, trainer, rank=rank, backend=backend)


def run_lightsecagg_process_group(cfg, dataset, model, backend: str = "INPROC",
                                  timeout: float = 600.0, drop_ranks: frozenset = frozenset()):
    """1 server + N LSA clients on threads over the in-proc fabric.
    ``drop_ranks`` simulates first-round dropouts: those clients complete the
    mask exchange but never upload a model (the hard dropout case — their
    masks are IN the other clients' share tables but their data is not in the
    sum)."""
    from ..comm.inproc import InProcRouter

    InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
    clients = []
    for r in range(1, cfg.client_num_in_total + 1):
        c = build_lsa_client(cfg, dataset, model, rank=r, backend=backend)
        if r in drop_ranks:
            c._train_masked = lambda: None  # drops out before model upload
        clients.append(c)
    for c in clients:
        c.run_in_thread()
    server = build_lsa_server(cfg, dataset, model, backend=backend)
    try:
        history = server.run_until_done(timeout=timeout)
    finally:
        for c in clients:
            c.finish()
    return history, server
