"""Cross-silo FL client (silo master process).

Parity with ``cross_silo/client/fedml_client_master_manager.py:22`` +
``fedml_trainer.py:8``: handles check-status/init/sync messages, trains the
local shard with the shared jitted local-SGD scan, uploads weights + sample
count, honors the finish protocol.

Intra-silo data parallelism (the reference's DDP-over-torchrun,
``fedml_trainer_dist_adapter.py``) maps to a local JAX ``data`` mesh axis: a
silo with k local chips batch-shards its local SGD — no process group or
broadcast_object_list needed, GSPMD inserts the gradient all-reduce.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms import hparams_from_config
from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from ..core import rng
from ..core.flags import cfg_extra
from ..fl.local_sgd import make_local_train_fn
from . import message_define as md

log = logging.getLogger("fedml_tpu.cross_silo.client")

# XLA executes a k-device collective program with k participant threads that
# must ALL reach a rendezvous; dispatching two such programs concurrently
# from different client threads on one host (the in-process cross-silo
# harness runs N silo masters as threads) can starve the shared device
# threadpool and deadlock — observed as >=120s AllReduce rendezvous stalls
# on XLA:CPU.  One host owns one device set anyway, so multi-device local
# training is serialized within the process; single-device trainers
# (dp_active=False) are unaffected.
_DP_TRAIN_LOCK = threading.Lock()

#: reconnect/resume handshake (ISSUE 10): a send that fails because the
#: server is mid-restart retries with capped exponential backoff +
#: deterministic jitter (comm.base.backoff_delay) before the upload is
#: abandoned to the server's redispatch watchdog
RECONNECT_TRIES = 5
RECONNECT_BASE_S = 0.05
RECONNECT_CAP_S = 2.0


def _leaf_delta(new, old):
    """new - old per leaf; float math runs in f32 then casts back (exact for
    f32 params), integer leaves subtract natively so the server's add-back
    reconstructs them exactly."""
    a, b = np.asarray(new), np.asarray(old)
    if a.dtype.kind in "fc":
        return (a.astype(np.float32) - b.astype(np.float32)).astype(a.dtype)
    return a - b


def data_parallel_constraint(mesh):
    """Sharding-constrain each training minibatch over ``mesh``'s data axis.
    The batch dim is what partitions the compute; at-rest array sharding
    alone gets undone by the random-index batch gather (verified via HLO in
    the tests).  Shared by the local-silo and distributed-silo trainers."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel import mesh as meshlib

    def batch_constraint(bx, by):
        cx = jax.lax.with_sharding_constraint(
            bx, NamedSharding(mesh, P(meshlib.AXIS_DATA, *([None] * (bx.ndim - 1)))))
        cy = jax.lax.with_sharding_constraint(
            by, NamedSharding(mesh, P(meshlib.AXIS_DATA, *([None] * (by.ndim - 1)))))
        return cx, cy

    return batch_constraint


class FedMLTrainer:
    """Local training operator (reference ``FedMLTrainer.train`` :71).

    Intra-silo data parallelism (the reference's torchrun-DDP-in-silo,
    ``fedml_trainer_dist_adapter.py``): when the silo host has multiple
    accelerators, each training step's minibatch is sharding-constrained
    over a silo-local ``data`` mesh axis INSIDE the jitted program, so GSPMD
    partitions the fwd/bwd compute per device and inserts the gradient
    all-reduce that DDP does with NCCL hooks.  (Sharding only the at-rest
    arrays would be undone by the random-index batch gather — verified via
    HLO in the tests.)  Numerics are identical to the single-device run.
    Requires batch_size divisible by the local device count; refused loudly
    otherwise.  Disable with ``cfg.extra['silo_dp'] = False``.
    """

    def __init__(self, cfg, model, x: np.ndarray, y: np.ndarray):
        cap = ((x.shape[0] + cfg.batch_size - 1) // cfg.batch_size) * cfg.batch_size
        reps = np.resize(np.arange(x.shape[0]), cap)
        self.x = jnp.asarray(x[reps])
        self.y = jnp.asarray(y[reps])
        self.count = jnp.int32(x.shape[0])
        spe = max(1, math.ceil(cap / cfg.batch_size))
        self.hp = hparams_from_config(cfg, steps_per_epoch=spe)
        self.dp_active = False
        self._train_fn = make_local_train_fn(
            model, self.hp, batch_constraint=self._batch_constraint(cfg)
        )
        self._train = jax.jit(self._train_fn)
        # client-side AOT export (extra.aot_programs): a restarted silo
        # deserializes its local-train program instead of re-tracing the
        # scanned local-SGD loop (the server side has been stored since PR 7;
        # this closes the carried client-side follow-on).  Bound lazily at
        # the first train() call, where the real argument shapes exist.
        from ..core import aot as aotlib

        self._aot = aotlib.store_from_config(cfg)
        self._aot_cfg_sig = aotlib.config_signature(cfg) if self._aot is not None else None
        self._aot_bound = False

    def _batch_constraint(self, cfg):
        """Minibatch sharding constraint for this silo's device set; the
        distributed-silo subclass overrides this with the global mesh."""
        n_local = len(jax.local_devices())
        if n_local > 1 and bool(cfg_extra(cfg, "silo_dp")):
            if cfg.batch_size % n_local == 0:
                from ..parallel import mesh as meshlib

                self.dp_active = True
                return data_parallel_constraint(
                    meshlib.make_mesh((meshlib.AXIS_DATA,), (n_local,), jax.local_devices())
                )
            log.warning(
                "silo_dp requested but batch_size %d is not divisible by "
                "the %d local devices — intra-silo data parallelism is "
                "DISABLED for this silo (make batch_size a multiple of "
                "the device count to enable it)",
                cfg.batch_size, n_local,
            )
        return None

    def train(self, global_vars, round_idx: int, seed_key, client_idx: int = 0) -> tuple:
        # per-client RNG stream keyed by the server-assigned client index —
        # matches the simulator's client_key(round_key(k, r), i) derivation so
        # cross-silo and simulation runs share sampling/dropout streams
        key = rng.client_key(rng.round_key(seed_key, round_idx), client_idx)
        variables = jax.tree_util.tree_map(jnp.asarray, global_vars)
        if self._aot is not None and not self._aot_bound:
            self._aot_bound = True
            from ..core import aot as aotlib

            args = (variables, self.x, self.y, self.count, key, None)
            self._train = self._aot.cached_jit(
                self._train_fn, args,
                key=aotlib.program_key(
                    "cross_silo.client_train",
                    trees={"args": args}, hparams=self.hp,
                    config=self._aot_cfg_sig,
                    extra={"dp_active": self.dp_active}),
            )
        with _DP_TRAIN_LOCK if self.dp_active else contextlib.nullcontext():
            new_vars, metrics = self._train(variables, self.x, self.y, self.count, key, None)
            new_vars = jax.device_get(new_vars)
        return new_vars, float(self.count)


class ClientMasterManager(FedMLCommManager):
    def __init__(self, cfg, trainer: FedMLTrainer, rank: int, backend: Optional[str] = None):
        super().__init__(cfg, rank=rank, size=cfg.client_num_in_total + 1, backend=backend)
        self.trainer = trainer
        self.seed_key = rng.root_key(cfg.random_seed)
        self.done = threading.Event()
        self.rounds_trained = 0
        # reconnect/resume bookkeeping (ISSUE 10): the server's session epoch
        # rides every dispatch when its recovery journal is on; an epoch bump
        # means the server restarted — count it, echo the DISPATCH's epoch in
        # the reply (acceptance is about which dispatch produced the work)
        self._last_epoch: Optional[int] = None
        self.server_restarts_seen = 0
        # crash-recovery journal (ISSUE 13, extra.client_journal_dir): the
        # client snapshots its protocol state (EF residuals, last version +
        # epoch, upload attempts, optional trainer local state) BEFORE every
        # upload and resumes from it on restart; uploads then carry the
        # idempotence key the servers dedup on.  None = off, wire unchanged.
        from .client_journal import client_journal_from_config

        self.client_journal = client_journal_from_config(cfg, rank)
        self.resumed_from_journal = False
        #: "<round>:<epoch>" -> attempts sent so far (bounded, journaled)
        self._upload_attempts: dict[str, int] = {}
        #: crash-simulation latch (the soak harnesses' in-process SIGKILL):
        #: once set, this client makes no further sends or journal writes
        self._killed = False
        # compressed uploads (extra.comm_compression: qsgd8 | topk): the
        # reply carries the DELTA vs the received global model, compressed
        # per-leaf on the wire-v2 format; the top-k error-feedback residual
        # is trainer-side state carried across rounds.  None = off, and the
        # send path below is byte-identical to the uncompressed protocol.
        from ..comm import codecs

        self.comm_codec = codecs.codec_from_config(cfg)
        self._comm_residuals = None
        # hierarchical aggregation tree (cross_silo/edge.py): model replies
        # go to this client's edge aggregator instead of the root.  Status
        # probes, FINISH, and telemetry stay root<->client direct — only the
        # model-upload hop is re-routed.  Flat topology -> 0, byte-identical.
        from .edge import build_topology

        _topo = build_topology(cfg)
        self._upload_dest = 0 if _topo is None else _topo.parent(rank)
        self._comm_ratio = float(cfg_extra(
            cfg, "comm_topk_ratio", getattr(cfg, "compression_ratio", 0.01) or 0.01))
        # compression floor resolution: an EXPLICIT comm_compress_min_size
        # flag wins; otherwise a trainer that knows its exchanged tree is
        # small (LoRA adapters: rank-r factors) may declare a per-tree
        # comm_compress_min_elems override; otherwise the model-scale default
        min_elems = cfg_extra(cfg, "comm_compress_min_size", None)
        if min_elems is None:
            min_elems = getattr(trainer, "comm_compress_min_elems", None)
        self._comm_min_elems = int(
            min_elems if min_elems is not None else codecs.DEFAULT_MIN_COMPRESS_ELEMS)
        # flight recorder (ISSUE 16, extra.flight_recorder): the client's
        # own black box — train/upload/journal/epoch events, dumped on
        # hard_kill / finish so the postmortem can pair every upload key it
        # sent against the server's fold/dedup/stale ledger.  The comm-event
        # tap stays off here: in-process harnesses run many clients per
        # process and the process-wide sink would cross-pollinate rings
        # (the server and fleet recorders own comm events).
        from ..obs import flight as obsflight

        self.flight = obsflight.recorder_from_config(
            cfg, name=f"client_r{rank}", meta={"role": "client", "rank": rank})
        # resume mid-conversation: restore residuals/epoch/attempts from the
        # newest intact journal snapshot (after the codec state above exists)
        if self.client_journal is not None:
            self._client_journal_recover()
        # remote observability: per-round events (+ anything the caller
        # ships via self.obs — perf samples, RuntimeLogDaemon batches) ride
        # the FL transport to the server's ObsCollector.  The train events
        # wrap trainer.train itself (not one subclass handler) so SecAgg/FHE
        # client managers — which override the train-and-send path — ship
        # the same telemetry.
        self.obs = None
        self._pallas_sink = None
        if cfg_extra(cfg, "enable_remote_obs"):
            from ..obs import trace as obstrace
            from ..obs.remote import RemoteObsShipper
            from ..ops.pallas import timing as pallas_timing

            self.obs = RemoteObsShipper(self.send_message, rank)

            # eager Pallas kernel timings (quantize round trips etc.) ride
            # the same trail, so `fedml-tpu obs report` can summarize them
            # next to the round phases
            def _pallas_sink(kernel, seconds, _obs=self.obs, _rank=rank):
                _obs.metric({"metric": "pallas_kernel_seconds",
                             "kernel": kernel, "value": seconds, "rank": _rank})

            self._pallas_sink = pallas_timing.add_sink(_pallas_sink)
            inner_train = self.trainer.train

            def train_with_obs(global_vars, round_idx, seed_key, client_idx=0):
                self.obs.event("train", "started", round_idx=int(round_idx),
                               client_idx=int(client_idx))
                # the span parents to the ambient context the comm layer
                # activated from the server's message trace header, so this
                # train span and the server's aggregate span share one
                # round-scoped trace_id
                with obstrace.traced("train", round_idx=int(round_idx),
                                     client_idx=int(client_idx),
                                     rank=rank) as span:
                    out = inner_train(global_vars, round_idx, seed_key, client_idx)
                self.obs.span(span, num_samples=float(out[1]))
                self.obs.event("train", "ended", round_idx=int(round_idx),
                               client_idx=int(client_idx),
                               num_samples=float(out[1]))
                # ship now: round telemetry is only useful while the round is
                # in flight, and the final interval flush can race teardown
                self.obs.flush()
                return out

            self.trainer.train = train_with_obs

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.handle_message_check_status)
        self.register_message_receive_handler(md.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_message_receive_model)
        self.register_message_receive_handler(md.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def handle_message_check_status(self, msg: Message) -> None:
        reply = Message(md.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        reply.add_params(md.MSG_ARG_KEY_CLIENT_STATUS, md.CLIENT_STATUS_ONLINE)
        reply.add_params(md.MSG_ARG_KEY_CLIENT_OS, md.CLIENT_OS_PYTHON)
        self.send_message(reply)

    def handle_message_init(self, msg: Message) -> None:
        self._train_and_send(msg)

    def handle_message_receive_model(self, msg: Message) -> None:
        self._train_and_send(msg)

    def _train_and_send(self, msg: Message) -> None:
        if self._killed:
            return  # crash simulation: a dead client trains and sends nothing
        round_idx = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        # session epoch (control-only read: absent on a journal-less server,
        # and materializing tensors here would be wasted work) — echoed back
        # verbatim so the server's recovery fence can attribute the upload
        epoch = msg.get_control(md.MSG_ARG_KEY_SESSION_EPOCH)
        if epoch is not None:
            if self._last_epoch is not None and int(epoch) != self._last_epoch:
                self.server_restarts_seen += 1
                if self.flight is not None:
                    self.flight.note("epoch", event="server_restart_seen",
                                     prev=self._last_epoch, epoch=int(epoch))
                log.info("client %d: server session epoch %s -> %s "
                         "(server restarted; resuming)",
                         self.rank, self._last_epoch, epoch)
            self._last_epoch = int(epoch)  # graftlint: disable=GL008(single-writer: only the receive-loop thread writes; the cross-thread readers are hard_kill/finish flight-bundle context where a stale snapshot is acceptable — the bundle records "around the kill", and a CPython int attribute read is atomic)
        params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
        client_idx = int(msg.get(md.MSG_ARG_KEY_CLIENT_INDEX, self.rank - 1))
        if self.flight is not None:
            self.flight.note("train", round_idx=round_idx,
                             epoch=None if epoch is None else int(epoch))
        new_vars, n_samples = self.trainer.train(params, round_idx, self.seed_key, client_idx)
        self.rounds_trained += 1  # graftlint: disable=GL008(same single-writer invariant as _last_epoch above: receive-loop-only writes; hard_kill/finish read it solely as flight-bundle context)
        reply = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
                        self._upload_dest)
        payload, is_delta = self._maybe_compress(new_vars, params, round_idx)
        reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, payload)
        if is_delta:
            reply.add_params(md.MSG_ARG_KEY_MODEL_IS_DELTA, True)
        reply.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, n_samples)
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        if epoch is not None:
            reply.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(epoch))
        if self.client_journal is not None:
            # exactly-once: journal (residuals + attempt) BEFORE the send, so
            # every distinct piece of work ships under a distinct key and any
            # redelivery of these bytes is server-deduplicable.  A crash here
            # burns an attempt number; a crash before this line re-trains
            # deterministically and re-sends under the same key.
            attempt = self._next_upload_attempt(round_idx, epoch)
            self._client_journal_snapshot(round_idx, epoch)
            upload_key = (f"{self.rank}:{round_idx}:"
                          f"{-1 if epoch is None else int(epoch)}:{attempt}")
            reply.add_params(md.MSG_ARG_KEY_UPLOAD_KEY, upload_key)
            if self.flight is not None:
                self.flight.note("upload_sent", key=upload_key,
                                 round_idx=round_idx,
                                 epoch=None if epoch is None else int(epoch))
        self._send_with_reconnect(reply, seed_extra=round_idx)

    # -- crash-recovery journal (ISSUE 13) ------------------------------------
    def _next_upload_attempt(self, round_idx: int, epoch) -> int:
        """Attempt ordinal for this (round, epoch)'s upload; the bounded dict
        drops the oldest entries (only the current assignment can still be
        re-dispatched)."""
        k = f"{round_idx}:{-1 if epoch is None else int(epoch)}"
        n = self._upload_attempts.get(k, 0)
        self._upload_attempts[k] = n + 1
        from .client_journal import MAX_ATTEMPT_ENTRIES

        while len(self._upload_attempts) > MAX_ATTEMPT_ENTRIES:
            self._upload_attempts.pop(next(iter(self._upload_attempts)))
        return n

    def _client_journal_snapshot(self, round_idx: int, epoch) -> None:
        """Durably commit the protocol state this upload depends on: the
        post-compression EF residual carry, last round/epoch, attempt
        counters, and (when the trainer keeps one) its local state."""
        if self.client_journal is None or self._killed:
            return
        from .client_journal import pack_client_state

        exporter = getattr(self.trainer, "export_local_state", None)
        tstate = exporter() if callable(exporter) else None
        proto, arrays = pack_client_state(
            rank=self.rank, round_idx=round_idx, session_epoch=self._last_epoch,
            rounds_trained=self.rounds_trained,
            server_restarts_seen=self.server_restarts_seen,
            upload_attempts=self._upload_attempts,
            residuals=self._comm_residuals, trainer_state=tstate)
        try:
            self.client_journal.snapshot_state(proto, arrays)
        except OSError:
            # durability degraded (disk full, dir vanished) must not kill the
            # round — the client keeps training, it just rejoins cold
            log.warning("client %d: journal snapshot failed; continuing "
                        "without durability", self.rank, exc_info=True)

    def _client_journal_recover(self) -> None:
        """Install the newest intact client snapshot (construction-time):
        the restarted client resumes mid-conversation — EF residuals intact,
        epoch remembered, attempt counters monotone — instead of rejoining
        cold."""
        from .client_journal import CLIENT_RESUMES, unpack_client_state

        snap = self.client_journal.restore_state()
        if snap is None:
            CLIENT_RESUMES.inc(result="cold")
            return
        state = unpack_client_state(snap)
        self._comm_residuals = state["residuals"]
        self._last_epoch = state["session_epoch"]
        self.rounds_trained = state["rounds_trained"]
        self.server_restarts_seen = state["server_restarts_seen"]
        self._upload_attempts = state["upload_attempts"]
        if state["trainer_state"] is not None:
            restorer = getattr(self.trainer, "restore_local_state", None)
            if callable(restorer):
                restorer(state["trainer_state"])
        self.resumed_from_journal = True
        CLIENT_RESUMES.inc(result="resumed")
        if self.flight is not None:
            self.flight.note("journal", event="client_resume",
                             step=snap["step"], round_idx=state["round_idx"],
                             epoch=state["session_epoch"])
        log.info("client %d: resumed from journal step %d (round %s, epoch "
                 "%s, %d rounds trained)", self.rank, snap["step"],
                 state["round_idx"], state["session_epoch"],
                 state["rounds_trained"])

    def hard_kill(self) -> None:  # graftlint: disable=GL008(crash simulation: deliberately lock-free like the server's hard_kill — a SIGKILL takes no locks either; the receive-loop thread re-checks _killed at every send/journal site and goes silent)
        """Crash simulation for the soak harnesses: stop the receive loop and
        go silent ABRUPTLY — no FINISH handshake, no journal write, no
        teardown.  Anything not already journaled is lost, exactly like a
        SIGKILL; only the process (which a real SIGKILL would reclaim) stays
        alive for the harness to inspect.  A mid-train handler finishes its
        XLA call but its send/journal sites observe ``_killed`` and drop the
        result."""
        if self.flight is not None:
            self.flight.trigger("hard_kill", rank=self.rank,
                                rounds_trained=self.rounds_trained,
                                epoch=self._last_epoch)
        self._killed = True
        self.com_manager.stop_receive_message()

    def _send_with_reconnect(self, reply: Message, seed_extra: int = 0) -> None:
        """Upload with the reconnect handshake: a server mid-restart refuses
        connections for a bounded window, so retry with capped exponential
        backoff + deterministic jitter (seeded per client/round — a silo
        fleet de-synchronizes instead of stampeding the restarted listener).
        Exhausted retries abandon the upload loudly: the server's straggler
        quorum / redispatch watchdog owns recovery from there."""
        from ..comm.base import BACKOFF_PURPOSE_RECONNECT, backoff_delay

        for attempt in range(RECONNECT_TRIES):
            if self._killed:
                return  # crash simulation: a dead client retries nothing
            try:
                self.send_message(reply)
                return
            except Exception:
                if attempt + 1 >= RECONNECT_TRIES:
                    break
                # the purpose constant namespaces this jitter stream away
                # from the receive loop's decode-retry stream, so colocated
                # schedules whose seeds coincide still de-correlate
                delay = backoff_delay(
                    attempt, base=RECONNECT_BASE_S, cap=RECONNECT_CAP_S,
                    seed=self.rank * 1_000_003 + int(seed_extra),
                    purpose=BACKOFF_PURPOSE_RECONNECT)
                log.warning(
                    "client %d: upload send failed (attempt %d/%d) — "
                    "reconnecting in %.3fs", self.rank, attempt + 1,
                    RECONNECT_TRIES, delay, exc_info=True)
                time.sleep(delay)
        log.error("client %d: upload abandoned after %d reconnect attempts "
                  "(server redispatch recovers the slot)",
                  self.rank, RECONNECT_TRIES)

    def _maybe_compress(self, new_vars, global_vars, round_idx: int):
        """(payload, is_delta) for the model reply.  Compression off -> the
        trained variables untouched (bit-exact with today's bytes); on ->
        per-leaf compressed delta vs the received global model."""
        if not self.comm_codec:
            return new_vars, False
        import jax

        from ..comm import codecs

        try:
            delta = jax.tree_util.tree_map(_leaf_delta, new_vars, global_vars)
            # a dedicated RNG stream (distinct fold from the train keys) so
            # stochastic rounding never aliases the sampling/dropout streams
            key = jax.random.fold_in(
                rng.client_key(rng.round_key(self.seed_key, round_idx), self.rank), 0x5157
            )
            payload, self._comm_residuals, stats = codecs.compress_pytree(
                delta, self.comm_codec, key=key, residuals=self._comm_residuals,
                ratio=self._comm_ratio, min_elems=self._comm_min_elems,
            )
            log.debug("round %d: %s upload %d -> %d bytes (%.2fx)", round_idx,
                      self.comm_codec, stats["raw_bytes"], stats["wire_bytes"],
                      stats["ratio"])
            return payload, True
        except Exception:
            # a codec failure must degrade to the uncompressed protocol, not
            # kill the round — the server accepts both shapes every round
            log.exception("comm compression failed; sending full model raw")
            return new_vars, False

    def handle_message_finish(self, msg: Message) -> None:
        # release any trainer-side resources first (a distributed-silo
        # trainer broadcasts CMD_FINISH to its follower processes here)
        trainer_finish = getattr(self.trainer, "finish", None)
        if callable(trainer_finish):
            trainer_finish()
        if self._pallas_sink is not None:
            from ..ops.pallas import timing as pallas_timing

            pallas_timing.remove_sink(self._pallas_sink)
            self._pallas_sink = None
        if self.obs is not None:
            self.obs.close()  # final flush while the transport is still up
        if self.flight is not None and not self.flight._closed:
            self.flight.trigger("finish", rank=self.rank,
                                rounds_trained=self.rounds_trained,
                                epoch=self._last_epoch)
            self.flight.close()
        try:
            self.send_message(Message(md.MSG_TYPE_C2S_FINISHED, self.rank, 0))
        except OSError:
            # best-effort terminal ack: over real sockets the server may have
            # torn down its listener right after broadcasting FINISH (the ack
            # is bookkeeping only, server.handle_message_client_finished)
            log.debug("client %d: FINISHED ack undeliverable (server gone)", self.rank)
        self.done.set()
        self.finish()
