"""Worker entry for the real-process SIGKILL soak (ISSUE 13).

Spawned by :func:`fedml_tpu.cross_silo.async_soak.run_multiproc_kill_soak`:

    python -m fedml_tpu.cross_silo.soak_worker <cfg.json> <role> <rank> <workdir>

``role`` is ``server`` (rank 0: one buffered-async manager over the TCP
backend, recovery journal on) or ``client`` (a REAL ``ClientMasterManager``
+ trainer with its own crash-recovery journal).  The supervisor SIGKILLs
workers mid-run and respawns the identical command line — recovery is
entirely the journals' job, the worker just builds and runs.

Supervisor-facing artifacts (all atomic tmp+``os.replace`` writes in
``workdir``):

- ``boot_r<rank>_<pid>.json`` — written by every client at startup:
  ``{"rank", "pid", "restart", "resumed"}``.  ``restart`` means an earlier
  boot file for this rank exists (so this process replaces a SIGKILLed
  predecessor); ``resumed`` is whether the client journal produced a warm
  resume.  The soak's client-side accounting identity reads these.
- ``server_summary.json`` — written by the server once the run completes:
  ``async_summary()`` + a ``completed`` flag.  Its presence is the
  supervisor's completion signal.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile


def _atomic_write_json(path: str, obj: dict) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=".tmp_")
    with os.fdopen(fd, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main() -> int:
    cfg_path, role, rank, workdir = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), sys.argv[4])
    timeout_s = float(os.environ.get("SOAK_WORKER_TIMEOUT_S", "600"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    # share the repo-root persistent compilation cache with the test suite /
    # dryrun / bench, so a SIGKILL-restarted worker recompiles nothing
    from fedml_tpu.core.cache import setup_persistent_cache

    setup_persistent_cache()

    import fedml_tpu
    from fedml_tpu.arguments import Config

    with open(cfg_path) as f:
        cfg = Config(**json.load(f))
    fedml_tpu.init(cfg)

    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    if role == "server":
        from fedml_tpu.comm.chaos import ChaosCommManager
        from fedml_tpu.cross_silo import build_server

        server = build_server(cfg, ds, model, backend="TCP")
        server.run_in_thread()
        server.start()
        ok = server.done.wait(timeout_s)
        summary = server.async_summary()
        summary["completed"] = bool(ok)
        if isinstance(server.com_manager, ChaosCommManager):
            # seeded-fault composition (ISSUE 14): record what the wrapper
            # injected on the real TCP dispatch leg alongside the SIGKILLs
            summary["chaos"] = {
                "injected": dict(server.com_manager.injected),
                "silent_losses": int(server.com_manager.silent_losses()),
            }
        _atomic_write_json(os.path.join(workdir, "server_summary.json"), summary)
        server.finish()
        return 0 if ok else 3

    if role == "edge":
        # hierarchical aggregation tree (ISSUE 17): a relay/fold tier node.
        # Same survivability contract as the other roles — the edge journal
        # (<server_journal_dir>/edge_<rank>) is the whole recovery story, a
        # respawned process resumes mid-round from it and recovery_resume()
        # ships a complete-but-unshipped partial immediately.
        from fedml_tpu.cross_silo.edge import EdgeAggregatorManager, build_topology

        topo = build_topology(cfg)
        if topo is None:
            raise SystemExit("edge role requires hier_fanout/hier_topology")
        edge = EdgeAggregatorManager(cfg, topo, rank=rank, backend="TCP")
        if edge.flight is not None:
            # real-process edge: one rank per process, so the process-wide
            # SIGTERM/excepthook taps are safe here (same reasoning as the
            # client role below; in-process trees leave them uninstalled)
            edge.flight.install_signal_handlers()
        prior_boots = glob.glob(os.path.join(workdir, f"boot_r{rank}_*.json"))
        _atomic_write_json(
            os.path.join(workdir, f"boot_r{rank}_{os.getpid()}.json"),
            {"rank": rank, "pid": os.getpid(), "restart": bool(prior_boots),
             "resumed": bool(edge.resumed_from_journal)})
        edge.run_in_thread()
        edge.recovery_resume()
        ok = edge.done.wait(timeout_s)
        edge.finish()
        return 0 if ok else 3

    from fedml_tpu.cross_silo import build_client

    client = build_client(cfg, ds, model, rank=rank, backend="TCP")
    if client.flight is not None:
        # real-process client: one rank per process, so the process-wide
        # SIGTERM/excepthook taps are safe here (the in-process harnesses
        # deliberately leave them uninstalled — many clients share one
        # process there and the taps would cross-pollinate rings)
        client.flight.install_signal_handlers()
    prior_boots = glob.glob(os.path.join(workdir, f"boot_r{rank}_*.json"))
    _atomic_write_json(
        os.path.join(workdir, f"boot_r{rank}_{os.getpid()}.json"),
        {"rank": rank, "pid": os.getpid(), "restart": bool(prior_boots),
         "resumed": bool(client.resumed_from_journal)})
    client.run_in_thread()
    ok = client.done.wait(timeout_s)
    client.finish()
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
