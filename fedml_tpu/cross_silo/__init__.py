"""Cross-silo platform ("Octopus" in the reference).

Entry: ``create_cross_silo_runner(cfg, dataset, model)`` builds either the
server (rank 0) or a client (rank k) runner from ``cfg.role``/``cfg.rank`` —
the dispatch done by ``cross_silo/server/server_initializer.py`` /
``client/client_initializer.py`` in the reference.

``run_in_process_group`` launches 1 server + N client managers on threads
over the in-proc backend — the hermetic equivalent of the reference's
"background nohup processes over a public MQTT broker" smoke test
(``tests/cross-silo/run_cross_silo.sh``).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..core.flags import cfg_extra
from ..data.dataset import pad_eval_set
from .client import ClientMasterManager, FedMLTrainer
from .server import FedMLAggregator, FedMLServerManager


def _client_shard(dataset, client_idx: int):
    ix = dataset.client_idx[client_idx]
    return dataset.train_x[ix], dataset.train_y[ix]


def build_aggregator(cfg, dataset, model, trust=None,
                     mesh=None) -> FedMLAggregator:
    eval_bs = min(256, max(32, cfg.test_batch_size))
    test_arrays = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
    sample_x = dataset.train_x[: cfg.batch_size]
    if trust is None:
        from ..trust.pipeline import build_trust_pipeline

        trust = build_trust_pipeline(cfg)
    return FedMLAggregator(cfg, model, sample_x, test_arrays, trust=trust,
                           mesh=mesh)


def build_server(cfg, dataset, model, backend: Optional[str] = None, trust=None,
                 runtime=None, mesh=None) -> FedMLServerManager:
    """``runtime`` (cross_silo/runtime.py ServerRuntime): the multi-tenant
    control plane passes its shared timer-wheel/dispatch loop so N tenant
    servers ride one thread; None = the manager owns its own (the
    single-job default, semantics unchanged).  ``mesh``: an externally
    supplied mesh — under the device-slot scheduler, the job's submesh
    LEASE — the aggregator's sharded fold resolves against; None = the
    full default mesh, unchanged."""
    aggregator = build_aggregator(cfg, dataset, model, trust=trust, mesh=mesh)
    if cfg_extra(cfg, "async_aggregation"):
        # buffered-async (FedBuff-style) server: clients upload whenever
        # ready, arrivals fold with staleness-decayed weights, a virtual
        # round closes every async_buffer_k arrivals.  Flag unset -> the
        # synchronous manager, bit-identical to before the flag existed.
        from .async_server import AsyncFedMLServerManager

        return AsyncFedMLServerManager(cfg, aggregator, backend=backend,
                                       runtime=runtime)
    return FedMLServerManager(cfg, aggregator, backend=backend, runtime=runtime)


def build_client(cfg, dataset, model, rank: int, backend: Optional[str] = None) -> ClientMasterManager:
    x, y = _client_shard(dataset, rank - 1)
    from ..parallel import multihost

    multihost.ensure_initialized(cfg)
    if multihost.is_multiprocess():
        # silo spans processes (reference torchrun-DDP launcher parity):
        # local SGD runs over the global jax.distributed data mesh, FL comm
        # stays on the master process — see cross_silo/silo_dist.py.  Only
        # the master builds a client manager; followers must go through
        # run_silo_follower (the runner routes them there).
        import jax

        if jax.process_index() != 0:
            raise RuntimeError(
                "build_client called on a silo follower process; followers "
                "run cross_silo.silo_dist.run_silo_follower (the cross-silo "
                "runner does this routing when role='client')"
            )
        from .silo_dist import DistributedSiloTrainer

        trainer = DistributedSiloTrainer(cfg, model, x, y)
    else:
        trainer = FedMLTrainer(cfg, model, x, y)
    return ClientMasterManager(cfg, trainer, rank=rank, backend=backend)


class _CrossSiloRunner:
    def __init__(self, cfg, dataset, model):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model

    def _builders(self):
        """(run_group, build_srv, build_cli) for the configured privacy mode."""
        cfg = self.cfg
        if getattr(cfg, "enable_secagg", False):
            # two secure-agg variants, as in the reference: LightSecAgg
            # (cross_silo/lightsecagg/) and Shamir pairwise-mask SecAgg
            # (cross_silo/secagg/) — selected by secagg_method
            method = str(cfg_extra(cfg, "secagg_method")).lower()
            if method in ("shamir", "secagg", "pairwise"):
                from .secagg_shamir import build_sa_client, build_sa_server, run_shamir_secagg_process_group

                return (lambda *a, **k: run_shamir_secagg_process_group(*a, **k)[0],
                        build_sa_server, build_sa_client)
            if method not in ("lightsecagg", "lsa"):
                raise ValueError(f"unknown secagg_method {method!r}; use 'lightsecagg' or 'shamir'")
            from .lightsecagg import build_lsa_client, build_lsa_server, run_lightsecagg_process_group

            return (lambda *a, **k: run_lightsecagg_process_group(*a, **k)[0],
                    build_lsa_server, build_lsa_client)
        if getattr(cfg, "enable_fhe", False):
            from .fhe import build_fhe_client, build_fhe_server, run_fhe_process_group

            return (lambda *a, **k: run_fhe_process_group(*a, **k)[0],
                    build_fhe_server, build_fhe_client)
        return run_in_process_group, build_server, build_client

    def run(self):
        cfg = self.cfg
        run_group, build_srv, build_cli = self._builders()
        if cfg.role == "server" and cfg.backend in ("INPROC", "MESH", ""):
            # single-process orchestration (tests / local runs)
            return run_group(cfg, self.dataset, self.model)
        if cfg.role == "server":
            return build_srv(cfg, self.dataset, self.model).run_until_done()
        from ..parallel import multihost

        multihost.ensure_initialized(cfg)
        if multihost.is_multiprocess():
            import jax

            if getattr(cfg, "enable_secagg", False) or getattr(cfg, "enable_fhe", False):
                raise NotImplementedError(
                    "multi-process silos are not wired into the secure-"
                    "aggregation clients; run the silo as one process"
                )
            if jax.process_index() != 0:
                # silo follower: lockstep local-SGD loop, no FL comm
                from .silo_dist import run_silo_follower

                x, y = _client_shard(self.dataset, int(cfg.rank) - 1)
                run_silo_follower(cfg, self.model, x, y)
                return None
        client = build_cli(cfg, self.dataset, self.model, rank=int(cfg.rank))
        try:
            thread = client.run_in_thread()
            # poll instead of a bare wait: if the comm thread dies on a
            # transport error it never sets done, and the finally below must
            # still run to release silo followers
            while not client.done.wait(5.0):
                if not thread.is_alive():
                    break
            thread.join(timeout=5.0)
        finally:
            # release distributed-silo followers even on an abnormal end
            # (timeout, transport error) — without CMD_FINISH they block
            # forever in the broadcast collective; idempotent on clean runs
            trainer_finish = getattr(getattr(client, "trainer", None), "finish", None)
            if callable(trainer_finish):
                trainer_finish()
        return None


def create_cross_silo_runner(cfg, dataset, model):
    return _CrossSiloRunner(cfg, dataset, model)


def run_in_process_group(cfg, dataset, model, backend: str = "INPROC", timeout: float = 600.0):
    """1 server + client_num_in_total clients on threads over the in-proc
    fabric; returns the server history."""
    from ..comm.inproc import InProcRouter

    InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
    clients = [
        build_client(cfg, dataset, model, rank=r, backend=backend)
        for r in range(1, cfg.client_num_in_total + 1)
    ]
    for c in clients:
        c.run_in_thread()
    # hierarchical aggregation tree (cross_silo/edge.py): one server-shaped
    # relay manager per aggregator rank, on the same fabric.  Flat topology
    # (hier flags unset) -> no edge managers, the historical group exactly.
    from .edge import EdgeAggregatorManager, build_topology

    topo = build_topology(cfg)
    edges = [] if topo is None else [
        EdgeAggregatorManager(cfg, topo, rank=r, backend=backend)
        for r in topo.aggregator_ranks
    ]
    for e in edges:
        e.run_in_thread()
    server = build_server(cfg, dataset, model, backend=backend)
    try:
        history = server.run_until_done(timeout=timeout)
        # graceful drain: a buffered-async client may still be mid-train on
        # its daemon thread when the server finishes (sync clients are idle
        # here and their done is already set) — give each a bounded window
        # to process FINISH, so interpreter exit never lands mid-XLA-call
        for c in clients:
            c.done.wait(5.0)
        for e in edges:
            e.done.wait(5.0)
    finally:
        for c in clients:
            c.finish()
        for e in edges:
            e.finish()
    return history
