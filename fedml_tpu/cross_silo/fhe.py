"""FHE-encrypted cross-silo aggregation.

Parity with ``core/fhe/fhe_agg.py:10`` (FedML-HE): clients encrypt their
1/n-scaled updates under a shared RLWE context (``trust/fhe/rlwe.py``; the
reference ships a shared TenSEAL CKKS context the same way), the server adds
ciphertexts — it never sees an individual plaintext update — and decrypts
only the AGGREGATE for eval + broadcast.  Message flow is the plain FedAvg
protocol; only the model payload changes representation:

    INIT(plaintext global)           server -> clients
    enc(update_i / n)                client -> server     (ciphertext blocks)
    SYNC(plaintext mean)             server -> clients

Key provisioning: ``cfg.extra['fhe_key_seed']`` (out-of-band in production,
exactly like the reference's ``context.pickle``; defaults to a
random_seed-derived value for hermetic tests — the privacy statement is
"server sees only aggregates", matching the reference's shared-context
threat model, NOT server-blind decryption).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from ..core.flags import cfg_extra
from ..trust.fhe.rlwe import RLWECipher, RLWEParams, add_ciphertexts
from ..comm.message import Message
from . import message_define as md
from .client import ClientMasterManager, FedMLTrainer
from .server import FedMLAggregator, FedMLServerManager

log = logging.getLogger("fedml_tpu.cross_silo.fhe")

MSG_ARG_KEY_FHE_LEN = "fhe_len"


def fhe_cipher(cfg) -> RLWECipher:
    key_seed = int(cfg_extra(cfg, "fhe_key_seed", cfg.random_seed * 7919 + 17))
    params = RLWEParams(
        n=int(cfg_extra(cfg, "fhe_ring_dim")),
        frac_bits=int(cfg_extra(cfg, "fhe_frac_bits")),
    )
    return RLWECipher(params, key_seed=key_seed)


def check_fhe_compatible(cfg) -> None:
    incompatible = [
        f for f in ("enable_attack", "enable_defense", "enable_dp",
                    "enable_contribution", "enable_secagg")
        if getattr(cfg, f, False)
    ]
    if incompatible:
        raise NotImplementedError(
            f"trust features {incompatible} need individual client updates, "
            "which FHE aggregation hides from the server; disable them or "
            "disable enable_fhe"
        )
    if getattr(cfg, "federated_optimizer", "FedAvg") not in ("FedAvg", "fedavg", "FedAvg_seq"):
        raise NotImplementedError(
            "FHE aggregation yields only the uniform mean of updates "
            "(reference fhe_agg.py scales by 1/n before encryption); server "
            f"optimizer {cfg.federated_optimizer!r} needs plaintext updates"
        )


class FHEAggregator(FedMLAggregator):
    """Stores ciphertext block stacks; aggregation = homomorphic addition +
    aggregate-only decryption."""

    def __init__(self, cfg, model, sample_x, test_arrays, trust=None):
        check_fhe_compatible(cfg)
        super().__init__(cfg, model, sample_x, test_arrays, trust=None)
        # ciphertext block stacks are not foldable f32 trees: the associative
        # streaming path must NEVER engage here, whatever the comm flags say
        self.stream_mode = False
        self._shard_fold = False
        self.cipher = fhe_cipher(cfg)
        flat, self._unravel = jax.flatten_util.ravel_pytree(self.global_vars)
        self.model_dim = int(flat.size)

    def add_local_trained_result(self, client_idx: int, blocks, sample_num: float) -> None:
        arr = np.asarray(blocks, dtype=np.int64)  # (B, 2, N)
        if arr.ndim != 3 or arr.shape[1] != 2 or arr.shape[2] != self.cipher.params.n:
            raise ValueError(f"bad ciphertext stack shape {arr.shape}")
        self.model_dict[client_idx] = arr
        self.sample_num_dict[client_idx] = sample_num
        self.flag_client_model_uploaded[client_idx] = True

    def aggregate(self, round_idx: int):
        ids = sorted(self.model_dict.keys())
        blocks_list = [list(self.model_dict[i]) for i in ids]
        summed = add_ciphertexts(blocks_list, self.cipher.params.q)
        mean = self.cipher.decrypt_vector(summed, self.model_dim)
        # Clients pre-scale by 1/n assuming FULL participation; when the
        # straggler-quorum path aggregates only k < n survivors the decrypted
        # value is sum(x_i)/n — rescale (in plaintext, post-decryption) to
        # the survivor mean sum(x_i)/k.
        n = self.cfg.client_num_in_total
        if len(ids) != n:
            log.warning("FHE round %d: %d/%d survivors, rescaling by n/k", round_idx, len(ids), n)
            mean = mean * (n / max(len(ids), 1))
        self.global_vars = self._unravel(jnp.asarray(mean, jnp.float32))
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self.flag_client_model_uploaded.clear()
        return self.global_vars


class FHEServerManager(FedMLServerManager):
    def __init__(self, cfg, aggregator: FHEAggregator, backend: Optional[str] = None, logger=None):
        super().__init__(cfg, aggregator, backend=backend, logger=logger)
        if self.per_round != len(self.client_ids):
            raise ValueError(
                "FHE aggregation requires full participation per round: the "
                "1/n scaling clients apply before encryption assumes all "
                f"n={len(self.client_ids)} contribute "
                f"(client_num_per_round={self.per_round})"
            )


class FHEClientManager(ClientMasterManager):
    def __init__(self, cfg, trainer: FedMLTrainer, rank: int, backend: Optional[str] = None):
        check_fhe_compatible(cfg)
        super().__init__(cfg, trainer, rank=rank, backend=backend)
        self.cipher = fhe_cipher(cfg)
        self.n = cfg.client_num_in_total

    def _train_and_send(self, msg: Message) -> None:
        round_idx = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
        client_idx = int(msg.get(md.MSG_ARG_KEY_CLIENT_INDEX, self.rank - 1))
        new_vars, n_samples = self.trainer.train(params, round_idx, self.seed_key, client_idx)
        self.rounds_trained += 1
        flat, _ = jax.flatten_util.ravel_pytree(new_vars)
        # 1/n scaling BEFORE encryption (reference fhe_enc weight_factors):
        # the server's ciphertext sum then decrypts directly to the mean
        blocks = self.cipher.encrypt_vector(np.asarray(flat, np.float64) / self.n)
        reply = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, np.stack(blocks))
        reply.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, n_samples)
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        self.send_message(reply)


# -- builders ----------------------------------------------------------------

def build_fhe_server(cfg, dataset, model, backend: Optional[str] = None) -> FHEServerManager:
    from ..data.dataset import pad_eval_set

    eval_bs = min(256, max(32, cfg.test_batch_size))
    test_arrays = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
    aggregator = FHEAggregator(cfg, model, dataset.train_x[: cfg.batch_size], test_arrays)
    return FHEServerManager(cfg, aggregator, backend=backend)


def build_fhe_client(cfg, dataset, model, rank: int, backend: Optional[str] = None) -> FHEClientManager:
    ix = dataset.client_idx[rank - 1]
    trainer = FedMLTrainer(cfg, model, dataset.train_x[ix], dataset.train_y[ix])
    return FHEClientManager(cfg, trainer, rank=rank, backend=backend)


def run_fhe_process_group(cfg, dataset, model, backend: str = "INPROC", timeout: float = 600.0):
    from ..comm.inproc import InProcRouter

    InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
    clients = [
        build_fhe_client(cfg, dataset, model, rank=r, backend=backend)
        for r in range(1, cfg.client_num_in_total + 1)
    ]
    for c in clients:
        c.run_in_thread()
    server = build_fhe_server(cfg, dataset, model, backend=backend)
    try:
        history = server.run_until_done(timeout=timeout)
    finally:
        for c in clients:
            c.finish()
    return history, server
