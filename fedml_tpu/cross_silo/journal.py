"""Durable server recovery journal — crash-safe cross-silo rounds (ISSUE 10).

Every capability shipped so far assumes the server process lives forever:
the sync and buffered-async managers keep the version counter, streaming
accumulator, in-flight dispatch table, and health scores only in memory, so
a mid-run SIGKILL loses the round and strands every client.  Production FL
is defined by partial failure (PAPERS.md 2405.20431 names client churn and
unreliable links the dominant cross-silo cost; 2604.10859 shows reconnect
behavior dominating tail latency), so recovery is a protocol property here,
not an ops afterthought:

- :class:`ServerJournal` atomically snapshots the **full server protocol
  state** at (virtual-)round boundaries: the model/server-state tree rides
  the existing orbax :class:`~fedml_tpu.core.checkpoint.RoundCheckpointer`,
  and the protocol sidecar (server version, session epoch, in-flight
  dispatch table, streaming-accumulator partials, staleness cursors, health
  ledger) is one ``MAGIC + json meta + npz`` file written with the
  tmp+``os.replace``+flock pattern proven in ``core/aot.py`` — readers see
  an old or a complete new step, never a torn one.
- **Corrupt or partial steps are discarded, never served.**  ``restore``
  walks steps newest-first and falls back to the previous intact step when
  the latest one is truncated (a hard kill mid-snapshot), mirroring the AOT
  store's corrupt-entry rebuild semantics; the model checkpointer applies
  the same discipline to its own steps.
- **A session epoch fences the crash boundary.**  Each snapshot records the
  epoch it was taken under; a recovering server resumes at ``epoch + 1`` and
  stamps the new epoch into every dispatch, so uploads produced by pre-crash
  dispatches are recognizable and can be folded with corrected staleness or
  rejected deterministically — never double-folded (the policy lives in the
  server managers; the journal supplies the fence).

Gated entirely on ``extra.server_journal_dir``: unset means
:func:`journal_from_config` returns ``None`` and both server managers run
their exact pre-existing paths — wire bytes and aggregation results stay
bit-identical to the flag-free build.

Thread model (GL008-audited): one journal belongs to ONE server manager and
every ``snapshot``/``restore`` call runs under that manager's ``_agg_lock``
(round boundaries / construction), so the journal itself is lock-free; the
flock below is CROSS-process (a lingering pre-crash writer vs the restarted
server), not cross-thread.
"""

from __future__ import annotations

import contextlib
import io
import json
import logging
import os
import re
import tempfile
import time
from typing import Any, Optional

import numpy as np

from ..core.checkpoint import RoundCheckpointer
from ..core.flags import cfg_extra
from ..obs import registry as obsreg

log = logging.getLogger("fedml_tpu.cross_silo.journal")

__all__ = ["ServerJournal", "journal_from_config"]

#: on-disk step format: MAGIC + one json meta line + an npz payload.  Bump
#: the magic when the envelope changes — old steps are then discarded as
#: corrupt and recovery falls back, never misreads.
_MAGIC = b"FMLJRN1\n"
_STEP_RE = re.compile(r"^step_(\d{10})\.journal$")

SNAPSHOTS = obsreg.REGISTRY.counter(
    "fedml_journal_snapshots_total",
    "Server protocol-state snapshots committed to the recovery journal.",
)
SNAPSHOT_TIME = obsreg.REGISTRY.histogram(
    "fedml_journal_snapshot_seconds",
    "Wall time of one journal snapshot (model checkpoint + protocol sidecar).",
)
RECOVERIES = obsreg.REGISTRY.counter(
    "fedml_journal_recoveries_total",
    "Journal restore attempts at server construction, by result "
    "(recovered = state applied, empty = no intact step found).",
    labels=("result",),
)
DISCARDED = obsreg.REGISTRY.counter(
    "fedml_journal_steps_discarded_total",
    "Corrupt/partial journal steps discarded during recovery (the restart "
    "fell back to the previous intact step).",
)


class ServerJournal:
    """Atomic, step-addressed snapshots of one server's protocol state.

    ``snapshot(step, protocol, arrays, model_state)`` commits:

    - ``model_state`` (a pytree dict, e.g. ``{"global_vars": ..,
      "server_state": ..}``) through a :class:`RoundCheckpointer` under
      ``<dir>/model`` at the same ``step``;
    - ``protocol`` (JSON-able dict: versions, epoch, dispatch table,
      cursors) + ``arrays`` (named float64/float32 numpy arrays: the
      streaming-accumulator partials) as one atomically replaced sidecar.

    ``restore(model_template)`` returns the newest step whose sidecar AND
    model checkpoint both read back intact, as
    ``{"step", "protocol", "arrays", "model"}`` — or ``None``.
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.keep = max(1, int(keep))
        self._model_ckpt: Optional[RoundCheckpointer] = None

    # -- paths ---------------------------------------------------------------
    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step):010d}.journal")

    def steps(self) -> list[int]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for n in names:
            m = _STEP_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _model(self) -> RoundCheckpointer:
        if self._model_ckpt is None:
            self._model_ckpt = RoundCheckpointer(
                os.path.join(self.directory, "model"), keep=self.keep)
        return self._model_ckpt

    # -- write side ----------------------------------------------------------
    def snapshot(self, step: int, protocol: dict,
                 arrays: Optional[dict] = None,
                 model_state: Optional[dict] = None,
                 model_step: Optional[int] = None) -> None:
        """Commit one step.  Write order is model-first so a crash between
        the two writes leaves a sidecar-less model step (ignored) rather
        than a sidecar pointing at a missing model — the sidecar is the
        commit record.

        ``model_step`` (mid-round snapshots, ISSUE 13): instead of
        re-serializing the unchanged model tree every few folds, the sidecar
        REFERENCES the boundary step whose model checkpoint already holds
        this round's starting global — restore loads the model from there.
        The referenced step is always the newest model checkpoint (the round
        being accumulated started from it), so pruning never orphans it.
        Re-snapshotting the SAME step (each fold cadence overwrites the
        round's sidecar with more progress) is atomic: readers see the
        previous complete sidecar or the new one, never a torn mix."""
        t0 = time.perf_counter()
        with self._journal_flock():
            has_model = model_state is not None
            if has_model:
                self._model().save(int(step), model_state)
            arrays = dict(arrays or {})
            buf = io.BytesIO()
            np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
            payload = buf.getvalue()
            meta = {
                "step": int(step),
                "has_model": bool(has_model),
                "payload_len": len(payload),
                "created_unix": round(time.time(), 3),
                "protocol": protocol,
            }
            if not has_model and model_step is not None:
                meta["model_step"] = int(model_step)
            blob = (_MAGIC + json.dumps(meta, sort_keys=True).encode("utf-8")
                    + b"\n" + payload)
            path = self._step_path(step)
            fd, tmp = tempfile.mkstemp(dir=self.directory, prefix=".tmp_",
                                       suffix=".journal")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)  # atomic: readers see old or complete new
            except OSError:
                with contextlib.suppress(OSError):
                    os.remove(tmp)
                raise
            self._prune()
        SNAPSHOTS.inc()
        SNAPSHOT_TIME.observe(time.perf_counter() - t0)

    def _prune(self) -> None:
        for step in self.steps()[: -self.keep]:
            with contextlib.suppress(OSError):
                os.remove(self._step_path(step))

    # -- read side -----------------------------------------------------------
    def _load_step(self, step: int) -> Optional[tuple[dict, dict]]:
        """(protocol, arrays) for one sidecar, or None when it is corrupt
        (bad magic, truncated meta/payload, unreadable npz)."""
        try:
            with open(self._step_path(step), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            rest = blob[len(_MAGIC):]
            nl = rest.find(b"\n")
            if nl < 0:
                raise ValueError("truncated meta")
            meta = json.loads(rest[:nl].decode("utf-8"))
            payload = rest[nl + 1:]
            if int(meta.get("payload_len", -1)) != len(payload):
                raise ValueError("truncated payload")
            with np.load(io.BytesIO(payload), allow_pickle=False) as z:
                arrays = {k: np.asarray(z[k]) for k in z.files}
            return dict(meta), arrays
        except Exception as e:
            log.warning("journal: discarding unusable step %s (%s: %s)",
                        self._step_path(step), type(e).__name__, e)
            return None

    def restore(self, model_template: Optional[dict] = None) -> Optional[dict]:
        """Newest intact snapshot, falling back past corrupt steps.

        A step counts only when its sidecar parses AND (when the snapshot
        carried or referenced a model) the model checkpoint it names
        restores; anything less is discarded and the previous step is
        tried.  The result's ``model_step`` is the step the model was
        actually loaded from (None for model-less snapshots — a mid-round-0
        sidecar, whose round started from the deterministic fresh init)."""
        for step in reversed(self.steps()):
            loaded = self._load_step(step)
            if loaded is None:
                DISCARDED.inc()
                with contextlib.suppress(OSError):
                    os.remove(self._step_path(step))
                continue
            meta, arrays = loaded
            model = None
            model_from: Optional[int] = None
            if meta.get("has_model"):
                model_from = step
            elif meta.get("model_step") is not None:
                model_from = int(meta["model_step"])
            if model_from is not None:
                try:
                    model = self._model().restore(model_from,
                                                  template=model_template)
                except Exception as e:
                    log.warning("journal: step %d sidecar is intact but the "
                                "model checkpoint it names (step %d) is not "
                                "(%s: %s) — falling back", step, model_from,
                                type(e).__name__, e)
                    DISCARDED.inc()
                    with contextlib.suppress(OSError):
                        os.remove(self._step_path(step))
                    continue
            RECOVERIES.inc(result="recovered")
            return {"step": step, "protocol": meta["protocol"],
                    "arrays": arrays, "model": model,
                    "model_step": model_from}
        RECOVERIES.inc(result="empty")
        return None

    # -- cross-process coordination ------------------------------------------
    @contextlib.contextmanager
    def _journal_flock(self):
        """Advisory flock over the journal dir's writers: a restarted server
        and a not-yet-dead predecessor must not interleave a step write
        (same pattern as the AOT store's per-entry lock).  Reads never lock —
        atomic replace keeps them safe."""
        lock_path = os.path.join(self.directory, ".journal.lock")
        try:
            import fcntl
        except ImportError:  # non-posix: best effort
            yield
            return
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            with contextlib.suppress(OSError):
                fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)


def journal_from_config(cfg: Any) -> Optional[ServerJournal]:
    """The one gate: ``extra.server_journal_dir`` unset/falsy → ``None``
    (both server managers then run their exact pre-existing paths)."""
    if cfg is None or not cfg_extra(cfg, "server_journal_dir"):
        return None
    root = cfg_extra(cfg, "server_journal_dir")
    keep = int(cfg_extra(cfg, "server_journal_keep"))
    try:
        return ServerJournal(str(root), keep=keep)
    except OSError as e:
        log.warning("journal: directory %s unusable (%s) — running without "
                    "crash recovery", root, e)
        return None
