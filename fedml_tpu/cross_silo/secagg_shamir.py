"""Shamir pairwise-mask SecAgg — the reference's SECOND secure-agg protocol.

Wire parity with ``cross_silo/secagg/sa_fedml_server_manager.py:14`` /
``sa_fedml_client_manager.py:20`` / ``sa_fedml_aggregator.py:18`` (the
Bonawitz-style protocol; LightSecAgg is the other variant, `lightsecagg.py`).
Message flow (reference ``sa_message_define.py`` + manager handlers):

    PK           (c_pk, s_pk)                       client -> server    (setup)
    PK TABLE     all public keys                    server -> clients   (setup)
    SHARES       Shamir shares of (b_u, s_sk_u)     client -> server -> peers
    --- each client holds one share of every peer's secrets ---
    INIT/SYNC    global model                       server -> clients
    masked model quantize(x_u) + PRG(b_u)
                 + sum_{v<u} PRG(s_uv) - sum_{v>u} PRG(s_uv)   client -> server
    ACTIVE SET   first-round survivors              server -> survivors
    REVEAL       b-share of survivors,
                 s_sk-share of dropped              survivor -> server
    --- >= T+1 reveals: server reconstructs, unmasks the SUM, averages ---

Reconstruction (reference ``sa_fedml_aggregator.py:92-135``): for every
SURVIVOR u the server Shamir-decodes the self-mask seed b_u and subtracts
PRG(b_u); for every DROPPED u it decodes s_sk_u, re-derives the pairwise
agreements s_uv with each survivor's s_pk, and cancels the orphaned halves of
the pair masks.  A client's b-share and s_sk-share are never both revealed,
so no individual update can be unmasked as long as < T+1 parties collude.

Deliberate divergences from the reference (each strengthens the protocol —
the masking equation and message flow are unchanged):

- **Real key exchange.** The reference's ``my_pk_gen(sk, p, g=0)`` RETURNS
  THE SECRET KEY as the "public key" (``core/mpc/secagg.py:329-342``: g==0 ->
  pk = sk, agreement = sk_u * pk_v), so every mask seed is derivable from
  wire traffic.  Here pk = g^sk mod p (g=5) and agreement = pk_v^sk_u mod p —
  a true DH shape.  (M31 is a toy group — smooth order, Pohlig-Hellman
  breakable; a production deployment swaps in X25519.  The reference has no
  group at all.)
- **Encrypted share transit.** The reference server stores every client's
  full share vectors (``sa_fedml_server_manager.py:158-168``:
  ``b_u_SS_list``/``s_sk_SS_list``), letting it reconstruct any secret alone.
  Here a share for peer v travels under a pad derived from the c-key
  agreement between u and v; the server relays ciphertext it cannot read.
- **Per-round mask seeds.** The reference reseeds ``np.random.seed(b_u)``
  with the SAME b_u every round (``sa_fedml_client_manager.py:227``) — masks
  repeat, so two rounds' uploads differ by exactly the model delta.  Here
  every round derives fresh seeds via SHA-256(seed, round).
- Secrets come from OS entropy, not ``np.random.seed(rank)``
  (``sa_fedml_client_manager.py:273``, which makes every "secret" public).
"""

from __future__ import annotations

import hashlib
import logging
import math
import os
import threading
from typing import Optional

import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from ..comm import codecs
from ..comm.message import Message
from ..core import rng
from ..core.flags import cfg_extra
from ..trust.secagg import stream as secagg_stream
from ..trust.secagg.field import DEFAULT_PRIME, dequantize_from_field, quantize_to_field
from ..trust.secagg.shamir import (
    masked_input,
    pairwise_mask,
    shamir_reconstruct,
    shamir_share,
    unmask_sum,
)
from . import message_define as md
from .client import ClientMasterManager, FedMLTrainer
from .server import FedMLAggregator, FedMLServerManager

log = logging.getLogger("fedml_tpu.cross_silo.secagg_shamir")

# protocol constants — continue the flat cross-silo namespace (0-8 core,
# 10-13 LightSecAgg)
MSG_TYPE_C2S_PUBLIC_KEY = 14      # ref MSG_TYPE_C2S_SEND_PK_TO_SERVER = 3
MSG_TYPE_S2C_PUBLIC_KEYS = 15     # ref MSG_TYPE_S2C_OTHER_PK_TO_CLIENT = 4
MSG_TYPE_C2S_SECRET_SHARES = 16   # ref MSG_TYPE_C2S_SEND_SS_TO_SERVER = 5
MSG_TYPE_S2C_PEER_SHARES = 17     # ref MSG_TYPE_S2C_OTHER_SS_TO_CLIENT = 6
MSG_TYPE_S2C_ACTIVE_SET = 18      # ref MSG_TYPE_S2C_ACTIVE_CLIENT_LIST = 10
MSG_TYPE_C2S_SHARE_REVEAL = 19    # ref MSG_TYPE_C2S_SEND_SS_OTHERS_TO_SERVER = 11

MSG_ARG_KEY_C_PK = "c_pk"
MSG_ARG_KEY_S_PK = "s_pk"
MSG_ARG_KEY_PK_TABLE = "pk_table"
MSG_ARG_KEY_B_SHARES = "b_shares_enc"
MSG_ARG_KEY_SK_SHARES = "sk_shares_enc"
MSG_ARG_KEY_SHARE_SOURCE = "share_source"
MSG_ARG_KEY_ACTIVE_SET = "active_set"
MSG_ARG_KEY_B_REVEALS = "b_reveals"
MSG_ARG_KEY_SK_REVEALS = "sk_reveals"
#: control-plane descriptor of a streaming masked upload (codec, ring_bits,
#: frac_bits, length, delta) — present only when extra.secagg_stream is set,
#: so the legacy wire stays byte-identical
MSG_ARG_KEY_SECAGG_META = "secagg_meta"

P = DEFAULT_PRIME
DH_G = 5


def dh_keypair() -> tuple[int, int]:
    sk = int.from_bytes(os.urandom(16), "little") % (P - 3) + 2
    return sk, pow(DH_G, sk, P)


def dh_agree(sk: int, peer_pk: int) -> int:
    return pow(int(peer_pk), int(sk), P)


def derive_round_seed(seed: int, round_idx: int) -> int:
    """Fresh 31-bit PRG seed per (secret, round) — masks never repeat across
    rounds (unlike reference ``sa_fedml_client_manager.py:227``)."""
    h = hashlib.sha256(f"sa:{int(seed)}:{int(round_idx)}".encode()).digest()
    return int.from_bytes(h[:4], "little") % (2**31)


def _share_pad(c_key: int, src: int, dst: int) -> tuple[int, int]:
    """Keystream hiding a (b, s_sk) share pair in server transit, derived
    from the c-key agreement the server does not know.  Bound to the
    DIRECTION and share kind: the u<->v agreement is symmetric, so a pad
    derived from the key alone would repeat for u->v and v->u, and a later
    plaintext b-share reveal would hand the server a known-plaintext recovery
    of the sibling s_sk pad.  Hashing (key, src, dst, kind) makes every pad
    element independent."""
    def h(kind: str) -> int:
        d = hashlib.sha256(f"pad:{int(c_key)}:{int(src)}:{int(dst)}:{kind}".encode()).digest()
        return int.from_bytes(d[:8], "little") % P

    return h("b"), h("sk")


def shamir_secagg_params(cfg):
    """(T, q_bits): T = privacy threshold, reconstruction needs T+1 shares
    (reference ``sa_fedml_aggregator.py:53``: T = floor(N/2))."""
    n = cfg.client_num_in_total
    t = int(cfg_extra(cfg, "secagg_privacy_t", max(1, n // 2)))
    q_bits = int(cfg_extra(cfg, "secagg_q_bits"))
    if not (0 < t < n):
        raise ValueError(f"Shamir SecAgg needs 0 < T({t}) < N({n})")
    # central DP composes with the STREAMING fold (ISSUE 15): the noise is
    # added exactly once, to the unmasked aggregate at finalize — it never
    # needs the individual updates SecAgg hides.  LDP (and everything else
    # below) still does, and stays refused.
    streaming_cdp_ok = bool(cfg_extra(cfg, "secagg_stream")) and (
        getattr(cfg, "dp_solution_type", "ldp").lower() == "cdp")
    incompatible = [
        f for f in ("enable_attack", "enable_defense", "enable_dp", "enable_contribution", "enable_fhe")
        if getattr(cfg, f, False) and not (f == "enable_dp" and streaming_cdp_ok)
    ]
    if incompatible:
        raise NotImplementedError(
            f"trust features {incompatible} operate on individual client "
            "updates, which SecAgg hides from the server by design; disable "
            "them or disable enable_secagg (central DP composes when "
            "secagg_stream is set: noise lands once on the unmasked "
            "aggregate at finalize)"
        )
    if getattr(cfg, "federated_optimizer", "FedAvg") not in ("FedAvg", "fedavg", "FedAvg_seq"):
        raise NotImplementedError(
            "SecAgg reconstruction yields only the uniform mean of the "
            "survivors' updates (reference sa_fedml_aggregator.py:182); "
            f"{cfg.federated_optimizer!r} needs per-client updates"
        )
    from ..fl.algorithm import config_supports_associative_fold

    if not config_supports_associative_fold(cfg):
        # the masked field total IS an associative fold — an algorithm whose
        # aggregate is order- or set-sensitive cannot ride it (same protocol
        # gate as the f32 streaming accumulator, fl/algorithm.py)
        raise NotImplementedError(
            "SecAgg's masked sum is a weight-associative fold; the "
            "configured algorithm overrides aggregate() and does not "
            "declare supports_associative_fold"
        )
    return t, q_bits


class SAAggregator(FedMLAggregator):
    """Server-side state: masked field vectors + revealed shares."""

    def __init__(self, cfg, model, sample_x, test_arrays, trust=None):
        super().__init__(cfg, model, sample_x, test_arrays, trust=trust)
        # masked field vectors are not foldable f32 trees: the base f32
        # streaming path must NEVER engage here, whatever the comm flags say
        # (regression-tested — the LoRA opt-in must not bypass this).  The
        # FIELD-domain streaming fold below (extra.secagg_stream) is this
        # protocol's own fast path.
        self.stream_mode = False
        self._shard_fold = False
        self.t, self.q_bits = shamir_secagg_params(cfg)
        flat, self._unravel = jax.flatten_util.ravel_pytree(self.global_vars)
        self.model_dim = int(flat.size)
        self.n = cfg.client_num_in_total
        # streaming masked folds (ISSUE 15): each arriving masked upload
        # folds into a running field total — peak buffered <= 2 at any
        # cohort size — and the masks come out once, at finalize.  Flag
        # unset -> the historical buffer-all path, bit-identical.
        self.field_stream = bool(cfg_extra(cfg, "secagg_stream"))
        self.ring = secagg_stream.ring_for(
            codecs.codec_from_config(cfg), self.n, q_bits=self.q_bits,
            q8_frac_bits=int(cfg_extra(cfg, "secagg_q8_frac_bits")))
        self._msum: Optional[secagg_stream.StreamingMaskedSum] = None
        self._stream_is_delta = False
        # central DP at finalize (streaming only; shamir_secagg_params
        # refuses every other trust composition)
        self._dp = None
        if getattr(cfg, "enable_dp", False):
            from ..trust.dp.dp import FedMLDifferentialPrivacy

            self._dp = FedMLDifferentialPrivacy(cfg)
        self.s_pk_table: dict[int, int] = {}
        # reveals[v] = (b_reveals {u: y}, sk_reveals {u: y}) from survivor v
        self.reveals: dict[int, tuple[dict, dict]] = {}
        # clients whose s_sk was reconstructed after a dropout: their pairwise
        # seeds are known to the server, so a later rejoin would let it also
        # learn b_u (revealed for survivors) and fully unmask that client's
        # upload.  Secrets are exchanged once per run, so the only sound move
        # is PERMANENT exclusion (the reference instead re-runs its offline
        # phase every round).
        self.compromised: set[int] = set()

    def add_local_trained_result(self, client_idx: int, masked_vec, sample_num: float) -> None:
        if client_idx in self.compromised:
            log.warning(
                "client %d rejoined after its s_sk was reconstructed; refusing "
                "its upload (accepting would reveal BOTH of its secrets)",
                client_idx,
            )
            return
        vec = np.asarray(masked_vec, dtype=np.int64)
        if vec.shape != (self.model_dim,):
            raise ValueError(f"masked vector shape {vec.shape} != ({self.model_dim},)")
        super().add_local_trained_result(client_idx, vec, sample_num)

    def add_masked_upload(self, client_idx: int, packed, sample_num: float,
                          meta: dict) -> None:
        """Streaming path (extra.secagg_stream): unpack the wire-width
        masked vector and fold it into the running field total IMMEDIATELY
        — nothing cohort-sized is ever buffered.  The packed form is freed
        as soon as the fold returns, so the peak is the total plus the one
        in-flight upload."""
        if client_idx in self.compromised:
            log.warning(
                "client %d rejoined after its s_sk was reconstructed; refusing "
                "its upload (accepting would reveal BOTH of its secrets)",
                client_idx,
            )
            return
        if not self.ring.matches(meta):
            log.warning("client %d masked upload ring %s != server %s; "
                        "rejecting", client_idx, meta, self.ring.meta(0))
            return
        vec = secagg_stream.unpack_ring(
            packed, self.ring.bits, int(meta.get("length", self.model_dim)))
        if vec.shape != (self.model_dim,):
            raise ValueError(f"masked vector shape {vec.shape} != ({self.model_dim},)")
        if self._msum is None:
            self._msum = secagg_stream.StreamingMaskedSum(self.model_dim, self.ring)
        self._stream_is_delta = bool(meta.get("delta"))
        self._msum.fold(vec)
        self.sample_num_dict[client_idx] = sample_num
        self.flag_client_model_uploaded[client_idx] = True
        self.peak_buffered_updates = max(self.peak_buffered_updates,
                                         self._msum.peak_buffered)

    def survivor_ids(self) -> list[int]:
        """Clients whose (masked) upload is in this round's sum — the one
        ledger both the buffer-all and streaming paths maintain."""
        return sorted(self.flag_client_model_uploaded)

    def add_reveal(self, sender: int, b_reveals: dict, sk_reveals: dict) -> None:
        self.reveals[int(sender)] = (
            {int(u): int(y) for u, y in b_reveals.items()},
            {int(u): int(y) for u, y in sk_reveals.items()},
        )

    def reveal_count(self) -> int:
        return len(self.reveals)

    def aggregate(self, round_idx: int):
        """Reference ``aggregate_model_reconstruction`` + ``aggregate_mask_
        reconstruction`` (``sa_fedml_aggregator.py:92-188``): decode survivors'
        b_u -> subtract self-masks; decode dropped s_sk_u -> cancel orphaned
        pairwise masks; dequantize; uniform average.

        With ``extra.secagg_stream`` the sum already exists — every upload
        folded into the field total as it arrived — so finalize is just the
        seed reconstruction (tiny scalars from the reveals), the unmask over
        ONE vector, and an optional single central-DP noise draw.  The
        mod-field math is exact, so the streamed result is BITWISE the
        buffer-all result."""
        active = self.survivor_ids()
        dropped = [u for u in range(1, self.n + 1) if u not in active]

        self_seeds = {}
        for u in active:
            shares = [(v, self.reveals[v][0][u]) for v in self.reveals if u in self.reveals[v][0]]
            if len(shares) < self.t + 1:
                raise RuntimeError(f"not enough b-shares for survivor {u}: {len(shares)}")
            b_u = shamir_reconstruct(shares[: self.t + 1])
            self_seeds[u] = derive_round_seed(b_u, round_idx)

        dropped_pair_seeds = {}
        for u in dropped:
            shares = [(v, self.reveals[v][1][u]) for v in self.reveals if u in self.reveals[v][1]]
            if len(shares) < self.t + 1:
                raise RuntimeError(f"not enough s_sk-shares for dropped {u}: {len(shares)}")
            s_sk_u = shamir_reconstruct(shares[: self.t + 1])
            self.compromised.add(u)  # its pairwise seeds are now server-known
            for v in active:
                s_uv = dh_agree(s_sk_u, self.s_pk_table[v])
                dropped_pair_seeds[(u, v)] = derive_round_seed(s_uv, round_idx)

        if self._msum is not None:
            total = self._msum.finalize(self_seeds, dropped_pair_seeds)
            avg = dequantize_from_field(
                total, len(active), p=self.ring.modulus, bits=self.ring.frac_bits)
            avg = avg / max(len(active), 1)
            if self._stream_is_delta:
                # qsgd8 composition ships quantized DELTAS vs the round's
                # broadcast global: the unmasked mean delta lands on it
                old_flat, _ = jax.flatten_util.ravel_pytree(self.global_vars)
                avg = np.asarray(old_flat, np.float64) + avg
        else:
            masked = {u: self.model_dict[u] for u in active}
            total = unmask_sum(masked, self_seeds, dropped_pair_seeds)
            avg = dequantize_from_field(total, len(active), bits=self.q_bits)
            avg = avg / max(len(active), 1)
        avg = self._apply_central_dp(avg, round_idx)
        self.global_vars = self._unravel(jnp.asarray(avg, jnp.float32))
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self.flag_client_model_uploaded.clear()
        self.reveals.clear()
        self._msum = None
        self._stream_is_delta = False
        return self.global_vars

    def _apply_central_dp(self, avg: np.ndarray, round_idx: int) -> np.ndarray:
        """Central DP, EXACTLY ONCE, at finalize (ISSUE 15): clip the
        aggregate's round delta and add calibrated noise on the Pallas RNG
        path (``ops/pallas/noise.py`` — noise drawn from the round key, the
        scale-and-add fused).  Engaged only when ``shamir_secagg_params``
        admitted the enable_dp + secagg_stream + CDP composition."""
        if self._dp is None or not self._dp.is_cdp_enabled():
            return avg
        from ..ops.pallas import noise as pallas_noise
        from ..trust.dp.dp import gaussian_sigma

        old_flat, _ = jax.flatten_util.ravel_pytree(self.global_vars)
        delta = jnp.asarray(avg, jnp.float32) - jnp.asarray(old_flat, jnp.float32)
        delta = self._dp.global_clip(delta)
        flat = jnp.asarray(old_flat, jnp.float32) + delta
        key = jax.random.fold_in(rng.round_key(self.root_key, round_idx), 0xCD9)
        if self._dp.mechanism == "gaussian":
            sigma = gaussian_sigma(self._dp.epsilon, self._dp.delta,
                                   self._dp.sensitivity)
            noised = pallas_noise.apply_gaussian_noise(
                flat, key, sigma, interpret=jax.default_backend() != "tpu")
        else:
            noised = self._dp.add_global_noise(flat, key)
        return np.asarray(noised, np.float64)


class SAServerManager(FedMLServerManager):
    """Reference ``FedMLServerManager`` (secagg): PK collection/broadcast,
    encrypted share relay, active-set announcement, reveal collection."""

    def __init__(self, cfg, aggregator: SAAggregator, backend: Optional[str] = None, logger=None):
        super().__init__(cfg, aggregator, backend=backend, logger=logger)
        if self.per_round != len(self.client_ids):
            raise ValueError(
                "Shamir SecAgg requires full participation per round "
                f"(client_num_per_round={self.per_round} != N={len(self.client_ids)}); "
                "the pairwise-mask topology is over all N clients"
            )
        self.n = cfg.client_num_in_total
        self.pk_table: dict[int, tuple[int, int]] = {}
        # share_box[dest] = {src: (b_share_enc, sk_share_enc)}
        self.share_box: dict[int, dict[int, tuple[int, int]]] = {v: {} for v in self.client_ids}
        self.active_first: list[int] = []
        self._phase = "model"  # model -> reveal

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(MSG_TYPE_C2S_PUBLIC_KEY, self.handle_message_public_key)
        self.register_message_receive_handler(MSG_TYPE_C2S_SECRET_SHARES, self.handle_message_secret_shares)
        self.register_message_receive_handler(MSG_TYPE_C2S_SHARE_REVEAL, self.handle_message_reveal)

    # -- setup: PK round ------------------------------------------------------
    def handle_message_public_key(self, msg: Message) -> None:
        """Collect every client's (c_pk, s_pk); broadcast the full table once
        complete (reference ``_handle_message_receive_public_key`` :146)."""
        with self._agg_lock:
            self.pk_table[msg.get_sender_id()] = (
                int(msg.get(MSG_ARG_KEY_C_PK)), int(msg.get(MSG_ARG_KEY_S_PK))
            )
            self.aggregator.s_pk_table = {u: pk[1] for u, pk in self.pk_table.items()}
            complete = len(self.pk_table) == self.n
        if complete:
            table = {str(u): [int(c), int(s)] for u, (c, s) in self.pk_table.items()}
            for cid in self.client_ids:
                out = Message(MSG_TYPE_S2C_PUBLIC_KEYS, 0, cid)
                out.add_params(MSG_ARG_KEY_PK_TABLE, table)
                self.send_message(out)

    # -- setup: share relay ---------------------------------------------------
    def handle_message_secret_shares(self, msg: Message) -> None:
        """Store-and-forward: client u's encrypted share for peer v goes to v
        only — the server keeps ciphertext it cannot open (unlike reference
        ``sa_fedml_server_manager.py:158``, which stores plaintext shares)."""
        src = msg.get_sender_id()
        b_enc = np.asarray(msg.get(MSG_ARG_KEY_B_SHARES), dtype=np.int64)
        sk_enc = np.asarray(msg.get(MSG_ARG_KEY_SK_SHARES), dtype=np.int64)
        with self._agg_lock:
            for v in self.client_ids:
                self.share_box[v][src] = (int(b_enc[v - 1]), int(sk_enc[v - 1]))
            ready = all(len(self.share_box[v]) == self.n for v in self.client_ids)
        if ready:
            for v in self.client_ids:
                out = Message(MSG_TYPE_S2C_PEER_SHARES, 0, v)
                out.add_params(MSG_ARG_KEY_B_SHARES,
                               {str(u): b for u, (b, _) in self.share_box[v].items()})
                out.add_params(MSG_ARG_KEY_SK_SHARES,
                               {str(u): s for u, (_, s) in self.share_box[v].items()})
                self.send_message(out)

    # -- round: masked models -------------------------------------------------
    def handle_message_receive_model(self, msg: Message) -> None:
        with self._agg_lock:
            if msg.get(md.MSG_ARG_KEY_ROUND_INDEX) != self.round_idx or self._phase != "model":
                return
            meta = msg.get_control(MSG_ARG_KEY_SECAGG_META)
            if meta is not None:
                # streaming masked upload (extra.secagg_stream): folds into
                # the field total right here — never buffered
                self.aggregator.add_masked_upload(
                    msg.get_sender_id(),
                    msg.get(md.MSG_ARG_KEY_MODEL_PARAMS),
                    float(msg.get(md.MSG_ARG_KEY_NUM_SAMPLES)),
                    meta,
                )
            else:
                self.aggregator.add_local_trained_result(
                    msg.get_sender_id(),
                    msg.get(md.MSG_ARG_KEY_MODEL_PARAMS),
                    float(msg.get(md.MSG_ARG_KEY_NUM_SAMPLES)),
                )
            # permanently-excluded (compromised) clients never count toward
            # the expectation — their uploads are refused by the aggregator
            expected = len([c for c in self.selected if c not in self.aggregator.compromised])
            if self.aggregator.check_whether_all_receive(expected):
                self._request_reveals()

    def _request_reveals(self) -> None:
        """Freeze the survivor set, announce it, collect reveals (reference
        ``_send_message_to_active_client`` :313).  Caller holds _agg_lock."""
        self._runtime.cancel(self, "straggler")
        self._phase = "reveal"
        self.active_first = self.aggregator.survivor_ids()
        for cid in self.active_first:
            out = Message(MSG_TYPE_S2C_ACTIVE_SET, 0, cid)
            out.add_params(MSG_ARG_KEY_ACTIVE_SET, [int(c) for c in self.active_first])
            out.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(out)
        self._arm_straggler_timer()

    def handle_message_reveal(self, msg: Message) -> None:
        with self._agg_lock:
            if msg.get(md.MSG_ARG_KEY_ROUND_INDEX) != self.round_idx or self._phase != "reveal":
                return
            self.aggregator.add_reveal(
                msg.get_sender_id(),
                msg.get(MSG_ARG_KEY_B_REVEALS),
                msg.get(MSG_ARG_KEY_SK_REVEALS),
            )
            if self.aggregator.reveal_count() >= len(self.active_first):
                self._phase = "model"
                self._finish_round()

    def _on_straggler_timeout(self) -> None:
        """Model phase: advance with a quorum; reveal phase: reconstruct as
        soon as >= T+1 reveals arrived (the hard decode threshold)."""
        with self._agg_lock:
            if self._phase == "model":
                # quorum over clients that CAN still upload: permanently
                # excluded (compromised) clients never will
                eligible = [c for c in self.selected if c not in self.aggregator.compromised]
                if len(eligible) < self.aggregator.t + 1:
                    self.failed = (
                        f"only {len(eligible)} eligible clients remain but "
                        f"reconstruction needs T+1={self.aggregator.t + 1}; "
                        "the run cannot make progress (too many permanently "
                        "excluded clients)"
                    )
                    log.error(self.failed)
                    self.send_finish()
                    return
                need = max(
                    self.aggregator.t + 1,
                    int(math.ceil(self.quorum_frac * len(eligible))),
                )
                if self.aggregator.received_count() >= need:
                    log.warning(
                        "round %d: straggler timeout, proceeding with %d/%d masked models",
                        self.round_idx, self.aggregator.received_count(), len(self.selected),
                    )
                    self._request_reveals()
                    return
            else:
                if self.aggregator.reveal_count() >= self.aggregator.t + 1:
                    log.warning(
                        "round %d: reveal-phase timeout, reconstructing from %d/%d reveals",
                        self.round_idx, self.aggregator.reveal_count(), len(self.active_first),
                    )
                    self._phase = "model"
                    self._finish_round()
                    return
            self._arm_straggler_timer()


class SAClientManager(ClientMasterManager):
    """Reference ``FedMLClientManager`` (secagg): keygen + share-out once,
    then per round: train, mask, upload; reveal on request."""

    def __init__(self, cfg, trainer: FedMLTrainer, rank: int, backend: Optional[str] = None):
        super().__init__(cfg, trainer, rank=rank, backend=backend)
        self.t, self.q_bits = shamir_secagg_params(cfg)
        self.n = cfg.client_num_in_total
        # streaming masked uploads (ISSUE 15): quantize(-then-mask) into the
        # cohort-sized ring and ship the minimal wire dtype; flag unset ->
        # the historical int64 field vector, byte-identical
        self.stream = bool(cfg_extra(cfg, "secagg_stream"))
        self.ring = secagg_stream.ring_for(
            codecs.codec_from_config(cfg), self.n, q_bits=self.q_bits,
            q8_frac_bits=int(cfg_extra(cfg, "secagg_q8_frac_bits")))
        # secrets from OS entropy (reference seeds np.random with the RANK,
        # sa_fedml_client_manager.py:273 — making every secret public)
        self.c_sk, self.c_pk = dh_keypair()
        self.s_sk, self.s_pk = dh_keypair()
        self.b_u = int.from_bytes(os.urandom(8), "little") % (2**31)
        self.pk_table: dict[int, tuple[int, int]] = {}
        # held_shares[u] = (b_share_y, sk_share_y) with x = own rank
        self.held_shares: dict[int, tuple[int, int]] = {}
        self._setup_done = threading.Event()
        self._pending_msg: Optional[Message] = None
        self._lock = threading.Lock()
        self._shared_out = False

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(MSG_TYPE_S2C_PUBLIC_KEYS, self.handle_message_pk_table)
        self.register_message_receive_handler(MSG_TYPE_S2C_PEER_SHARES, self.handle_message_peer_shares)
        self.register_message_receive_handler(MSG_TYPE_S2C_ACTIVE_SET, self.handle_message_active_set)

    # -- setup ----------------------------------------------------------------
    def _train_and_send(self, msg: Message) -> None:
        """INIT/SYNC: run setup lazily on the first round, then train+mask."""
        with self._lock:
            self._pending_msg = msg
        if not self._setup_done.is_set():
            if not self.pk_table:
                out = Message(MSG_TYPE_C2S_PUBLIC_KEY, self.rank, 0)
                out.add_params(MSG_ARG_KEY_C_PK, int(self.c_pk))
                out.add_params(MSG_ARG_KEY_S_PK, int(self.s_pk))
                self.send_message(out)
            # else: PK table held, peer shares still in flight — the
            # handle_message_peer_shares completion triggers training
            return
        self._train_masked()

    def handle_message_pk_table(self, msg: Message) -> None:
        """PK table in: Shamir-share b_u and s_sk, encrypt share (u -> v)
        under the c-key agreement with v, ship through the server
        (reference ``__offline`` :272 + ``_send_secret_share_to_sever``)."""
        with self._lock:
            # Share-out must happen exactly once: re-sharing b_u/s_sk under a
            # FRESH random polynomial (e.g. on an MQTT redelivery of the PK
            # table) would leave peers holding shares of the same secret from
            # different polynomials — Shamir reconstruction then silently
            # yields garbage and the unmasked aggregate is wrong.
            if self._shared_out:
                return
            self._shared_out = True
        try:
            self._share_out(msg)
        except Exception:
            # the single send failed atomically — no peer holds shares yet, so
            # a redelivered PK table may safely retry with a fresh polynomial
            with self._lock:
                self._shared_out = False
            raise

    def _share_out(self, msg: Message) -> None:
        table = msg.get(MSG_ARG_KEY_PK_TABLE)
        self.pk_table = {int(u): (int(v[0]), int(v[1])) for u, v in table.items()}
        rng = np.random.RandomState(
            int.from_bytes(os.urandom(4), "little")
        )
        b_shares = shamir_share(self.b_u, self.n, self.t + 1, rng)
        sk_shares = shamir_share(self.s_sk, self.n, self.t + 1, rng)
        b_enc = np.zeros(self.n, dtype=np.int64)
        sk_enc = np.zeros(self.n, dtype=np.int64)
        for v in range(1, self.n + 1):
            pad_b, pad_sk = _share_pad(
                dh_agree(self.c_sk, self.pk_table[v][0]), self.rank, v
            )
            b_enc[v - 1] = (b_shares[v - 1][1] + pad_b) % P
            sk_enc[v - 1] = (sk_shares[v - 1][1] + pad_sk) % P
        out = Message(MSG_TYPE_C2S_SECRET_SHARES, self.rank, 0)
        out.add_params(MSG_ARG_KEY_B_SHARES, b_enc)
        out.add_params(MSG_ARG_KEY_SK_SHARES, sk_enc)
        self.send_message(out)

    def handle_message_peer_shares(self, msg: Message) -> None:
        b_enc = msg.get(MSG_ARG_KEY_B_SHARES)
        sk_enc = msg.get(MSG_ARG_KEY_SK_SHARES)
        with self._lock:
            for u_str, b in b_enc.items():
                u = int(u_str)
                pad_b, pad_sk = _share_pad(
                    dh_agree(self.c_sk, self.pk_table[u][0]), u, self.rank
                )
                self.held_shares[u] = (
                    (int(b) - pad_b) % P,
                    (int(sk_enc[u_str]) - pad_sk) % P,
                )
            ready = len(self.held_shares) == self.n
        if ready:
            self._setup_done.set()
            self._train_masked()

    # -- per round ------------------------------------------------------------
    def _train_masked(self) -> None:
        with self._lock:
            msg = self._pending_msg
            self._pending_msg = None
        if msg is None:
            return
        round_idx = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
        client_idx = int(msg.get(md.MSG_ARG_KEY_CLIENT_INDEX, self.rank - 1))
        new_vars, n_samples = self.trainer.train(params, round_idx, self.seed_key, client_idx)
        self.rounds_trained += 1
        flat, _ = jax.flatten_util.ravel_pytree(new_vars)
        peer_seeds = {
            v: derive_round_seed(dh_agree(self.s_sk, self.pk_table[v][1]), round_idx)
            for v in self.pk_table if v != self.rank
        }
        self_seed = derive_round_seed(self.b_u, round_idx)
        reply = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        if self.stream:
            ring = self.ring
            if ring.codec == "qsgd8":
                # quantize-then-mask (ISSUE 15): qsgd8's stochastic grid at
                # the config-shared scale over the round's DELTA — small
                # values, int8 width, masked sum exactly decodable
                base_flat, _ = jax.flatten_util.ravel_pytree(params)
                delta = np.asarray(flat, np.float64) - np.asarray(base_flat, np.float64)
                q = secagg_stream.quantize_stochastic_int8(
                    delta, ring.frac_bits,
                    [int(self.cfg.random_seed), int(round_idx), int(self.rank)])
                x_field = np.mod(q, ring.modulus)
                is_delta = True
            else:
                x_field = quantize_to_field(np.asarray(flat), bits=self.q_bits)
                is_delta = False
            masked = secagg_stream.mask_vector(x_field, self.rank, peer_seeds,
                                               self_seed, ring.modulus)
            packed = secagg_stream.pack_ring(masked, ring.bits)
            codecs.note_masked_payload(
                f"secagg_{ring.codec}", packed.nbytes, np.asarray(flat).nbytes)
            reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, packed)
            meta = ring.meta(int(x_field.size))
            meta["delta"] = is_delta
            reply.add_params(MSG_ARG_KEY_SECAGG_META, meta)
        else:
            x_field = quantize_to_field(np.asarray(flat), bits=self.q_bits)
            masked = masked_input(x_field, self.rank, peer_seeds, self_seed)
            reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, masked)
        reply.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, n_samples)
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        self.send_message(reply)

    def handle_message_active_set(self, msg: Message) -> None:
        """Reveal b-shares of survivors, s_sk-shares of dropped — NEVER both
        for the same peer (reference ``handle_message_receive_active_from_
        server`` :134)."""
        active = {int(c) for c in msg.get(MSG_ARG_KEY_ACTIVE_SET)}
        with self._lock:
            b_rev = {str(u): y[0] for u, y in self.held_shares.items() if u in active}
            sk_rev = {str(u): y[1] for u, y in self.held_shares.items() if u not in active}
        reply = Message(MSG_TYPE_C2S_SHARE_REVEAL, self.rank, 0)
        reply.add_params(MSG_ARG_KEY_B_REVEALS, b_rev)
        reply.add_params(MSG_ARG_KEY_SK_REVEALS, sk_rev)
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX)))
        self.send_message(reply)


# -- builders -----------------------------------------------------------------

def build_sa_server(cfg, dataset, model, backend: Optional[str] = None) -> SAServerManager:
    from ..data.dataset import pad_eval_set

    eval_bs = min(256, max(32, cfg.test_batch_size))
    test_arrays = pad_eval_set(dataset.test_x, dataset.test_y, eval_bs)
    aggregator = SAAggregator(cfg, model, dataset.train_x[: cfg.batch_size], test_arrays)
    return SAServerManager(cfg, aggregator, backend=backend)


def build_sa_client(cfg, dataset, model, rank: int, backend: Optional[str] = None) -> SAClientManager:
    ix = dataset.client_idx[rank - 1]
    trainer = FedMLTrainer(cfg, model, dataset.train_x[ix], dataset.train_y[ix])
    return SAClientManager(cfg, trainer, rank=rank, backend=backend)


def run_shamir_secagg_process_group(cfg, dataset, model, backend: str = "INPROC",
                                    timeout: float = 600.0, drop_ranks: frozenset = frozenset()):
    """1 server + N Shamir-SecAgg clients on threads over the in-proc fabric.
    ``drop_ranks`` clients complete setup (their pair masks ARE in survivors'
    uploads) but never upload a model — the hard dropout case requiring
    s_sk reconstruction."""
    from ..comm.inproc import InProcRouter

    InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
    clients = []
    for r in range(1, cfg.client_num_in_total + 1):
        c = build_sa_client(cfg, dataset, model, rank=r, backend=backend)
        if r in drop_ranks:
            c._train_masked = lambda: None  # drops out before model upload
        clients.append(c)
    for c in clients:
        c.run_in_thread()
    server = build_sa_server(cfg, dataset, model, backend=backend)
    try:
        history = server.run_until_done(timeout=timeout)
    finally:
        for c in clients:
            c.finish()
    return history, server
