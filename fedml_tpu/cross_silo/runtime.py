"""Event-driven server runtime — one dispatch loop + timer wheel (ISSUE 14).

Before this module, every server manager hand-rolled its own thread soup:
the sync server spawned a fresh ``threading.Timer`` per straggler deadline
AND per status re-probe, the buffered-async server added a third family for
its redispatch watchdog, and each timer callback was its own short-lived
thread racing the receive loop for ``_agg_lock``.  That shape is why the
GL007/GL008 concurrency lint grew a suppression list: timer *handles* were
shared mutable state written from three thread roots.

:class:`ServerRuntime` replaces all of it with ONE daemon thread per
runtime (started lazily — a server that never arms a timer never pays for
the thread):

- a **timer wheel**: ``arm(owner, name, delay, fn)`` schedules ``fn`` on
  the wheel; re-arming the same ``(owner, name)`` atomically supersedes the
  previous entry (the cancel+create dance the managers used to do with raw
  Timer handles), and ``cancel(owner)`` drops everything an owner scheduled
  — so managers no longer store timer handles at all, which is what lets
  their GL008 suppressions be *deleted* instead of grown;
- a **dispatch loop**: ``post(fn)`` runs ``fn`` on the same thread, the
  hook the multi-tenant gang scheduler uses to run round-grant callbacks
  off every server's receive loop.

Callbacks run OUTSIDE the runtime's internal lock (a callback that takes a
server's ``_agg_lock`` never creates a runtime-lock -> agg-lock edge), and
one runtime can serve MANY managers: the multi-tenant control plane
(``sched/multi_tenant.py``) passes one shared runtime to every tenant's
server, collapsing N per-job thread soups into a single loop.  A manager
constructed without a runtime builds (and owns) its own — the single-job
path keeps exactly one extra thread, timer semantics unchanged.

:class:`GangScheduler` is the round-boundary arbiter the control plane
builds on top: N jobs request the mesh slot when they are ready to start a
(virtual) round, the scheduler grants ``slots`` of them by strict priority
then weighted fair share (virtual time += measured hold / weight), and
grant callbacks are ``post()``-ed to the runtime so they never run under
the scheduler's lock.  Preemption is at round boundaries by construction:
a higher-priority job never aborts a running round, it simply wins every
subsequent grant until it finishes (each pass-over of an otherwise-next
job is metered as a preemption).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from typing import Callable, Optional

from ..obs import registry as obsreg

log = logging.getLogger("fedml_tpu.cross_silo.runtime")

__all__ = ["ServerRuntime", "GangScheduler"]

TIMER_FIRES = obsreg.REGISTRY.counter(
    "fedml_runtime_timer_fires_total",
    "Timer-wheel callbacks executed by the event-driven server runtime.",
)
POSTED_CALLBACKS = obsreg.REGISTRY.counter(
    "fedml_runtime_posted_total",
    "Callbacks posted onto the runtime's dispatch loop (gang-scheduler "
    "grants, deferred work).",
)
SLOT_GRANTS = obsreg.REGISTRY.counter(
    "fedml_mt_slot_grants_total",
    "Mesh-slot grants issued by the gang scheduler, by job.",
    labels=("job",),
)
SLOT_WAIT = obsreg.REGISTRY.histogram(
    "fedml_mt_slot_wait_seconds",
    "Round-boundary wait between a job's slot request and its grant, by job.",
    labels=("job",),
)
SLOT_HOLD = obsreg.REGISTRY.histogram(
    "fedml_mt_round_hold_seconds",
    "Mesh-slot hold time of one granted (virtual) round, by job — the "
    "per-tenant round latency under gang scheduling.",
    labels=("job",),
)
PREEMPTIONS = obsreg.REGISTRY.counter(
    "fedml_mt_preemptions_total",
    "Round-boundary preemptions: grants where a higher-priority job was "
    "chosen over the fair-share (lowest-virtual-time) candidate, by the "
    "job that was passed over.",
    labels=("job",),
)
FLEET_SUBMESHES = obsreg.REGISTRY.gauge(
    "fedml_fleet_submeshes",
    "Disjoint per-job submeshes the device-slot scheduler arbitrates (0 = "
    "no SubmeshPlan; time-sliced full-mesh gate).",
)
LEASE_GRANTS = obsreg.REGISTRY.counter(
    "fedml_fleet_lease_grants_total",
    "Submesh-lease grants issued by the device-slot scheduler, by job — a "
    "grant binds the job's round to its leased devices, not the full mesh.",
    labels=("job",),
)
QUOTA_THROTTLED = obsreg.REGISTRY.counter(
    "fedml_fleet_quota_throttled_total",
    "Admissions deferred because the tenant's token-bucket quota "
    "(extra.mt_quota_burst) was empty, by job; the job resumes when the "
    "bucket refills — throttled, never starved.",
    labels=("job",),
)


class ServerRuntime:
    """One daemon thread driving a timer wheel + posted-callback queue.

    Thread model (GL008-audited): every mutable structure below is touched
    only under ``_cond`` (its lock); callbacks are dequeued under the lock
    and invoked outside it on the loop thread.  A callback exception is
    logged and contained — one bad timer must not kill every tenant's
    timers.  The loop thread starts lazily at the first ``arm``/``post``.
    """

    def __init__(self, name: str = "fedml-server-runtime"):
        self.name = name
        self._cond = threading.Condition()
        #: min-heap of (due_monotonic, seq) — entries resolve through
        #: _timers so a superseded/cancelled heap entry is skipped cheaply
        self._heap: list[tuple[float, int]] = []
        #: (owner-id, name) -> (seq, due, fn); seq identifies the live entry
        self._timers: dict[tuple[int, str], tuple[int, float, Callable]] = {}
        self._by_seq: dict[int, tuple[int, str]] = {}
        self._posted: list[Callable] = []
        self._seq = itertools.count(1)
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- scheduling interface -------------------------------------------------
    def arm(self, owner: object, name: str, delay_s: float, fn: Callable) -> None:
        """Schedule ``fn`` after ``delay_s``; supersedes any previous timer
        armed under the same ``(owner, name)`` (the old entry never fires)."""
        key = (id(owner), str(name))
        due = time.monotonic() + max(0.0, float(delay_s))
        with self._cond:
            if self._closed:
                return
            old = self._timers.pop(key, None)
            if old is not None:
                self._by_seq.pop(old[0], None)
            seq = next(self._seq)
            self._timers[key] = (seq, due, fn)
            self._by_seq[seq] = key
            heapq.heappush(self._heap, (due, seq))
            self._ensure_thread()
            self._cond.notify()

    def cancel(self, owner: object, name: Optional[str] = None) -> None:
        """Cancel one named timer, or every timer of ``owner`` when ``name``
        is None.  A callback already dequeued keeps running (exactly the
        ``threading.Timer.cancel`` race the managers always had)."""
        oid = id(owner)
        with self._cond:
            keys = ([(oid, str(name))] if name is not None
                    else [k for k in self._timers if k[0] == oid])
            for key in keys:
                entry = self._timers.pop(key, None)
                if entry is not None:
                    self._by_seq.pop(entry[0], None)

    def post(self, fn: Callable) -> None:
        """Run ``fn`` as soon as possible on the loop thread (FIFO)."""
        with self._cond:
            if self._closed:
                return
            self._posted.append(fn)
            POSTED_CALLBACKS.inc()
            self._ensure_thread()
            self._cond.notify()

    def close(self) -> None:
        """Stop the loop thread and drop every pending timer/callback.
        Idempotent; safe to call from a callback (the loop notices the flag
        on its next iteration)."""
        with self._cond:
            self._closed = True
            self._timers.clear()
            self._by_seq.clear()
            self._heap.clear()
            self._posted.clear()
            self._cond.notify_all()
            t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    # -- loop -----------------------------------------------------------------
    def _ensure_thread(self) -> None:  # graftlint: disable=GL004(caller holds _cond: both arm() and post() call this under the lock)
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name=self.name, daemon=True)
            self._thread.start()

    def _next_work(self) -> tuple[Optional[Callable], bool]:
        """(callback-or-None, closed) — one bounded wait for due work.
        Posted callbacks run before due timers (grants must not starve
        behind a busy wheel)."""
        with self._cond:
            if self._closed:
                return None, True
            if self._posted:
                return self._posted.pop(0), False
            now = time.monotonic()
            while self._heap and self._heap[0][0] <= now:
                _due, seq = heapq.heappop(self._heap)
                key = self._by_seq.pop(seq, None)
                if key is None:
                    continue  # superseded or cancelled
                entry = self._timers.pop(key, None)
                if entry is None or entry[0] != seq:
                    continue
                TIMER_FIRES.inc()
                return entry[2], False
            timeout = 0.2
            if self._heap:
                timeout = min(timeout, max(0.0, self._heap[0][0] - now))
            self._cond.wait(timeout=max(0.001, timeout))
            return None, self._closed

    def _loop(self) -> None:
        while True:
            fn, closed = self._next_work()
            if closed:
                return
            if fn is None:
                continue
            try:
                fn()
            except Exception:
                # contain: one tenant's bad callback must not kill the
                # shared wheel (same invariant as the receive loop's
                # handler guard)
                log.exception("runtime callback failed on %s", self.name)


class GangScheduler:
    """Round-boundary device-slot arbiter for N concurrent FL jobs.

    Jobs (server managers) call :meth:`request` when ready to start a
    (virtual) round and :meth:`release` when the round's aggregate commits.
    ``slots`` rounds run concurrently; the next grant goes to the highest
    priority class first, then the lowest virtual time within it
    (``vtime += hold_seconds / weight`` — weighted fair share over the
    *measured* round cost, so an expensive tenant does not starve cheap
    ones at equal weights).  Grant callbacks are posted to the runtime's
    dispatch loop, never run under this scheduler's lock or the caller's.

    Two admission layers sit on top of fair share (ISSUE 19), both off by
    default and bit-identical when off:

    - **submesh leases**: constructed with a ``SubmeshPlan``, a grant is a
      lease of the job's HOME submesh (static — its compiled programs bind
      to those devices), ``slots`` equals the partition degree, and jobs on
      distinct leases run genuinely concurrently; :meth:`lease_of` exposes
      the Mesh so callers build their shardings against the lease.
    - **token-bucket quota** (``quota_burst`` grants, one token refilled
      every ``quota_refill_s`` seconds): caps one tenant's admission rate
      between round boundaries regardless of weight.  A quota-blocked job
      stays pending and a refill timer re-pumps at the earliest token
      arrival — throttled, never starved.

    Thread model (GL008-audited): all state below is guarded by ``_lock``;
    grant callbacks are collected under the lock and posted outside it.
    """

    def __init__(self, runtime: ServerRuntime, slots: int = 1,
                 plan=None, quota_burst: float = 0.0,
                 quota_refill_s: float = 1.0):
        self.runtime = runtime
        #: optional parallel.mesh.SubmeshPlan — present, a grant is a
        #: SUBMESH LEASE (the job's round runs on its leased devices while
        #: siblings run on theirs) and ``slots`` is the partition degree;
        #: absent, grants are time-sliced full-mesh round tokens (PR-14
        #: semantics, bit-identical)
        self.plan = plan
        self.slots = len(plan) if plan is not None else max(1, int(slots))
        #: token-bucket admission quota (extra.mt_quota_burst /
        #: mt_quota_refill_s); burst <= 0 disables the bucket entirely
        self.quota_burst = float(quota_burst or 0.0)
        self.quota_refill_s = max(1e-6, float(quota_refill_s or 1.0))
        self._lock = threading.Lock()
        self._names: dict[int, str] = {}
        self._weights: dict[int, float] = {}
        self._priority: dict[int, int] = {}
        self._vtime: dict[int, float] = {}
        #: job-id -> (grant callback, enqueue monotonic, arrival seq)
        self._pending: dict[int, tuple[Callable, float, int]] = {}
        #: job-id -> grant monotonic of the held slot
        self._holders: dict[int, float] = {}
        #: job-id -> home lease index (static: a job's compiled programs
        #: bind to its lease's devices, so the lease never migrates)
        self._home_lease: dict[int, int] = {}
        self._lease_busy: set[int] = set()
        self._next_lease = 0
        #: job-id -> tokens / last-refill monotonic (lazy refill)
        self._tokens: dict[int, float] = {}
        self._tokens_at: dict[int, float] = {}
        self._throttled: set[int] = set()
        self._arrival = itertools.count()
        #: per-job accounting the bench/tests read: grants, waits, holds,
        #: times this job was passed over by a higher-priority grant
        self.stats: dict[str, dict] = {}
        FLEET_SUBMESHES.set(len(plan) if plan is not None else 0)

    def register(self, job: object, name: str, weight: float = 1.0,
                 priority: int = 0, lease_index: Optional[int] = None) -> None:
        with self._lock:
            jid = id(job)
            self._names[jid] = str(name)
            self._weights[jid] = max(1e-6, float(weight))
            self._priority[jid] = int(priority)
            if self.plan is not None:
                if lease_index is None:
                    lease_index = self._next_lease
                self._home_lease[jid] = int(lease_index) % len(self.plan)
                self._next_lease += 1
            # WFQ catch-up: a late-admitted job starts at the busiest
            # sibling's virtual time instead of replaying the past
            floor = max(self._vtime.values(), default=0.0)
            self._vtime[jid] = max(self._vtime.get(jid, 0.0), floor)
            self.stats.setdefault(self._names[jid], {
                "grants": 0, "preempted": 0, "throttled": 0,
                "wait_s": [], "hold_s": [],
                "weight": self._weights[jid], "priority": self._priority[jid],
            })

    def lease_of(self, job: object):
        """The submesh leased to ``job`` (None without a SubmeshPlan).
        Stable across grants: servers resolve their NamedShardings and AOT
        fingerprints against this once, at build time."""
        if self.plan is None:
            return None
        with self._lock:
            idx = self._home_lease.get(id(job))
        return None if idx is None else self.plan.lease(idx)

    def request(self, job: object, grant_cb: Callable) -> None:
        """Queue ``job`` for the next slot; idempotent per job (a re-request
        before the grant replaces the callback)."""
        with self._lock:
            jid = id(job)
            if jid not in self._names:
                # un-registered single-job use: admit with defaults
                self._register_locked(jid, f"job{jid % 1000}")
            if jid in self._holders:
                # already holding (a re-broadcast inside the same round):
                # run the callback directly on the loop, no second slot
                self.runtime.post(grant_cb)
                return
            prev = self._pending.get(jid)
            self._pending[jid] = (grant_cb, prev[1] if prev else time.monotonic(),
                                  prev[2] if prev else next(self._arrival))
        self._pump()

    def release(self, job: object) -> None:
        """Release ``job``'s held slot/lease (no-op when it holds none) and
        charge the measured hold time to its virtual clock."""
        with self._lock:
            jid = id(job)
            t0 = self._holders.pop(jid, None)
            if t0 is not None:
                hold = time.monotonic() - t0
                self._vtime[jid] = self._vtime.get(jid, 0.0) + hold / self._weights.get(jid, 1.0)
                if self.plan is not None:
                    self._lease_busy.discard(self._home_lease.get(jid, -1))
                name = self._names.get(jid, "?")
                rec = self.stats.setdefault(name, {"grants": 0, "preempted": 0,
                                                   "throttled": 0,
                                                   "wait_s": [], "hold_s": []})
                rec["hold_s"].append(hold)
                SLOT_HOLD.observe(hold, job=name)
        self._pump()

    def _register_locked(self, jid: int, name: str) -> None:  # graftlint: disable=GL004(caller holds _lock)
        self._names[jid] = name
        self._weights[jid] = 1.0
        self._priority[jid] = 0
        if self.plan is not None and jid not in self._home_lease:
            self._home_lease[jid] = self._next_lease % len(self.plan)
            self._next_lease += 1
        self._vtime[jid] = max(self._vtime.values(), default=0.0)
        self.stats.setdefault(name, {"grants": 0, "preempted": 0,
                                     "throttled": 0,
                                     "wait_s": [], "hold_s": []})

    def _refill_locked(self, jid: int, now: float) -> None:  # graftlint: disable=GL004(caller holds _lock: _eligible_locked's lazy refill)
        last = self._tokens_at.get(jid)
        if last is None:
            self._tokens[jid] = self.quota_burst  # a new tenant starts full
        else:
            self._tokens[jid] = min(
                self.quota_burst,
                self._tokens.get(jid, self.quota_burst)
                + (now - last) / self.quota_refill_s)
        self._tokens_at[jid] = now

    def _eligible_locked(self, jid: int, now: float) -> bool:  # graftlint: disable=GL004(caller holds _lock: _pump's admission filter)
        """Quota + lease admission filter; a quota-blocked job is metered
        as throttled ONCE per blocked wait (not once per pump pass)."""
        if self.quota_burst > 0:
            self._refill_locked(jid, now)
            if self._tokens.get(jid, 0.0) < 1.0:
                if jid not in self._throttled:
                    self._throttled.add(jid)
                    name = self._names.get(jid, "?")
                    rec = self.stats.setdefault(
                        name, {"grants": 0, "preempted": 0, "throttled": 0,
                               "wait_s": [], "hold_s": []})
                    rec["throttled"] = rec.get("throttled", 0) + 1
                    QUOTA_THROTTLED.inc(job=name)
                return False
        if self.plan is not None:
            if self._home_lease.get(jid, 0) in self._lease_busy:
                return False
        return True

    def _pump(self) -> None:
        """Grant free slots/leases; callbacks post to the runtime OUTSIDE
        the lock (a grant callback takes its server's _agg_lock — posting
        under _lock would build the scheduler-lock -> agg-lock edge this
        design exists to avoid).  When every pending job is quota-blocked,
        a refill timer re-pumps at the earliest token arrival — throttled
        tenants resume, they never starve."""
        grants: list[Callable] = []
        refill_delay = None
        with self._lock:
            while self._pending and len(self._holders) < self.slots:
                now = time.monotonic()
                eligible = [j for j in self._pending
                            if self._eligible_locked(j, now)]
                if not eligible:
                    if self.quota_burst > 0 and self._pending:
                        refill_delay = self._earliest_refill_locked()
                    break
                chosen = self._pick_locked(eligible)
                cb, enq, _seq = self._pending.pop(chosen)
                now = time.monotonic()
                self._holders[chosen] = now
                if self.quota_burst > 0:
                    self._tokens[chosen] = self._tokens.get(chosen, self.quota_burst) - 1.0
                    self._throttled.discard(chosen)
                name = self._names.get(chosen, "?")
                if self.plan is not None:
                    self._lease_busy.add(self._home_lease.get(chosen, 0))
                    LEASE_GRANTS.inc(job=name)
                rec = self.stats.setdefault(name, {"grants": 0, "preempted": 0,
                                                   "throttled": 0,
                                                   "wait_s": [], "hold_s": []})
                rec["grants"] += 1
                rec["wait_s"].append(now - enq)
                SLOT_GRANTS.inc(job=name)
                SLOT_WAIT.observe(now - enq, job=name)
                grants.append(cb)
        for cb in grants:
            self.runtime.post(cb)
        if refill_delay is not None:
            self.runtime.arm(self, "quota_refill", refill_delay, self._pump)

    def _earliest_refill_locked(self) -> float:  # graftlint: disable=GL004(caller holds _lock: _pump's backoff computation)
        deficits = [max(0.0, 1.0 - self._tokens.get(j, 0.0))
                    for j in self._pending]
        return max(0.001, min(deficits, default=1.0) * self.quota_refill_s)

    def _pick_locked(self, candidates) -> int:  # graftlint: disable=GL004(caller holds _lock: _pump's selection step)
        """Highest priority class, then lowest virtual time, then arrival
        order, over the quota/lease-eligible candidates.  When priority
        overrides fair share, the passed-over job's preemption counter
        ticks — the boundary-preemption meter."""
        def fair_key(jid: int):
            return (self._vtime.get(jid, 0.0), self._pending[jid][2])

        fair = min(candidates, key=fair_key)
        chosen = min(candidates,
                     key=lambda j: (-self._priority.get(j, 0),) + fair_key(j))
        if chosen != fair and self._priority.get(chosen, 0) > self._priority.get(fair, 0):
            name = self._names.get(fair, "?")
            self.stats.setdefault(name, {"grants": 0, "preempted": 0,
                                         "throttled": 0,
                                         "wait_s": [], "hold_s": []})
            self.stats[name]["preempted"] += 1
            PREEMPTIONS.inc(job=name)
        return chosen

    # -- introspection --------------------------------------------------------
    def summary(self) -> dict:
        """Per-job scheduling accounting (grants, p50/p95 wait + hold)."""
        import numpy as np

        with self._lock:
            out = {}
            for name, rec in self.stats.items():
                holds = rec["hold_s"]
                waits = rec["wait_s"]
                out[name] = {
                    "grants": rec["grants"],
                    "preempted": rec["preempted"],
                    "throttled": rec.get("throttled", 0),
                    "weight": rec.get("weight", 1.0),
                    "priority": rec.get("priority", 0),
                    "hold_p50_s": round(float(np.percentile(holds, 50)), 6) if holds else None,
                    "hold_p95_s": round(float(np.percentile(holds, 95)), 6) if holds else None,
                    "wait_p50_s": round(float(np.percentile(waits, 50)), 6) if waits else None,
                    "wait_p95_s": round(float(np.percentile(waits, 95)), 6) if waits else None,
                }
            return out
