"""Buffered-async cross-silo server — FedBuff-style staleness-decayed folds.

The synchronous server (``cross_silo/server.py``) closes a round when every
selected client has replied, so round wall time is the SLOWEST cohort
member's wall time; the PR-4 streaming accumulator overlapped aggregation
with the network tail but kept the barrier.  Production FL traffic is not
round-synchronous (ROADMAP north star; the communication-perspective survey
2405.20431 and the cross-silo backend study 2604.10859 both name
buffered-async aggregation as the straggler-bound -> throughput-bound
lever), so this manager removes the barrier:

- **Clients train continuously.**  Every upload is answered with a fresh
  dispatch of the current global model; a client never waits for a round
  boundary.  ``async_concurrency`` clients (default ``client_num_per_round``)
  are kept in flight; a deterministic round-robin cursor rotates work
  through the rest of the fleet.
- **Every arrival folds immediately** into the streaming accumulator
  (``FedMLAggregator.fold``, the associative-fold protocol) with a
  staleness-decayed weight ``w * s(tau)`` where ``tau = server_version -
  client_version`` (the version the dispatch carried, echoed back in the
  reply's round index) and ``s(tau) = (1 + tau) ** -async_staleness_exponent``
  — FedBuff/FedAsync's polynomial decay.  ``s(0)`` is exactly ``1.0``, so an
  all-fresh run folds bitwise like the synchronous streaming path.
- **A virtual round closes every ``async_buffer_k`` arrivals** (FedBuff's
  K): finalize the accumulator, run the algorithm's server step, bump
  ``server_version``, eval on the configured cadence.
- **The health ledger gates admission.**  Behind
  ``extra.health_aware_selection`` a degraded sender's upload is still
  folded — throttled, never dropped — but its next assignment waits for the
  virtual-round boundary, so a flapping silo cannot monopolize dispatch
  slots while healthy clients starve.
- **A redispatch watchdog bounds lost work**: a dispatch not answered
  within ``async_redispatch_timeout_s`` records a deadline breach against
  that client and re-issues the slot to another one, so injected drops cost
  one timeout, not a stalled buffer.

Gated entirely on ``extra.async_aggregation``: unset, ``build_server``
returns the synchronous manager and this module is never imported — wire
bytes and aggregation results stay bit-identical to the flag-free build.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from ..comm.message import Message
from ..core.flags import cfg_extra
from ..obs import registry as obsreg, trace as obstrace
from ..obs.metrics import MetricsLogger
from . import message_define as md
from .server import (
    AGGREGATE_TIME, BUFFERED_PEAK, CLIENT_ROUND_TRIP, DEDUPED_UPLOADS,
    FedMLAggregator, FedMLServerManager, REJECTED_STALE,
)

log = logging.getLogger("fedml_tpu.cross_silo.async_server")

ARRIVALS = obsreg.REGISTRY.counter(
    "fedml_async_arrivals_total",
    "Uploads received by the buffered-async server, by admission path "
    "(folded = streaming accumulator, buffered = exact-mode dense buffer).",
    labels=("path",),
)
STALENESS = obsreg.REGISTRY.histogram(
    "fedml_async_staleness_versions",
    "Version lag tau of each arrival (server_version minus the version the "
    "client trained against).",
    buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
)
FOLD_LAG = obsreg.REGISTRY.histogram(
    "fedml_async_fold_lag_seconds",
    "First received byte of an upload to its fold into the accumulator — "
    "the head-of-line-blocking quantity chunked transport bounds.",
)
VIRTUAL_ROUNDS = obsreg.REGISTRY.counter(
    "fedml_async_virtual_rounds_total",
    "Virtual rounds closed (one per async_buffer_k folded arrivals).",
)
REDISPATCHES = obsreg.REGISTRY.counter(
    "fedml_async_redispatches_total",
    "Work dispatched after round 0, by trigger (upload = fold-and-refill, "
    "timeout = dispatch deadline expired, round = throttled client released "
    "at the virtual-round boundary).",
    labels=("reason",),
)
THROTTLED = obsreg.REGISTRY.counter(
    "fedml_async_throttled_total",
    "Uploads whose sender was health-throttled: folded, but the next "
    "dispatch deferred to the virtual-round boundary.",
)


def staleness_scale(staleness: int, exponent: float) -> float:
    """Polynomial staleness decay ``s(tau) = (1 + tau) ** -exponent``
    (FedBuff).  ``s(0)`` returns the literal ``1.0`` so a fresh update's
    fold is bitwise identical to the synchronous streaming fold; a zero
    exponent disables the decay entirely."""
    if staleness <= 0 or exponent == 0.0:
        return 1.0
    return float((1.0 + float(staleness)) ** (-float(exponent)))


class AsyncFedMLServerManager(FedMLServerManager):
    """Buffered-async server manager (see module docstring).

    Thread model: the receive loop (folds + re-dispatch), the watchdog
    timer (deadline redispatch), and the caller's thread all touch the fold
    buffer and dispatch ledger — every access runs under ``_agg_lock``.
    """

    #: journal recovery runs at the END of this __init__ (the base-class
    #: recover would fire before the async dispatch ledger exists)
    _journal_recover_deferred = True

    def __init__(self, cfg, aggregator: FedMLAggregator, backend: Optional[str] = None,
                 logger: Optional[MetricsLogger] = None, runtime=None):
        super().__init__(cfg, aggregator, backend=backend, logger=logger,
                         runtime=runtime)
        if self.topology is not None:
            # the async protocol dispatches per client on each fold (no
            # round barrier for an edge to fold against) — hierarchical
            # async needs per-edge virtual rounds, a later scale item
            raise NotImplementedError(
                "hierarchical aggregation (hier_fanout/hier_topology) is "
                "synchronous-only for now; unset it or async_aggregation")
        # re-bound (construction-time, before any receive/timer thread
        # exists) so this class's own body declares the guarded state for
        # the GL004 lock-discipline scan
        self._agg_lock = threading.Lock()
        self.server_version = 0
        self.buffer_k = max(1, int(cfg_extra(cfg, "async_buffer_k")))
        self.staleness_exponent = float(cfg_extra(cfg, "async_staleness_exponent"))
        self.concurrency = max(1, int(
            cfg_extra(cfg, "async_concurrency", None) or self.per_round))
        self.redispatch_timeout = float(cfg_extra(cfg, "async_redispatch_timeout_s"))
        #: cid -> (dispatched_version, monotonic send time) for every
        #: in-flight assignment — the watchdog's scan set
        self._outstanding: dict[int, tuple[int, float]] = {}
        #: health-throttled senders awaiting the next virtual-round boundary
        self._throttled: set[int] = set()
        self._ever_dispatched: set[int] = set()
        self._rr_cursor = 0
        self._arrivals_in_round = 0
        self._round_staleness: list[int] = []
        self._finished = False
        #: gang-gated dispatch (sched/multi_tenant.py): True while this job
        #: holds the mesh slot — new work dispatches only then; arrivals
        #: from the previous wave keep folding regardless.  Always False on
        #: the single-job path (round_gate None short-circuits every check).
        self._has_slot = False
        # soak/bench accounting (all guarded by _agg_lock)
        self.total_arrivals = 0
        self.timeout_redispatches = 0
        self.staleness_sum = 0
        self.staleness_max = 0
        self.first_dispatch_monotonic: Optional[float] = None
        self.finished_monotonic: Optional[float] = None
        # recovery (ISSUE 10): the journaled in-flight table at restart.
        # _recovered_outstanding re-enters _outstanding when dispatching
        # resumes (lost dispatches then re-issue through the existing
        # watchdog); _prev_epoch_inflight is the ACCEPTANCE set for uploads
        # still carrying the pre-crash epoch — a (client, version) pair in it
        # was dispatched but never folded into the journaled state, so
        # folding it once (with corrected staleness) cannot double-count.
        # Anything else from the old epoch is rejected deterministically.
        self._recovered_outstanding: dict[int, int] = {}
        self._prev_epoch_inflight: dict[int, int] = {}
        self._journal_recover()

    # -- protocol ------------------------------------------------------------
    def send_init_msg(self) -> None:
        """All clients online: warm the program store, open the round span,
        dispatch the initial concurrency wave, arm the watchdog.

        A recovered server re-enters here at the journaled version: the
        journaled in-flight table re-arms first (those dispatches were sent
        pre-crash — their uploads may still arrive under the old epoch and
        fold via ``_prev_epoch_inflight``, or never arrive and re-issue
        through the existing redispatch watchdog), then ``_refill`` tops the
        concurrency back up with new-epoch work."""
        with self._agg_lock:
            if self._init_sent:
                return
            self._init_sent = True
            if self.server_version >= self.comm_round:
                # crash landed after the final virtual round's snapshot but
                # before the FINISH broadcast: nothing left to fold
                self._finished = True
                self.finished_monotonic = time.monotonic()
                self.send_finish()
                return
            warm = self.aggregator.warm_programs()
            if warm is not None:
                log.info("async server: program store warm %s", warm)
            # bootstrap publication (ISSUE 11): serving workers come up on
            # the initial (or journal-recovered) global before the first
            # virtual round closes
            self._publish_model()
            self._round_span = obstrace.Span(
                "round", round_idx=self.server_version, async_mode=True)
            self.first_dispatch_monotonic = time.monotonic()
            if self._recovered_outstanding:
                now = time.monotonic()
                for cid, ver in self._recovered_outstanding.items():
                    self._outstanding.setdefault(cid, (ver, now))
                self._recovered_outstanding = {}
            if self.round_gate is None:
                self._refill()
            else:
                self.round_gate.request(self, self._granted_wave)
            self._arm_watchdog()

    def handle_message_receive_model(self, msg: Message) -> None:
        now = time.monotonic()
        with self._agg_lock:
            if self._finished:
                return  # post-finish stragglers: the run is already closed
            sender = int(msg.get_sender_id())
            # exactly-once (ISSUE 13): an idempotence key the server already
            # folded is a redelivery of the same bytes — dropped and counted
            # FIRST, before the epoch fence, because the journaled key table
            # outlives a crash (a pre-crash fold's duplicate still dedups
            # after recovery instead of re-entering the in-flight check)
            upload_key = msg.get_control(md.MSG_ARG_KEY_UPLOAD_KEY)
            if upload_key is not None and self._is_duplicate_upload(sender, upload_key):
                self.deduped_uploads += 1
                DEDUPED_UPLOADS.inc()
                if self.flight is not None:
                    self.flight.note("upload", path="dedup", client=sender,
                                     key=upload_key)
                return
            # control-only reads: a plain get() of a missing key would
            # materialize the tensor section and defeat the streaming fold
            client_version = int(msg.get_control(md.MSG_ARG_KEY_ROUND_INDEX,
                                                 self.server_version))
            if self.journal is not None:
                # session-epoch fence (recovery): an old-epoch upload folds
                # EXACTLY ONCE iff its (client, version) survives in the
                # journaled in-flight table — dispatched pre-crash, never
                # folded into the recovered state; its staleness below is
                # computed against the RECOVERED version (corrected decay).
                # Everything else from the old epoch is rejected: the work it
                # carries is either already in the journal or unattributable.
                epoch = int(msg.get_control(
                    md.MSG_ARG_KEY_SESSION_EPOCH, self.session_epoch))
                if epoch != self.session_epoch:
                    accept = (epoch == self.session_epoch - 1
                              and self._prev_epoch_inflight.get(sender)
                              == client_version)
                    if not accept:
                        self.rejected_stale += 1
                        REJECTED_STALE.inc(reason="epoch")
                        if self.flight is not None:
                            self.flight.note("upload", path="stale",
                                             client=sender, key=upload_key,
                                             upload_epoch=epoch,
                                             epoch=self.session_epoch)
                        return
                    del self._prev_epoch_inflight[sender]
                    if self.flight is not None:
                        # the one-shot prev-epoch refold: pre-crash work
                        # surviving the epoch fence via the in-flight table
                        self.flight.note("upload", path="refold",
                                         client=sender, key=upload_key,
                                         upload_epoch=epoch,
                                         epoch=self.session_epoch)
            staleness = max(0, self.server_version - client_version)
            sent_at = self._sent_at.pop(sender, None)
            if sent_at is not None:
                rtt = time.perf_counter() - sent_at
                CLIENT_ROUND_TRIP.observe(rtt, client=str(sender))
                self.health.observe_rtt(sender, rtt)
                self._round_rtts[sender] = rtt
            self._outstanding.pop(sender, None)
            n_samples = float(msg.get(md.MSG_ARG_KEY_NUM_SAMPLES))
            is_delta = bool(msg.get_control(md.MSG_ARG_KEY_MODEL_IS_DELTA, False))
            self._round_payload_bytes += int(getattr(msg, "wire_nbytes", 0) or 0)
            scale = staleness_scale(staleness, self.staleness_exponent)
            if self.aggregator.fold(sender, msg, n_samples, is_delta, scale=scale):
                ARRIVALS.inc(path="folded")
                if self.flight is not None:
                    self.flight.note("upload", path="fold", client=sender,
                                     key=upload_key, version=client_version,
                                     staleness=int(staleness))
            else:
                # exact-mode fallback (custom aggregate, or a trust pipeline
                # that needs the stacked matrix — attack/defense/LDP; a
                # central-DP-only pipeline STREAMS and lands its noise at
                # each virtual round's finalize, ISSUE 15): the decay rides
                # the weight, so a weight-sensitive aggregate still sees the
                # staleness-discounted contribution
                params = msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)
                self.aggregator.add_local_trained_result(
                    sender, params, n_samples * scale, is_delta=is_delta)
                ARRIVALS.inc(path="buffered")
                if self.flight is not None:
                    self.flight.note("upload", path="buffer", client=sender,
                                     key=upload_key, version=client_version,
                                     staleness=int(staleness))
            self._note_upload_key(sender, upload_key)
            self.total_arrivals += 1
            self._arrivals_in_round += 1
            self._round_staleness.append(int(staleness))
            self.staleness_sum += int(staleness)
            self.staleness_max = max(self.staleness_max, int(staleness))
            STALENESS.observe(float(staleness))
            if msg.recv_monotonic is not None:
                FOLD_LAG.observe(max(0.0, now - msg.recv_monotonic))
            # admission gate: a degraded sender's update was folded, but its
            # next assignment waits for the virtual-round boundary
            throttled = (self.health_aware
                         and self.health.score(sender) < self.health.degraded_threshold)
            if throttled:
                self._throttled.add(sender)
                THROTTLED.inc()
            if self._arrivals_in_round >= self.buffer_k:
                self._close_virtual_round()
            if (not throttled and not self._finished
                    and (self.round_gate is None or self._has_slot)):
                self._dispatch(self._next_client(fallback=sender))
                REDISPATCHES.inc(reason="upload")

    def _close_virtual_round(self) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: receive handler at the K-arrival boundary)
        """Finalize the accumulator, step the server, bump the version."""
        arrivals = self._arrivals_in_round
        with obstrace.traced("aggregate", parent=self._round_span,
                             round_idx=self.server_version,
                             arrivals=arrivals) as agg_span:
            self.aggregator.aggregate(self.server_version)
        AGGREGATE_TIME.observe(agg_span.duration_s)
        BUFFERED_PEAK.set(self.aggregator.peak_buffered_updates)
        VIRTUAL_ROUNDS.inc()
        if self.flight is not None:
            self.flight.note("virtual_round", version=self.server_version,
                             arrivals=arrivals, epoch=self.session_epoch)
        stal = self._round_staleness
        metrics = {
            "round": self.server_version,
            "arrivals": arrivals,
            "staleness_mean": round(float(np.mean(stal)), 4) if stal else 0.0,
            "staleness_max": int(max(stal)) if stal else 0,
        }
        eval_span = None
        if self.cfg.frequency_of_the_test and (
            (self.server_version + 1) % self.cfg.frequency_of_the_test == 0
            or self.server_version == self.comm_round - 1
        ):
            with obstrace.traced("eval", parent=self._round_span,
                                 round_idx=self.server_version) as eval_span:
                metrics.update(self.aggregator.test_on_server())
        self._close_round_trace(agg_span, eval_span)
        self.logger.log(metrics)
        self.history.append(metrics)
        if self.timeline is not None:
            # convergence tee: the async series is keyed by server version
            self.timeline.note_round(server_version=self.server_version,
                                     test_acc=metrics.get("test_acc"))
        self.server_version += 1
        self.round_idx = self.server_version  # keep base-class reporting honest
        self._arrivals_in_round = 0
        self._round_staleness = []
        # virtual-round boundary: the accumulator is freshly reset and the
        # dispatch ledger is consistent — the journal's commit point, and
        # (behind extra.model_publish_dir) the serving fleet's version bump
        self._journal_snapshot()
        self._publish_model()
        if self.server_version >= self.comm_round:
            self._finished = True
            self.finished_monotonic = time.monotonic()
            if self.round_gate is not None and self._has_slot:
                self._has_slot = False
                self.round_gate.release(self)
            self.send_finish()
            return
        self._round_span = obstrace.Span(
            "round", round_idx=self.server_version, async_mode=True)
        if self.round_gate is not None:
            # virtual-round boundary: hand the mesh slot back and get back
            # in line — in-flight uploads keep folding while a sibling
            # tenant holds the mesh, so the network tail still overlaps.
            # (A K-arrival close can land BETWEEN release and the next
            # grant: release is a no-op then and request() replaces the
            # pending callback — the scheduler stays single-entry per job.)
            if self._has_slot:
                self._has_slot = False
                self.round_gate.release(self)
            self.round_gate.request(self, self._granted_wave)
            return
        # throttled clients re-enter on the fresh version (deprioritized,
        # never dropped)
        for cid in sorted(self._throttled):
            self._dispatch(cid)
            REDISPATCHES.inc(reason="round")
        self._throttled.clear()
        self._refill()

    def _granted_wave(self) -> None:
        """Gang-scheduler grant: dispatch this virtual round's wave —
        throttled re-entries first (deprioritized, never dropped), then
        refill to concurrency.  Runs on the control plane's runtime loop."""
        with self._agg_lock:
            if self._finished or self.done.is_set():
                self.round_gate.release(self)
                return
            self._has_slot = True
            for cid in sorted(self._throttled):
                self._dispatch(cid)
                REDISPATCHES.inc(reason="round")
            self._throttled.clear()
            self._refill()

    # -- dispatch ------------------------------------------------------------
    def _dispatch(self, cid: int) -> None:  # graftlint: disable=GL004(caller holds _agg_lock: every dispatch site is a lock-held handler/timer body)
        """Send the current global (stamped with ``server_version``) to one
        client and track the in-flight assignment."""
        first = cid not in self._ever_dispatched
        self._ever_dispatched.add(cid)
        msg = Message(
            md.MSG_TYPE_S2C_INIT_CONFIG if first else md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            0, cid)
        msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, self.aggregator._host_global())
        msg.add_params(md.MSG_ARG_KEY_CLIENT_INDEX, cid - 1)
        msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self.server_version)
        if self.journal is not None:
            # recovery fence: the client echoes this epoch with its upload
            msg.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, self.session_epoch)
        obstrace.inject(msg, self._round_span)
        try:
            self._sent_at[cid] = time.perf_counter()
            self._outstanding[cid] = (self.server_version, time.monotonic())
            if self.flight is not None:
                self.flight.note("dispatch", client=cid,
                                 version=self.server_version,
                                 epoch=self.session_epoch)
            self.send_message(msg)
        except Exception:
            # one unreachable peer must not kill the receive/timer thread;
            # the watchdog refills the slot
            self._outstanding.pop(cid, None)
            self._sent_at.pop(cid, None)
            self.health.record_comm_failure(cid)
            log.warning("async dispatch to client %d failed; slot refills", cid,
                        exc_info=True)

    def _next_client(self, fallback: int) -> int:  # graftlint: disable=GL004(caller holds _agg_lock)
        """Deterministic round-robin over the candidate pool, skipping
        in-flight and throttled clients; degraded ranks (behind
        health_aware_selection) are used only when nothing healthy is idle."""
        pool = self._candidate_ids()
        n = len(pool)
        backup = None
        for _ in range(n):
            cid = pool[self._rr_cursor % n]
            self._rr_cursor += 1
            if cid in self._outstanding or cid in self._throttled:
                continue
            if (self.health_aware
                    and self.health.score(cid) < self.health.degraded_threshold):
                backup = cid if backup is None else backup
                continue
            return cid
        return backup if backup is not None else fallback

    def _refill(self) -> None:  # graftlint: disable=GL004(caller holds _agg_lock)
        """Top the in-flight set back up to ``concurrency``."""
        if self.round_gate is not None and not self._has_slot:
            return  # between release and grant: no new work off-slot
        need = self.concurrency - len(self._outstanding)
        for _ in range(max(0, need)):
            cid = self._next_client(fallback=-1)
            if cid < 0 or cid in self._outstanding:
                return  # pool exhausted (everyone in flight or throttled)
            self._dispatch(cid)

    # -- watchdog ------------------------------------------------------------
    def _arm_watchdog(self) -> None:  # graftlint: disable=GL004(caller holds _agg_lock)
        if self.redispatch_timeout <= 0:
            return
        self._runtime.arm(self, "watchdog",
                          max(0.05, min(1.0, self.redispatch_timeout / 4)),
                          self._on_watchdog)

    def _on_watchdog(self) -> None:
        with self._agg_lock:
            if self._finished or self.done.is_set():
                return
            if self.round_gate is None or self._has_slot:
                # off-slot, overdue dispatches stay TRACKED (the accounting
                # identity counts them in-flight) and re-issue at the next
                # grant instead of dispatching while a sibling holds the mesh
                now = time.monotonic()
                overdue = [cid for cid, (_v, t0) in self._outstanding.items()
                           if now - t0 > self.redispatch_timeout]
                for cid in overdue:
                    self._outstanding.pop(cid, None)
                    self._sent_at.pop(cid, None)
                    # the breach is remembered: behind health_aware_selection
                    # the repeat offender is throttled out of the hot rotation
                    self.health.record_deadline_breach(cid)
                    self.timeout_redispatches += 1
                    REDISPATCHES.inc(reason="timeout")
                    if self.flight is not None:
                        self.flight.note("redispatch", reason="timeout",
                                         client=cid,
                                         version=self.server_version)
                    self._dispatch(self._next_client(fallback=cid))
                self._refill()
            self._arm_watchdog()

    # -- recovery journal ------------------------------------------------------
    def _journal_recover(self) -> None:  # graftlint: disable=GL004(construction-time: runs from __init__ before the receive loop or any timer thread exists)
        """Install the newest intact journal snapshot: server version, model
        + server state, dispatch ledger (in-flight table, round-robin cursor,
        throttle set), streaming partials, staleness cursors, health scores,
        and run accounting — then resume under a bumped session epoch."""
        if self.journal is None:
            return
        snap = self.journal.restore(model_template=self.aggregator.model_state())
        if snap is None:
            return
        p = snap["protocol"]
        self.session_epoch = int(p.get("session_epoch", 0)) + 1
        self.server_version = int(p.get("server_version", 0))
        self.round_idx = self.server_version
        self.recovered_step = int(snap["step"])
        self._rr_cursor = int(p.get("rr_cursor", 0))
        self._ever_dispatched = {int(c) for c in p.get("ever_dispatched", [])}
        self._throttled = {int(c) for c in p.get("throttled", [])}
        self.total_arrivals = int(p.get("total_arrivals", 0))
        self.timeout_redispatches = int(p.get("timeout_redispatches", 0))
        self.rejected_stale = int(p.get("rejected_stale", 0))
        self.staleness_sum = int(p.get("staleness_sum", 0))
        self.staleness_max = int(p.get("staleness_max", 0))
        self._recovered_outstanding = {
            int(c): int(v) for c, v in (p.get("outstanding") or {}).items()}
        self._prev_epoch_inflight = dict(self._recovered_outstanding)
        if snap["model"] is not None:
            self.aggregator.restore_model_state(snap["model"])
        self.aggregator.restore_stream_state(p, snap["arrays"])
        self._restore_folded_keys(p)
        self.health.import_state(p.get("health") or {})
        if self.flight is not None:
            self.flight.note(
                "epoch", event="recovery", step=self.recovered_step,
                version=self.server_version, epoch=self.session_epoch,
                inflight_rearmed=sorted(self._recovered_outstanding))
        log.info("recovered from journal step %d (version %d, session epoch "
                 "%d, %d in-flight re-armed)", self.recovered_step,
                 self.server_version, self.session_epoch,
                 len(self._recovered_outstanding))

    def _journal_protocol_state(self) -> dict:  # graftlint: disable=GL004(caller holds _agg_lock: _journal_snapshot runs at the locked virtual-round boundary)
        return {
            "kind": "async", "session_epoch": self.session_epoch,
            "server_version": self.server_version, "round_idx": self.round_idx,
            "outstanding": {str(c): int(v)
                            for c, (v, _t) in sorted(self._outstanding.items())},
            "throttled": sorted(self._throttled),
            "ever_dispatched": sorted(self._ever_dispatched),
            "rr_cursor": int(self._rr_cursor),
            "total_arrivals": int(self.total_arrivals),
            "timeout_redispatches": int(self.timeout_redispatches),
            "rejected_stale": int(self.rejected_stale),
            "deduped": int(self.deduped_uploads),
            "folded_keys": self._export_folded_keys(),
            "staleness_sum": int(self.staleness_sum),
            "staleness_max": int(self.staleness_max),
            "health": self.health.export_state(),
        }

    # -- teardown ------------------------------------------------------------
    def finish(self) -> None:  # graftlint: disable=GL004(single boolean latch; runs under _agg_lock when reached via send_finish, bare on the timeout path — both orders are safe because _finished only ever flips False->True),GL008(same invariant: taking _agg_lock here would self-deadlock on the send_finish path, and the worst bare-path outcome is one extra watchdog fire that re-checks _finished under the lock and exits)
        self._finished = True
        super().finish()

    def hard_kill(self) -> None:  # graftlint: disable=GL004(crash simulation: deliberately lock-free — a SIGKILL takes no locks either; every surviving thread re-checks state under _agg_lock and exits),GL008(same invariant)
        """Crash simulation for the chaos harness: stop the receive loop and
        watchdog ABRUPTLY — no finish broadcast, no journal write, no
        teardown bookkeeping.  Everything not already committed to the
        journal is lost, exactly like a SIGKILL; only the process (which a
        real SIGKILL would reclaim) stays alive for the test to inspect."""
        if self.flight is not None:
            # the black-box moment (racy reads by design — a real SIGKILL
            # takes no locks either): which dispatches were in flight, and
            # which pre-crash in-flight uploads a successor may still refold
            self.flight.trigger(
                "hard_kill", server_version=self.server_version,
                epoch=self.session_epoch,
                outstanding={str(c): int(v)
                             for c, (v, _t) in list(self._outstanding.items())},
                prev_epoch_inflight={str(c): int(v) for c, v in
                                     list(self._prev_epoch_inflight.items())})
        self._finished = True
        self._runtime.cancel(self)
        self.com_manager.stop_receive_message()

    # -- accounting (soak harness / bench) ------------------------------------
    def async_summary(self) -> dict:
        """Run-level accounting for the soak harness and BENCH json."""
        with self._agg_lock:
            wall = None
            if self.first_dispatch_monotonic is not None:
                end = self.finished_monotonic or time.monotonic()
                wall = max(1e-9, end - self.first_dispatch_monotonic)
            return {
                "server_version": self.server_version,
                "arrivals": self.total_arrivals,
                "buffer_k": self.buffer_k,
                "concurrency": self.concurrency,
                "staleness_mean": round(self.staleness_sum / max(1, self.total_arrivals), 4),
                "staleness_max": self.staleness_max,
                "timeout_redispatches": self.timeout_redispatches,
                "rejected_stale": self.rejected_stale,
                "deduped": self.deduped_uploads,
                "recovered_step": self.recovered_step,
                "session_epoch": self.session_epoch,
                "outstanding_at_end": len(self._outstanding),
                "prev_epoch_inflight_at_end": len(self._prev_epoch_inflight),
                "throttled_at_end": len(self._throttled),
                "wall_s": round(wall, 4) if wall is not None else None,
                "versions_per_sec": (round(self.server_version / wall, 4)
                                     if wall else None),
            }
