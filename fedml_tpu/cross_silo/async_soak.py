"""Buffered-async soak harness: ~10k simulated clients vs ONE real server.

The thing under test is the :class:`~fedml_tpu.cross_silo.async_server.
AsyncFedMLServerManager` — real aggregator, real fold/decay math, real
dispatch ledger, real watchdog, real wire bytes (the in-proc router encodes
every message).  The CLIENT side is simulated: 10k clients as scheduled
events on a latency heap (lognormal skew — a long straggler tail), not 10k
threads, so the harness scales to fleet-sized populations on one box.
Injected upload drops give the redispatch watchdog real work; the summary
accounts for every one (``unaccounted_drops`` must come back 0: a drop
either timed out and was re-issued, or its slot is still tracked
in-flight — nothing silently vanishes).

Shared by ``scripts/soak_async.py`` (CLI), the ``bench.py`` ``async``
section (floor-guarded versions/s), and the ``__graft_entry__``
``async_soak`` dryrun stage (small population, same assertions).
"""

from __future__ import annotations

import dataclasses
import heapq
import queue
import threading
import time
from typing import Optional

import numpy as np


def _percentile_from_hist(hist, q: float, base_counts: Optional[list] = None) -> Optional[float]:
    """Approximate quantile from a registry histogram family (upper bucket
    bound of the bucket where the cumulative count crosses ``q``), optionally
    against a pre-run baseline so in-process reruns measure only themselves."""
    snap = hist._snapshot()
    if not snap["samples"]:
        return None
    counts = list(snap["samples"][0]["counts"])
    if base_counts:
        counts = [c - (base_counts[i] if i < len(base_counts) else 0)
                  for i, c in enumerate(counts)]
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for bound, n in zip(snap["buckets"], counts):
        cum += n
        if cum >= target:
            return float(bound)
    return float(snap["buckets"][-1])


def _hist_counts(hist) -> list:
    snap = hist._snapshot()
    return list(snap["samples"][0]["counts"]) if snap["samples"] else []


class _TaggedQueue:
    """Queue-shaped proxy: every ``put`` lands in the shared fan-in queue
    tagged with the simulated client's rank."""

    __slots__ = ("rid", "shared")

    def __init__(self, rid: int, shared: "queue.Queue"):
        self.rid = rid
        self.shared = shared

    def put(self, item) -> None:
        self.shared.put((self.rid, item))


class _FanInQueues(dict):
    """``InProcRouter.queues`` replacement: rank 0 keeps the server's real
    inbox; every other rank fans into one shared queue the simulated-client
    workers drain — 10k clients without 10k queues or threads."""

    def __init__(self, shared: "queue.Queue", server_inbox: "queue.Queue"):
        super().__init__()
        self[0] = server_inbox
        self._shared = shared

    def __missing__(self, rid: int):
        proxy = _TaggedQueue(rid, self._shared)
        self[rid] = proxy
        return proxy


class _SimulatedFleet:
    """Event-scheduled client population.

    Worker threads drain the fan-in queue: status checks are answered
    immediately; model dispatches either get DROPPED (seeded per-event
    coin — the injected failure) or scheduled on the latency heap.  One
    scheduler thread pops due replies and routes them (the router encodes,
    so replies pay the real wire cost)."""

    def __init__(self, router, md, template_params, *, drop_prob: float,
                 latency_mean_s: float, latency_sigma: float, seed: int,
                 workers: int = 4):
        self.router = router
        self.md = md
        self.template = template_params
        self.drop_prob = float(drop_prob)
        # lognormal(mu, sigma) with mean latency_mean_s: heavy right tail,
        # the realistic straggler skew
        self.mu = float(np.log(max(latency_mean_s, 1e-6)) - 0.5 * latency_sigma ** 2)
        self.sigma = float(latency_sigma)
        self.seed = int(seed)
        self.drops_injected = 0
        self.replies_sent = 0
        self._nonce = 0
        self._lock = threading.Lock()
        self._heap: list = []
        self._cond = threading.Condition(self._lock)
        self._stop = False
        self._threads: list[threading.Thread] = []
        self._workers = workers

    def start(self, shared: "queue.Queue") -> None:
        for i in range(self._workers):
            t = threading.Thread(target=self._worker, args=(shared,),
                                 name=f"soak-client-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._scheduler, name="soak-sched", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self, shared: "queue.Queue") -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for _ in range(self._workers):
            shared.put(None)  # sentinel per worker
        for t in self._threads:
            t.join(timeout=10.0)

    # -- event handling -------------------------------------------------------
    def _worker(self, shared: "queue.Queue") -> None:
        from ..comm.message import Message

        md = self.md
        while True:
            item = shared.get()
            if item is None:
                return
            rid, data = item
            try:
                msg = Message.decode(data)  # control only: tensors stay lazy
            except Exception:
                continue
            mtype = msg.get_type()
            if mtype == md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS:
                reply = Message(md.MSG_TYPE_C2S_CLIENT_STATUS, rid, 0)
                reply.add_params(md.MSG_ARG_KEY_CLIENT_STATUS, md.CLIENT_STATUS_ONLINE)
                reply.add_params(md.MSG_ARG_KEY_CLIENT_OS, md.CLIENT_OS_PYTHON)
                self.router.route(reply)
            elif mtype in (md.MSG_TYPE_S2C_INIT_CONFIG,
                           md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
                version = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX, 0))
                # session epoch (recovery fence): echoed back like a real
                # client — control-only read so 10k simulated clients never
                # pay a tensor decode
                epoch = msg.get_control(md.MSG_ARG_KEY_SESSION_EPOCH)
                with self._lock:
                    self._nonce += 1
                    nonce = self._nonce
                rng = np.random.default_rng([self.seed, rid, nonce])
                if rng.random() < self.drop_prob:
                    with self._lock:
                        self.drops_injected += 1
                    continue  # the upload is lost; the watchdog must recover
                latency = float(rng.lognormal(self.mu, self.sigma))
                with self._cond:
                    heapq.heappush(self._heap,
                                   (time.monotonic() + latency, nonce, rid,
                                    version, epoch))
                    self._cond.notify()
            # FINISH needs no ack in the soak

    def _scheduler(self) -> None:
        while True:
            with self._cond:
                while not self._stop and (
                        not self._heap or self._heap[0][0] > time.monotonic()):
                    wait = (self._heap[0][0] - time.monotonic()) if self._heap else 0.2
                    self._cond.wait(timeout=max(0.001, min(wait, 0.2)))
                if self._stop:
                    return
                _due, nonce, rid, version, epoch = heapq.heappop(self._heap)
            self._send_reply(rid, version, nonce, epoch)

    def _send_reply(self, rid: int, version: int, nonce: int,
                    epoch=None) -> None:
        import jax

        from ..comm.message import Message

        md = self.md
        # a cheap, deterministic "trained" model: the template scaled per
        # (client, nonce) — non-degenerate folds without any jax compute
        f = 1.0 + 1e-3 * ((rid * 31 + nonce) % 97) / 97.0
        params = jax.tree_util.tree_map(
            lambda a: (a * f).astype(a.dtype) if np.asarray(a).dtype.kind == "f" else a,
            self.template)
        reply = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rid, 0)
        reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
        reply.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(16 + (rid % 7) * 8))
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, version)
        if epoch is not None:
            reply.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(epoch))
        try:
            self.router.route(reply)
        except Exception:
            return
        with self._lock:
            self.replies_sent += 1


def _soak_config(run_id: str, n_clients: int, concurrency: int, buffer_k: int,
                 versions: int, staleness_exponent: float,
                 redispatch_timeout_s: float, extra_flags: Optional[dict] = None):
    from fedml_tpu.arguments import Config

    return Config(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=n_clients, client_num_per_round=concurrency,
        comm_round=versions, epochs=1, batch_size=16, learning_rate=0.1,
        partition_method="homo", synthetic_train_size=512,
        synthetic_test_size=64, frequency_of_the_test=0,
        compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
        extra={
            "async_aggregation": True,
            "async_buffer_k": buffer_k,
            "async_staleness_exponent": staleness_exponent,
            "async_concurrency": concurrency,
            "async_redispatch_timeout_s": redispatch_timeout_s,
            **(extra_flags or {}),
        },
    )


def run_soak(n_clients: int = 10000, concurrency: int = 1024, buffer_k: int = 64,
             versions: int = 20, staleness_exponent: float = 0.5,
             drop_prob: float = 0.02, latency_mean_s: float = 0.005,
             latency_sigma: float = 1.0, redispatch_timeout_s: float = 2.0,
             seed: int = 0, workers: int = 4, timeout_s: float = 600.0,
             journal_dir: Optional[str] = None,
             extra_flags: Optional[dict] = None) -> dict:
    """Drive one buffered-async server to ``versions`` virtual rounds under
    ``n_clients`` simulated clients; returns the accounting dict (versions/s,
    staleness stats, fold-lag p50/p95, peak buffered updates, drop/retry
    accounting).  ``journal_dir`` turns on the recovery journal WITHOUT any
    kill — the bench's clean leg uses it so the recovery ratio isolates the
    crash/chaos cost from the journal's per-round snapshot cost.
    ``extra_flags`` merges additional ``cfg.extra`` flags into the server's
    config — the serving bench points ``model_publish_dir`` here so the
    async server publishes versions while a worker fleet serves."""
    import jax

    import fedml_tpu

    from ..comm.inproc import InProcRouter
    from ..data import loader
    from ..models import model_hub
    from . import build_server, message_define as md
    from .async_server import FOLD_LAG, STALENESS

    run_id = f"soak_async_{seed}_{n_clients}_{versions}"
    cfg = _soak_config(run_id, n_clients, concurrency, buffer_k, versions,
                       staleness_exponent, redispatch_timeout_s,
                       extra_flags={
                           **({"server_journal_dir": journal_dir}
                              if journal_dir else {}),
                           **(extra_flags or {}),
                       })
    fedml_tpu.init(cfg)
    # the server only needs the dataset for its eval arrays + sample batch;
    # load it with a small client count so the partitioner never has to
    # split a tiny synthetic set 10000 ways
    ds_cfg = dataclasses.replace(cfg, client_num_in_total=8, client_num_per_round=8)
    ds = loader.load(ds_cfg)
    model = model_hub.create(ds_cfg, ds.class_num)

    InProcRouter.reset(run_id)
    server = build_server(cfg, ds, model, backend="INPROC")
    router = InProcRouter.get(run_id)
    shared: queue.Queue = queue.Queue()
    # swap in the fan-in fabric AFTER the server bound its rank-0 inbox
    router.queues = _FanInQueues(shared, router.queues[0])

    template = jax.device_get(server.aggregator.global_vars)
    fleet = _SimulatedFleet(
        router, md, template, drop_prob=drop_prob,
        latency_mean_s=latency_mean_s, latency_sigma=latency_sigma,
        seed=seed, workers=workers)

    fold_lag_base = _hist_counts(FOLD_LAG)
    stal_base = _hist_counts(STALENESS)
    fleet.start(shared)
    t0 = time.monotonic()
    server.run_in_thread()
    server.start()
    completed = server.done.wait(timeout_s)
    wall_total = time.monotonic() - t0
    summary = server.async_summary()
    peak = int(server.aggregator.peak_buffered_updates)
    server.finish()
    fleet.stop(shared)
    InProcRouter.reset(run_id)
    if not completed:
        raise RuntimeError(
            f"async soak did not reach {versions} versions in {timeout_s}s: "
            f"{summary}, drops_injected={fleet.drops_injected}, "
            f"replies_sent={fleet.replies_sent}")

    drops = fleet.drops_injected
    # every injected drop must be accounted: recovered by a watchdog
    # redispatch, or its slot still tracked in-flight at finish — anything
    # else means the dispatch ledger silently lost work
    unaccounted = max(0, drops - summary["timeout_redispatches"]
                      - summary["outstanding_at_end"])
    stal_counts = [c - (stal_base[i] if i < len(stal_base) else 0)
                   for i, c in enumerate(_hist_counts(STALENESS))]
    return {
        "clients": n_clients,
        "concurrency": summary["concurrency"],
        "buffer_k": summary["buffer_k"],
        "versions": summary["server_version"],
        "arrivals": summary["arrivals"],
        "wall_s": summary["wall_s"],
        "wall_total_s": round(wall_total, 4),
        "versions_per_sec": summary["versions_per_sec"],
        "arrivals_per_sec": (round(summary["arrivals"] / summary["wall_s"], 2)
                             if summary["wall_s"] else None),
        "staleness_mean": summary["staleness_mean"],
        "staleness_max": summary["staleness_max"],
        "staleness_hist_counts": stal_counts,
        "fold_lag_p50_s": _percentile_from_hist(FOLD_LAG, 0.50, fold_lag_base),
        "fold_lag_p95_s": _percentile_from_hist(FOLD_LAG, 0.95, fold_lag_base),
        "peak_buffered_updates": peak,
        "drops_injected": drops,
        "replies_sent": fleet.replies_sent,
        "timeout_redispatches": summary["timeout_redispatches"],
        "outstanding_at_end": summary["outstanding_at_end"],
        "throttled_at_end": summary["throttled_at_end"],
        "unaccounted_drops": unaccounted,
        "comm_pressure": {"drops": server.health.comm_drops,
                          "retries": server.health.comm_retries},
    }


#: default seeded chaos for the kill-and-recover soak: every fault class
#: exercised on the server->client dispatch leg, mild enough that the
#: watchdog keeps the run progressing
DEFAULT_CHAOS_FLAGS = {
    "chaos_drop_prob": 0.02,
    "chaos_corrupt_prob": 0.01,
    "chaos_duplicate_prob": 0.01,
    "chaos_reorder_prob": 0.02,
    "chaos_delay_prob": 0.05,
    "chaos_delay_max_s": 0.002,
}


def run_kill_recover_soak(n_clients: int = 256, concurrency: int = 64,
                          buffer_k: int = 16, versions: int = 8,
                          kill_at_version: Optional[int] = None,
                          staleness_exponent: float = 0.5,
                          drop_prob: float = 0.02,
                          latency_mean_s: float = 0.003,
                          latency_sigma: float = 1.0,
                          redispatch_timeout_s: float = 1.0, seed: int = 0,
                          workers: int = 4, journal_dir: Optional[str] = None,
                          chaos: Optional[dict] = None,
                          timeout_s: float = 300.0) -> dict:
    """Kill-and-recover soak (ISSUE 10): run the buffered-async server under
    seeded chaos with the recovery journal on, HARD-KILL it mid-run (abrupt
    receive-loop/watchdog teardown — the in-process equivalent of SIGKILL:
    nothing past the last journal snapshot survives), restart it against the
    same journal dir, and drive the SAME simulated fleet to completion.

    The returned accounting proves the recovery invariants the dryrun/bench
    assert: the restarted server resumes at the journaled version
    (``recovered_version``, monotone continuity), completes all ``versions``,
    and every silent loss (fleet upload drops + chaos drop/corrupt on the
    dispatch leg) is accounted as a watchdog redispatch, a deterministic
    stale-epoch rejection, a tracked in-flight slot, or a slot that was
    in flight at the kill but past the last snapshot (``unaccounted`` == 0 —
    nothing vanishes without a trail)."""
    import shutil
    import tempfile

    import jax

    import fedml_tpu

    from ..comm.chaos import ChaosCommManager
    from ..comm.inproc import InProcRouter
    from ..data import loader
    from ..models import model_hub
    from . import build_server, message_define as md

    owns_journal = journal_dir is None
    if owns_journal:
        journal_dir = tempfile.mkdtemp(prefix="soak_journal_")
    chaos_flags = dict(DEFAULT_CHAOS_FLAGS if chaos is None else chaos)
    chaos_flags.setdefault("chaos_seed", seed)
    kill_at = kill_at_version if kill_at_version is not None else max(1, versions // 2)

    run_id = f"soak_killrec_{seed}_{n_clients}_{versions}"
    cfg = _soak_config(run_id, n_clients, concurrency, buffer_k, versions,
                       staleness_exponent, redispatch_timeout_s,
                       extra_flags={"server_journal_dir": journal_dir,
                                    **chaos_flags})
    fedml_tpu.init(cfg)
    ds_cfg = dataclasses.replace(cfg, client_num_in_total=8, client_num_per_round=8)
    ds = loader.load(ds_cfg)
    model = model_hub.create(ds_cfg, ds.class_num)

    try:
        InProcRouter.reset(run_id)
        server_a = build_server(cfg, ds, model, backend="INPROC")
        router = InProcRouter.get(run_id)
        shared: queue.Queue = queue.Queue()
        router.queues = _FanInQueues(shared, router.queues[0])

        template = jax.device_get(server_a.aggregator.global_vars)
        fleet = _SimulatedFleet(
            router, md, template, drop_prob=drop_prob,
            latency_mean_s=latency_mean_s, latency_sigma=latency_sigma,
            seed=seed, workers=workers)
        fleet.start(shared)

        t0 = time.monotonic()
        server_a.run_in_thread()
        server_a.start()
        # wait for the kill point (bare version read: an intentionally racy
        # poll — the kill does not need a consistent snapshot, the journal
        # provides one)
        deadline = time.monotonic() + timeout_s
        while server_a.server_version < kill_at:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"kill-recover soak never reached version {kill_at}: "
                    f"{server_a.async_summary()}")
            if server_a.done.is_set():
                break  # tiny runs can finish before the poll sees kill_at
            time.sleep(0.005)
        a_summary = server_a.async_summary()
        a_chaos = (server_a.com_manager.silent_losses()
                   if isinstance(server_a.com_manager, ChaosCommManager) else 0)
        t_kill = time.monotonic()
        server_a.hard_kill()

        # restart against the same journal: the constructor recovers
        server_b = build_server(cfg, ds, model, backend="INPROC")
        recovered_version = server_b.server_version
        recovered_inflight = len(server_b._prev_epoch_inflight)
        # journaled carry-over of the redispatch counter: B resumed it from
        # the snapshot, so B's final value minus this is B's OWN work
        recovered_redisp = server_b.timeout_redispatches
        t_restart = time.monotonic()
        server_b.run_in_thread()
        server_b.start()
        completed = server_b.done.wait(timeout_s)
        t_done = time.monotonic()
        b_summary = server_b.async_summary()
        b_chaos = (server_b.com_manager.silent_losses()
                   if isinstance(server_b.com_manager, ChaosCommManager) else 0)
        peak = max(int(server_a.aggregator.peak_buffered_updates),
                   int(server_b.aggregator.peak_buffered_updates))
        server_b.finish()
        fleet.stop(shared)
        InProcRouter.reset(run_id)
        if not completed:
            raise RuntimeError(
                f"recovered server did not reach {versions} versions in "
                f"{timeout_s}s: {b_summary}, recovered_at={recovered_version}, "
                f"kill_summary={a_summary}")

        # -- the accounting identity ------------------------------------------
        # silent losses: fleet-injected upload drops + chaos drop/corrupt on
        # the dispatch leg (both lifetimes)
        losses = fleet.drops_injected + a_chaos + b_chaos
        # accounted: redispatches observed in BOTH lifetimes (A's kill-time
        # truth + B's post-recovery delta over the journaled carry-over),
        # stale-epoch rejections, still-tracked slots, and slots that were in
        # flight at the kill but newer than the last snapshot (lost with the
        # crash — visible here because the harness read A's table before
        # killing it)
        b_own_redisp = b_summary["timeout_redispatches"] - recovered_redisp
        total_redisp = a_summary["timeout_redispatches"] + b_own_redisp
        accounted = (total_redisp
                     + b_summary["rejected_stale"]
                     + b_summary["outstanding_at_end"]
                     + b_summary["prev_epoch_inflight_at_end"]
                     + max(0, a_summary["outstanding_at_end"] - recovered_inflight))
        unaccounted = max(0, losses - accounted)
        wall = (t_kill - t0) + (t_done - t_restart)
        return {
            "clients": n_clients,
            "concurrency": concurrency,
            "buffer_k": buffer_k,
            "versions": b_summary["server_version"],
            "versions_at_kill": a_summary["server_version"],
            "recovered_version": recovered_version,
            "recovered_inflight": recovered_inflight,
            "session_epoch": b_summary["session_epoch"],
            "monotone": (0 < recovered_version <= a_summary["server_version"]
                         <= b_summary["server_version"]),
            "arrivals": b_summary["arrivals"],
            "wall_s": round(wall, 4),
            "versions_per_sec": round(b_summary["server_version"] / max(wall, 1e-9), 4),
            "fleet_drops_injected": fleet.drops_injected,
            "chaos_silent_losses": a_chaos + b_chaos,
            "timeout_redispatches": total_redisp,
            "rejected_stale": b_summary["rejected_stale"],
            "outstanding_at_end": b_summary["outstanding_at_end"],
            "prev_epoch_inflight_at_end": b_summary["prev_epoch_inflight_at_end"],
            "lost_inflight_at_kill": max(
                0, a_summary["outstanding_at_end"] - recovered_inflight),
            "unaccounted": unaccounted,
            "peak_buffered_updates": peak,
            "journal_dir": journal_dir,
        }
    finally:
        if owns_journal:
            shutil.rmtree(journal_dir, ignore_errors=True)
