"""Buffered-async soak harness: ~10k simulated clients vs ONE real server.

The thing under test is the :class:`~fedml_tpu.cross_silo.async_server.
AsyncFedMLServerManager` — real aggregator, real fold/decay math, real
dispatch ledger, real watchdog, real wire bytes (the in-proc router encodes
every message).  The CLIENT side is simulated: 10k clients as scheduled
events on a latency heap (lognormal skew — a long straggler tail), not 10k
threads, so the harness scales to fleet-sized populations on one box.
Injected upload drops give the redispatch watchdog real work; the summary
accounts for every one (``unaccounted_drops`` must come back 0: a drop
either timed out and was re-issued, or its slot is still tracked
in-flight — nothing silently vanishes).

Shared by ``scripts/soak_async.py`` (CLI), the ``bench.py`` ``async``
section (floor-guarded versions/s), and the ``__graft_entry__``
``async_soak`` dryrun stage (small population, same assertions).
"""

from __future__ import annotations

import contextlib
import dataclasses
import heapq
import os
import queue
import threading
import time
from typing import Optional

import numpy as np

from ..obs import flight as obsflight


def _percentile_from_hist(hist, q: float, base_counts: Optional[list] = None) -> Optional[float]:
    """Approximate quantile from a registry histogram family (upper bucket
    bound of the bucket where the cumulative count crosses ``q``), optionally
    against a pre-run baseline so in-process reruns measure only themselves."""
    snap = hist._snapshot()
    if not snap["samples"]:
        return None
    counts = list(snap["samples"][0]["counts"])
    if base_counts:
        counts = [c - (base_counts[i] if i < len(base_counts) else 0)
                  for i, c in enumerate(counts)]
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for bound, n in zip(snap["buckets"], counts):
        cum += n
        if cum >= target:
            return float(bound)
    return float(snap["buckets"][-1])


def _hist_counts(hist) -> list:
    snap = hist._snapshot()
    return list(snap["samples"][0]["counts"]) if snap["samples"] else []


class _FleetSender:
    """``BaseCommunicationManager``-shaped adapter over the in-proc router,
    so the simulated fleet's UPLOAD leg can ride :class:`~fedml_tpu.comm.
    chaos.ChaosCommManager` like a real client's sends do (ISSUE 13
    satellite: the soak fleet used to bypass the chaos wrapper entirely, so
    drop/duplicate/corrupt never hit uploads).  ``route()`` is called with
    the single positional message argument, exactly the pre-chunk signature,
    so router taps (tests, tooling) that wrap the unchunked fabric keep
    working; ``send_raw`` is the chaos wrapper's corrupt-frame injection
    point."""

    def __init__(self, router):
        self.router = router

    def send_message(self, msg) -> None:
        self.router.route(msg)

    def send_raw(self, receiver_id: int, payload: bytes) -> None:
        self.router.queues[receiver_id].put(payload)

    def add_observer(self, observer) -> None:
        pass

    def remove_observer(self, observer) -> None:
        pass

    def handle_receive_message(self) -> None:
        pass

    def stop_receive_message(self) -> None:
        pass


def _upload_chaos_sender(router, chaos_flags: Optional[dict], seed: int):
    """(sender, chaos_wrapper_or_None) for the fleet's upload leg: flags set
    → the seeded :class:`ChaosCommManager` over the router adapter (its own
    wrapper rank so the schedule is independent of the server's dispatch-leg
    wrapper); unset → the bare adapter, bytes untouched."""
    sender = _FleetSender(router)
    if not chaos_flags:
        return sender, None
    from ..comm.chaos import ChaosCommManager, ChaosConfig

    cfg = ChaosConfig(
        seed=int(chaos_flags.get("chaos_seed", seed)) + 1,
        drop=float(chaos_flags.get("chaos_drop_prob", 0.0)),
        delay=float(chaos_flags.get("chaos_delay_prob", 0.0)),
        delay_max_s=float(chaos_flags.get("chaos_delay_max_s", 0.05)),
        duplicate=float(chaos_flags.get("chaos_duplicate_prob", 0.0)),
        reorder=float(chaos_flags.get("chaos_reorder_prob", 0.0)),
        corrupt=float(chaos_flags.get("chaos_corrupt_prob", 0.0)),
    )
    if not cfg.active():
        return sender, None
    wrapper = ChaosCommManager(sender, cfg, rank=1)
    return wrapper, wrapper


def _note_chaos(flight, mgr, leg: str) -> None:
    """Post-hoc (ISSUE 16): ring a chaos wrapper's injection schedule —
    (fault, target rank, nonce) per event — into a flight recorder, so the
    postmortem can attribute every silent loss to the specific injected
    fault instead of a bulk counter."""
    if flight is None or mgr is None:
        return
    for fault, rid, nonce in list(getattr(mgr, "schedule", ())):
        flight.note("chaos", fault=fault, client=rid, nonce=nonce, leg=leg)


class _TaggedQueue:
    """Queue-shaped proxy: every ``put`` lands in the shared fan-in queue
    tagged with the simulated client's rank."""

    __slots__ = ("rid", "shared")

    def __init__(self, rid: int, shared: "queue.Queue"):
        self.rid = rid
        self.shared = shared

    def put(self, item) -> None:
        self.shared.put((self.rid, item))


class _FanInQueues(dict):
    """``InProcRouter.queues`` replacement: rank 0 keeps the server's real
    inbox; every other rank fans into one shared queue the simulated-client
    workers drain — 10k clients without 10k queues or threads."""

    def __init__(self, shared: "queue.Queue", server_inbox: "queue.Queue"):
        super().__init__()
        self[0] = server_inbox
        self._shared = shared

    def __missing__(self, rid: int):
        proxy = _TaggedQueue(rid, self._shared)
        self[rid] = proxy
        return proxy


class _SimulatedFleet:
    """Event-scheduled client population.

    Worker threads drain the fan-in queue: status checks are answered
    immediately; model dispatches either get DROPPED (seeded per-event
    coin — the injected failure) or scheduled on the latency heap.  One
    scheduler thread pops due replies and routes them (the router encodes,
    so replies pay the real wire cost)."""

    def __init__(self, router, md, template_params, *, drop_prob: float,
                 latency_mean_s: float, latency_sigma: float, seed: int,
                 workers: int = 4, sender=None, upload_keys: bool = False,
                 flight=None):
        self.router = router
        # upload-leg send path (ISSUE 13 satellite): model replies go through
        # ``sender`` — the chaos wrapper when the soak enables upload chaos —
        # while status replies stay on the bare router (a dropped status
        # reply only delays discovery; it must not enter the loss identity)
        self.sender = sender if sender is not None else _FleetSender(router)
        #: stamp idempotence keys on uploads (the kill-recover legs): the
        #: nonce is the per-dispatch ordinal, so a chaos-DUPLICATED frame
        #: reuses its key and the server's dedup reconciles it
        self.upload_keys = bool(upload_keys)
        #: fleet-side flight recorder (ISSUE 16): rings every injected drop
        #: and every reply (with its idempotence key) so the postmortem can
        #: pair the fleet's sends against the server's fold/dedup ledger
        self.flight = flight
        self.md = md
        self.template = template_params
        self.drop_prob = float(drop_prob)
        # lognormal(mu, sigma) with mean latency_mean_s: heavy right tail,
        # the realistic straggler skew
        self.mu = float(np.log(max(latency_mean_s, 1e-6)) - 0.5 * latency_sigma ** 2)
        self.sigma = float(latency_sigma)
        self.seed = int(seed)
        self.drops_injected = 0
        self.replies_sent = 0
        #: the upload-leg ChaosCommManager when attach_sim_fleet wired one
        self.upload_chaos = None
        self._nonce = 0
        self._lock = threading.Lock()
        self._heap: list = []
        self._cond = threading.Condition(self._lock)
        self._stop = False
        self._threads: list[threading.Thread] = []
        self._workers = workers

    def start(self, shared: "queue.Queue") -> None:
        for i in range(self._workers):
            t = threading.Thread(target=self._worker, args=(shared,),
                                 name=f"soak-client-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._scheduler, name="soak-sched", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self, shared: "queue.Queue") -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for _ in range(self._workers):
            shared.put(None)  # sentinel per worker
        for t in self._threads:
            t.join(timeout=10.0)

    # -- event handling -------------------------------------------------------
    def _worker(self, shared: "queue.Queue") -> None:
        from ..comm.message import Message

        md = self.md
        while True:
            item = shared.get()
            if item is None:
                return
            rid, data = item
            try:
                msg = Message.decode(data)  # control only: tensors stay lazy
            except Exception:
                continue
            mtype = msg.get_type()
            if mtype == md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS:
                reply = Message(md.MSG_TYPE_C2S_CLIENT_STATUS, rid, 0)
                reply.add_params(md.MSG_ARG_KEY_CLIENT_STATUS, md.CLIENT_STATUS_ONLINE)
                reply.add_params(md.MSG_ARG_KEY_CLIENT_OS, md.CLIENT_OS_PYTHON)
                self.router.route(reply)
            elif mtype in (md.MSG_TYPE_S2C_INIT_CONFIG,
                           md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
                version = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX, 0))
                # session epoch (recovery fence): echoed back like a real
                # client — control-only read so 10k simulated clients never
                # pay a tensor decode
                epoch = msg.get_control(md.MSG_ARG_KEY_SESSION_EPOCH)
                with self._lock:
                    self._nonce += 1
                    nonce = self._nonce
                rng = np.random.default_rng([self.seed, rid, nonce])
                if rng.random() < self.drop_prob:
                    with self._lock:
                        self.drops_injected += 1
                    if self.flight is not None:
                        self.flight.note(
                            "drop", client=rid, version=version, nonce=nonce,
                            epoch=None if epoch is None else int(epoch))
                    continue  # the upload is lost; the watchdog must recover
                latency = float(rng.lognormal(self.mu, self.sigma))
                with self._cond:
                    heapq.heappush(self._heap,
                                   (time.monotonic() + latency, nonce, rid,
                                    version, epoch))
                    self._cond.notify()
            # FINISH needs no ack in the soak

    def _scheduler(self) -> None:
        while True:
            with self._cond:
                while not self._stop and (
                        not self._heap or self._heap[0][0] > time.monotonic()):
                    wait = (self._heap[0][0] - time.monotonic()) if self._heap else 0.2
                    self._cond.wait(timeout=max(0.001, min(wait, 0.2)))
                if self._stop:
                    return
                _due, nonce, rid, version, epoch = heapq.heappop(self._heap)
            self._send_reply(rid, version, nonce, epoch)

    def _send_reply(self, rid: int, version: int, nonce: int,
                    epoch=None) -> None:
        import jax

        from ..comm.message import Message

        md = self.md
        # a cheap, deterministic "trained" model: the template scaled per
        # (client, nonce) — non-degenerate folds without any jax compute
        f = 1.0 + 1e-3 * ((rid * 31 + nonce) % 97) / 97.0
        params = jax.tree_util.tree_map(
            lambda a: (a * f).astype(a.dtype) if np.asarray(a).dtype.kind == "f" else a,
            self.template)
        reply = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rid, 0)
        reply.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
        reply.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(16 + (rid % 7) * 8))
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, version)
        if epoch is not None:
            reply.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(epoch))
        upload_key = None
        if self.upload_keys:
            upload_key = (
                f"{rid}:{version}:{-1 if epoch is None else int(epoch)}:{nonce}")
            reply.add_params(md.MSG_ARG_KEY_UPLOAD_KEY, upload_key)
        try:
            self.sender.send_message(reply)
        except Exception:
            return
        with self._lock:
            self.replies_sent += 1
        if self.flight is not None:
            self.flight.note("reply", client=rid, version=version,
                             nonce=nonce, key=upload_key,
                             epoch=None if epoch is None else int(epoch))


def attach_sim_fleet(server, *, drop_prob: float = 0.0,
                     latency_mean_s: float = 0.003, latency_sigma: float = 1.0,
                     seed: int = 0, workers: int = 4,
                     upload_chaos: Optional[dict] = None,
                     upload_keys: bool = False, flight=None):
    """Swap an already-built in-proc server's fabric for the fan-in
    simulated fleet and start it; returns ``(fleet, shared_queue)`` —
    ``fleet.stop(shared_queue)`` tears it down.  Shared by :func:`run_soak`
    and the multi-tenant control plane's fleet-scale jobs (ISSUE 14), so
    both drive the identical simulated-client machinery."""
    import jax

    from ..comm.inproc import InProcRouter
    from . import message_define as md

    run_id = str(getattr(server.cfg, "run_id", "0"))
    router = InProcRouter.get(run_id)
    shared: "queue.Queue" = queue.Queue()
    # swap in the fan-in fabric AFTER the server bound its rank-0 inbox
    router.queues = _FanInQueues(shared, router.queues[0])
    template = jax.device_get(server.aggregator.global_vars)
    sender = chaos_wrapper = None
    if upload_chaos:
        sender, chaos_wrapper = _upload_chaos_sender(router, upload_chaos, seed)
    fleet = _SimulatedFleet(
        router, md, template, drop_prob=drop_prob,
        latency_mean_s=latency_mean_s, latency_sigma=latency_sigma,
        seed=seed, workers=workers, sender=sender, upload_keys=upload_keys,
        flight=flight)
    fleet.upload_chaos = chaos_wrapper
    fleet.start(shared)
    return fleet, shared


def _soak_config(run_id: str, n_clients: int, concurrency: int, buffer_k: int,
                 versions: int, staleness_exponent: float,
                 redispatch_timeout_s: float, extra_flags: Optional[dict] = None):
    from fedml_tpu.arguments import Config

    return Config(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=n_clients, client_num_per_round=concurrency,
        comm_round=versions, epochs=1, batch_size=16, learning_rate=0.1,
        partition_method="homo", synthetic_train_size=512,
        synthetic_test_size=64, frequency_of_the_test=0,
        compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
        extra={
            "async_aggregation": True,
            "async_buffer_k": buffer_k,
            "async_staleness_exponent": staleness_exponent,
            "async_concurrency": concurrency,
            "async_redispatch_timeout_s": redispatch_timeout_s,
            **(extra_flags or {}),
        },
    )


def run_soak(n_clients: int = 10000, concurrency: int = 1024, buffer_k: int = 64,
             versions: int = 20, staleness_exponent: float = 0.5,
             drop_prob: float = 0.02, latency_mean_s: float = 0.005,
             latency_sigma: float = 1.0, redispatch_timeout_s: float = 2.0,
             seed: int = 0, workers: int = 4, timeout_s: float = 600.0,
             journal_dir: Optional[str] = None,
             extra_flags: Optional[dict] = None) -> dict:
    """Drive one buffered-async server to ``versions`` virtual rounds under
    ``n_clients`` simulated clients; returns the accounting dict (versions/s,
    staleness stats, fold-lag p50/p95, peak buffered updates, drop/retry
    accounting).  ``journal_dir`` turns on the recovery journal WITHOUT any
    kill — the bench's clean leg uses it so the recovery ratio isolates the
    crash/chaos cost from the journal's per-round snapshot cost.
    ``extra_flags`` merges additional ``cfg.extra`` flags into the server's
    config — the serving bench points ``model_publish_dir`` here so the
    async server publishes versions while a worker fleet serves."""
    import jax

    import fedml_tpu

    from ..comm.inproc import InProcRouter
    from ..data import loader
    from ..models import model_hub
    from . import build_server, message_define as md
    from .async_server import FOLD_LAG, STALENESS

    run_id = f"soak_async_{seed}_{n_clients}_{versions}"
    cfg = _soak_config(run_id, n_clients, concurrency, buffer_k, versions,
                       staleness_exponent, redispatch_timeout_s,
                       extra_flags={
                           **({"server_journal_dir": journal_dir}
                              if journal_dir else {}),
                           **(extra_flags or {}),
                       })
    fedml_tpu.init(cfg)
    # the server only needs the dataset for its eval arrays + sample batch;
    # load it with a small client count so the partitioner never has to
    # split a tiny synthetic set 10000 ways
    ds_cfg = dataclasses.replace(cfg, client_num_in_total=8, client_num_per_round=8)
    ds = loader.load(ds_cfg)
    model = model_hub.create(ds_cfg, ds.class_num)

    InProcRouter.reset(run_id)
    server = build_server(cfg, ds, model, backend="INPROC")
    fold_lag_base = _hist_counts(FOLD_LAG)
    stal_base = _hist_counts(STALENESS)
    # ISSUE 16: the fleet gets its own flight ring (the server built one for
    # itself in its constructor) so drop/reply events land beside the
    # server's upload/dispatch notes in the postmortem timeline
    fleet_flight = obsflight.recorder_from_config(
        cfg, name="fleet", meta={"role": "fleet"})
    fleet, shared = attach_sim_fleet(
        server, drop_prob=drop_prob, latency_mean_s=latency_mean_s,
        latency_sigma=latency_sigma, seed=seed, workers=workers,
        flight=fleet_flight)
    t0 = time.monotonic()
    server.run_in_thread()
    server.start()
    completed = server.done.wait(timeout_s)
    wall_total = time.monotonic() - t0
    summary = server.async_summary()
    peak = int(server.aggregator.peak_buffered_updates)
    server.finish()
    # SLO watchdog verdict (ISSUE 16) — read AFTER finish(): stop() runs the
    # engine's final evaluation pass, so even a sub-tick run evaluates once.
    # None unless extra.slo_specs armed it
    slo_summary = server.slo.summary() if server.slo is not None else None
    fleet.stop(shared)
    InProcRouter.reset(run_id)
    if not completed:
        raise RuntimeError(
            f"async soak did not reach {versions} versions in {timeout_s}s: "
            f"{summary}, drops_injected={fleet.drops_injected}, "
            f"replies_sent={fleet.replies_sent}")

    drops = fleet.drops_injected
    # every injected drop must be accounted: recovered by a watchdog
    # redispatch, or its slot still tracked in-flight at finish — anything
    # else means the dispatch ledger silently lost work
    unaccounted = max(0, drops - summary["timeout_redispatches"]
                      - summary["outstanding_at_end"])
    if fleet_flight is not None:
        reason = "accounting_violation" if unaccounted else "soak_finish"
        fleet_flight.trigger(reason, drops_injected=drops,
                             unaccounted=unaccounted,
                             timeout_redispatches=summary["timeout_redispatches"],
                             outstanding_at_end=summary["outstanding_at_end"])
        fleet_flight.close()
    stal_counts = [c - (stal_base[i] if i < len(stal_base) else 0)
                   for i, c in enumerate(_hist_counts(STALENESS))]
    return {
        "clients": n_clients,
        "concurrency": summary["concurrency"],
        "buffer_k": summary["buffer_k"],
        "versions": summary["server_version"],
        "arrivals": summary["arrivals"],
        "wall_s": summary["wall_s"],
        "wall_total_s": round(wall_total, 4),
        "versions_per_sec": summary["versions_per_sec"],
        "arrivals_per_sec": (round(summary["arrivals"] / summary["wall_s"], 2)
                             if summary["wall_s"] else None),
        "staleness_mean": summary["staleness_mean"],
        "staleness_max": summary["staleness_max"],
        "staleness_hist_counts": stal_counts,
        "fold_lag_p50_s": _percentile_from_hist(FOLD_LAG, 0.50, fold_lag_base),
        "fold_lag_p95_s": _percentile_from_hist(FOLD_LAG, 0.95, fold_lag_base),
        "peak_buffered_updates": peak,
        "drops_injected": drops,
        "replies_sent": fleet.replies_sent,
        "timeout_redispatches": summary["timeout_redispatches"],
        "outstanding_at_end": summary["outstanding_at_end"],
        "throttled_at_end": summary["throttled_at_end"],
        "unaccounted_drops": unaccounted,
        "comm_pressure": {"drops": server.health.comm_drops,
                          "retries": server.health.comm_retries},
        **({"slo": slo_summary} if slo_summary is not None else {}),
    }


#: default seeded chaos for the kill-and-recover soak: every fault class
#: exercised on the server->client dispatch leg, mild enough that the
#: watchdog keeps the run progressing
DEFAULT_CHAOS_FLAGS = {
    "chaos_drop_prob": 0.02,
    "chaos_corrupt_prob": 0.01,
    "chaos_duplicate_prob": 0.01,
    "chaos_reorder_prob": 0.02,
    "chaos_delay_prob": 0.05,
    "chaos_delay_max_s": 0.002,
}


def run_kill_recover_soak(n_clients: int = 256, concurrency: int = 64,
                          buffer_k: int = 16, versions: int = 8,
                          kill_at_version: Optional[int] = None,
                          staleness_exponent: float = 0.5,
                          drop_prob: float = 0.02,
                          latency_mean_s: float = 0.003,
                          latency_sigma: float = 1.0,
                          redispatch_timeout_s: float = 1.0, seed: int = 0,
                          workers: int = 4, journal_dir: Optional[str] = None,
                          chaos: Optional[dict] = None,
                          client_chaos: Optional[dict] = None,
                          extra_flags: Optional[dict] = None,
                          timeout_s: float = 300.0) -> dict:
    """Kill-and-recover soak (ISSUE 10): run the buffered-async server under
    seeded chaos with the recovery journal on, HARD-KILL it mid-run (abrupt
    receive-loop/watchdog teardown — the in-process equivalent of SIGKILL:
    nothing past the last journal snapshot survives), restart it against the
    same journal dir, and drive the SAME simulated fleet to completion.

    The returned accounting proves the recovery invariants the dryrun/bench
    assert: the restarted server resumes at the journaled version
    (``recovered_version``, monotone continuity), completes all ``versions``,
    and every silent loss (fleet upload drops + chaos drop/corrupt on BOTH
    legs — the dispatch leg through the server's wrapper AND the upload leg
    through the fleet's, ISSUE 13 satellite) is accounted as a watchdog
    redispatch, a deterministic stale-epoch rejection, a tracked in-flight
    slot, or a slot that was in flight at the kill but past the last
    snapshot (``unaccounted`` == 0 — nothing vanishes without a trail).
    Chaos-DUPLICATED uploads carry their original's idempotence key and must
    come back as server-side dedups, never as double folds
    (``client_chaos`` defaults to the same fault mix as the dispatch leg;
    pass ``{}`` to disable upload-leg chaos).

    ``extra_flags`` merges additional ``cfg.extra`` flags on top of the
    journal + chaos flags (caller wins) — the flight-recorder dryrun stage
    and the postmortem test pass ``{"flight_recorder": True,
    "flight_dir": ...}`` here so both server lifetimes, the fleet, and the
    chaos schedules leave black-box bundles the ``fedml-tpu obs postmortem``
    CLI can stitch into one causal timeline."""
    import shutil
    import tempfile

    import jax

    import fedml_tpu

    from ..comm.chaos import ChaosCommManager
    from ..comm.inproc import InProcRouter
    from ..data import loader
    from ..models import model_hub
    from . import build_server, message_define as md

    owns_journal = journal_dir is None
    if owns_journal:
        journal_dir = tempfile.mkdtemp(prefix="soak_journal_")
    chaos_flags = dict(DEFAULT_CHAOS_FLAGS if chaos is None else chaos)
    chaos_flags.setdefault("chaos_seed", seed)
    kill_at = kill_at_version if kill_at_version is not None else max(1, versions // 2)

    run_id = f"soak_killrec_{seed}_{n_clients}_{versions}"
    cfg = _soak_config(run_id, n_clients, concurrency, buffer_k, versions,
                       staleness_exponent, redispatch_timeout_s,
                       extra_flags={"server_journal_dir": journal_dir,
                                    **chaos_flags,
                                    **(extra_flags or {})})
    fedml_tpu.init(cfg)
    ds_cfg = dataclasses.replace(cfg, client_num_in_total=8, client_num_per_round=8)
    ds = loader.load(ds_cfg)
    model = model_hub.create(ds_cfg, ds.class_num)

    try:
        InProcRouter.reset(run_id)
        server_a = build_server(cfg, ds, model, backend="INPROC")
        router = InProcRouter.get(run_id)
        shared: queue.Queue = queue.Queue()
        router.queues = _FanInQueues(shared, router.queues[0])

        template = jax.device_get(server_a.aggregator.global_vars)
        # upload-leg chaos (ISSUE 13 satellite): the fleet's model replies go
        # through their own seeded ChaosCommManager, so drop/duplicate/
        # corrupt hit uploads exactly like they hit dispatches
        upload_flags = dict(chaos_flags if client_chaos is None else client_chaos)
        sender, upload_chaos = _upload_chaos_sender(router, upload_flags, seed)
        fleet_flight = obsflight.recorder_from_config(
            cfg, name="fleet", meta={"role": "fleet"})
        fleet = _SimulatedFleet(
            router, md, template, drop_prob=drop_prob,
            latency_mean_s=latency_mean_s, latency_sigma=latency_sigma,
            seed=seed, workers=workers, sender=sender, upload_keys=True,
            flight=fleet_flight)
        fleet.start(shared)

        t0 = time.monotonic()
        server_a.run_in_thread()
        server_a.start()
        # wait for the kill point (bare version read: an intentionally racy
        # poll — the kill does not need a consistent snapshot, the journal
        # provides one)
        deadline = time.monotonic() + timeout_s
        while server_a.server_version < kill_at:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"kill-recover soak never reached version {kill_at}: "
                    f"{server_a.async_summary()}")
            if server_a.done.is_set():
                break  # tiny runs can finish before the poll sees kill_at
            time.sleep(0.005)
        a_summary = server_a.async_summary()
        a_chaos = (server_a.com_manager.silent_losses()
                   if isinstance(server_a.com_manager, ChaosCommManager) else 0)
        t_kill = time.monotonic()
        server_a.hard_kill()

        # restart against the same journal: the constructor recovers
        server_b = build_server(cfg, ds, model, backend="INPROC")
        recovered_version = server_b.server_version
        recovered_inflight = len(server_b._prev_epoch_inflight)
        # journaled carry-over of the redispatch counter: B resumed it from
        # the snapshot, so B's final value minus this is B's OWN work
        recovered_redisp = server_b.timeout_redispatches
        t_restart = time.monotonic()
        server_b.run_in_thread()
        server_b.start()
        completed = server_b.done.wait(timeout_s)
        t_done = time.monotonic()
        b_summary = server_b.async_summary()
        b_chaos = (server_b.com_manager.silent_losses()
                   if isinstance(server_b.com_manager, ChaosCommManager) else 0)
        peak = max(int(server_a.aggregator.peak_buffered_updates),
                   int(server_b.aggregator.peak_buffered_updates))
        server_b.finish()
        fleet.stop(shared)
        InProcRouter.reset(run_id)
        if not completed:
            raise RuntimeError(
                f"recovered server did not reach {versions} versions in "
                f"{timeout_s}s: {b_summary}, recovered_at={recovered_version}, "
                f"kill_summary={a_summary}")

        # -- the accounting identity ------------------------------------------
        # silent losses: fleet-injected upload drops + chaos drop/corrupt on
        # the dispatch leg (both lifetimes) + chaos drop/corrupt on the
        # UPLOAD leg (the fleet's wrapper, one lifetime spanning the kill) —
        # a lost upload and a lost dispatch look identical to the server (an
        # unanswered slot), so one identity covers both legs
        upload_losses = upload_chaos.silent_losses() if upload_chaos else 0
        upload_dups = (upload_chaos.injected.get("duplicate", 0)
                       if upload_chaos else 0)
        losses = fleet.drops_injected + a_chaos + b_chaos + upload_losses
        # accounted: redispatches observed in BOTH lifetimes (A's kill-time
        # truth + B's post-recovery delta over the journaled carry-over),
        # stale-epoch rejections, still-tracked slots, and slots that were in
        # flight at the kill but newer than the last snapshot (lost with the
        # crash — visible here because the harness read A's table before
        # killing it)
        b_own_redisp = b_summary["timeout_redispatches"] - recovered_redisp
        total_redisp = a_summary["timeout_redispatches"] + b_own_redisp
        accounted = (total_redisp
                     + b_summary["rejected_stale"]
                     + b_summary["outstanding_at_end"]
                     + b_summary["prev_epoch_inflight_at_end"]
                     + max(0, a_summary["outstanding_at_end"] - recovered_inflight))
        unaccounted = max(0, losses - accounted)
        wall = (t_kill - t0) + (t_done - t_restart)
        if fleet_flight is not None:
            # post-hoc chaos attribution: every injected fault — dispatch leg
            # through both server lifetimes' wrappers, upload leg through the
            # fleet's — becomes a ring event the postmortem can match to a
            # specific lost/deduped upload by (client, nonce)
            _note_chaos(fleet_flight,
                        server_a.com_manager if isinstance(
                            server_a.com_manager, ChaosCommManager) else None,
                        "dispatch")
            _note_chaos(fleet_flight,
                        server_b.com_manager if isinstance(
                            server_b.com_manager, ChaosCommManager) else None,
                        "dispatch")
            _note_chaos(fleet_flight, upload_chaos, "upload")
            reason = "accounting_violation" if unaccounted else "soak_finish"
            fleet_flight.trigger(
                reason, unaccounted=unaccounted, losses=losses,
                accounted=accounted, fleet_drops=fleet.drops_injected,
                dispatch_chaos=a_chaos + b_chaos, upload_chaos=upload_losses,
                timeout_redispatches=total_redisp,
                rejected_stale=b_summary["rejected_stale"],
                deduped=b_summary["deduped"],
                recovered_version=recovered_version)
            fleet_flight.close()
        return {
            "clients": n_clients,
            "concurrency": concurrency,
            "buffer_k": buffer_k,
            "versions": b_summary["server_version"],
            "versions_at_kill": a_summary["server_version"],
            "recovered_version": recovered_version,
            "recovered_inflight": recovered_inflight,
            "session_epoch": b_summary["session_epoch"],
            "monotone": (0 < recovered_version <= a_summary["server_version"]
                         <= b_summary["server_version"]),
            "arrivals": b_summary["arrivals"],
            "wall_s": round(wall, 4),
            "versions_per_sec": round(b_summary["server_version"] / max(wall, 1e-9), 4),
            "fleet_drops_injected": fleet.drops_injected,
            "chaos_silent_losses": a_chaos + b_chaos,
            "upload_chaos_losses": upload_losses,
            "upload_duplicates_injected": upload_dups,
            "deduped": b_summary["deduped"],
            "timeout_redispatches": total_redisp,
            "rejected_stale": b_summary["rejected_stale"],
            "outstanding_at_end": b_summary["outstanding_at_end"],
            "prev_epoch_inflight_at_end": b_summary["prev_epoch_inflight_at_end"],
            "lost_inflight_at_kill": max(
                0, a_summary["outstanding_at_end"] - recovered_inflight),
            "unaccounted": unaccounted,
            "peak_buffered_updates": peak,
            "journal_dir": journal_dir,
        }
    finally:
        if owns_journal:
            shutil.rmtree(journal_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# hierarchical edge-node survivability (ISSUE 17)
# ---------------------------------------------------------------------------

def run_edge_kill_soak(n_clients: int = 4, fanout: int = 2, rounds: int = 2,
                       kill: Optional[tuple] = (0, 0, 1), seed: int = 0,
                       hop_codec: Optional[str] = None,
                       codec: Optional[str] = None,
                       topology: Optional[dict] = None,
                       timeout_s: float = 120.0,
                       extra_flags: Optional[dict] = None) -> dict:
    """Edge-node SIGKILL soak over the SYNCHRONOUS hierarchical tree
    (ISSUE 17): real root + real :class:`~fedml_tpu.cross_silo.edge.
    EdgeAggregatorManager` nodes on the in-proc fabric, clients simulated by
    THIS harness so every arrival is sequenced deterministically — uploads
    go in sorted order, one edge's subtree at a time, and the harness waits
    for each fold before the next send.  Determinism is what upgrades the
    ISSUE's acceptance from "close" to BITWISE: the clean leg and the kill
    leg run the identical fold op sequence, so the final globals must match
    bit for bit (raw hop; ``hop_codec`` trades that pin for the bytes win).

    ``kill = (round, edge_ordinal, after_children)`` hard-kills that edge
    mid-round once ``after_children`` of its children have folded (each fold
    is journaled under ``<journal>/edge_<rank>`` before the kill lands — the
    per-fold cadence, same discipline as the root's mid-round snapshots),
    rebuilds the manager against the same journal and the SAME router queue
    (uploads sent while dead stay queued), re-sends the pre-kill uploads
    under their original idempotence keys, and drives the run out.  The
    accounting identity must close: every upload the harness ever sent is a
    fold, a dedup, or a relay at exactly one edge across both manager
    lifetimes — ``unaccounted == 0``, nothing vanishes with the crash.
    ``kill=None`` is the clean leg.

    ``fanout=0`` (and no ``topology``) runs the FLAT protocol under the
    same deterministic sequencing — the reference leg for the root-ingress
    bytes comparison and for the protocol-level bitwise pin (a prefix-edge
    ``topology`` like ``{"edges": [[1, 2], [3], [4]]}`` folds the identical
    op sequence the flat leg does, so their finals must match bit for
    bit).  ``topology`` is an explicit ``extra.hier_topology`` dict."""
    import shutil
    import tempfile

    import jax

    import fedml_tpu

    from ..comm.inproc import InProcRouter
    from ..comm.message import Message
    from ..data import loader
    from ..models import model_hub
    from . import build_server, message_define as md
    from .edge import EdgeAggregatorManager, build_topology

    workdir = tempfile.mkdtemp(prefix="soak_edgekill_")
    shape = "flat" if (fanout <= 0 and not topology) else (
        "topo" if topology else f"f{fanout}")
    run_id = (f"soak_edgekill_{seed}_{n_clients}_{rounds}_{shape}_"
              f"{'clean' if kill is None else 'kill'}")
    from fedml_tpu.arguments import Config

    hier_extra = ({"hier_topology": topology} if topology
                  else {"hier_fanout": fanout} if fanout > 0 else {})
    cfg = Config(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=n_clients, client_num_per_round=n_clients,
        comm_round=rounds, epochs=1, batch_size=16, learning_rate=0.1,
        partition_method="homo", synthetic_train_size=64 * n_clients,
        synthetic_test_size=64, frequency_of_the_test=0,
        compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
        random_seed=seed,
        extra={"streaming_aggregation": True,
               "server_journal_dir": f"{workdir}/journal", **hier_extra,
               **({"hier_hop_codec": hop_codec} if hop_codec else {}),
               # caller overrides last (flight_recorder, perf_timeline, ...);
               # point any output dirs OUTSIDE the soak's workdir — it is
               # rmtree'd on the way out
               **(extra_flags or {})},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    topo = build_topology(cfg)

    try:
        InProcRouter.reset(run_id)
        router = InProcRouter.get(run_id)
        agg_ranks = [] if topo is None else topo.aggregator_ranks
        edges = {r: EdgeAggregatorManager(cfg, topo, rank=r, backend="INPROC")
                 for r in agg_ranks}
        for e in edges.values():
            e.run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC")
        template = jax.device_get(server.aggregator.global_vars)

        # client ranks fan into one harness queue; root + edge inboxes stay
        # real (their queue objects are copied into the FanIn dict)
        shared: queue.Queue = queue.Queue()
        fan = _FanInQueues(shared, router.queues[0])
        for r in agg_ranks:
            fan[r] = router.queues[r]
        router.queues = fan

        # worker: answer status probes, record model dispatches per client
        dispatches: dict[tuple, object] = {}
        cond = threading.Condition()
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                try:
                    item = shared.get(timeout=0.05)
                except queue.Empty:
                    continue
                if item is None:
                    return
                rid, data = item
                try:
                    msg = Message.decode(data)  # control only, tensors lazy
                except Exception:
                    continue
                mtype = msg.get_type()
                if mtype == md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS:
                    reply = Message(md.MSG_TYPE_C2S_CLIENT_STATUS, rid, 0)
                    reply.add_params(md.MSG_ARG_KEY_CLIENT_STATUS,
                                     md.CLIENT_STATUS_ONLINE)
                    reply.add_params(md.MSG_ARG_KEY_CLIENT_OS,
                                     md.CLIENT_OS_PYTHON)
                    router.route(reply)
                elif mtype in (md.MSG_TYPE_S2C_INIT_CONFIG,
                               md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
                    r = int(msg.get_control(md.MSG_ARG_KEY_ROUND_INDEX, -1))
                    epoch = msg.get_control(md.MSG_ARG_KEY_SESSION_EPOCH)
                    with cond:
                        dispatches[(rid, r)] = epoch
                        cond.notify_all()
                # FINISH needs no ack

        wt = threading.Thread(target=worker, name="edge-soak-clients",
                              daemon=True)
        wt.start()
        deadline = time.monotonic() + timeout_s

        def wait_for(pred, what: str):
            while not pred():
                if time.monotonic() > deadline:
                    raise RuntimeError(f"edge kill soak stalled waiting for "
                                       f"{what} (run_id={run_id})")
                time.sleep(0.002)

        def upload_for(rid: int, round_idx: int, epoch) -> Message:
            f = 1.0 + 1e-3 * ((rid * 31 + round_idx * 7) % 97) / 97.0
            params = jax.tree_util.tree_map(
                lambda a: ((a * f).astype(a.dtype)
                           if np.asarray(a).dtype.kind == "f" else a),
                template)
            if codec is not None:
                # ``codec`` puts the CLIENT hop on the compressed wire too,
                # so flat-vs-tree root-ingress comparisons are codec-fair
                # (deterministic per-(client, round) quantization key)
                from ..comm import codecs as codecs_mod

                params, _res, _stats = codecs_mod.compress_pytree(
                    params, codec,
                    key=jax.random.fold_in(
                        jax.random.PRNGKey(seed), rid * 1009 + round_idx),
                    min_elems=codecs_mod.LOW_RANK_MIN_COMPRESS_ELEMS)
            up = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rid,
                         0 if topo is None else topo.parent(rid))
            up.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
            up.add_params(md.MSG_ARG_KEY_NUM_SAMPLES,
                          float(16 + (rid % 7) * 8))
            up.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            if epoch is not None:
                up.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(epoch))
            # a stable per-(client, round) idempotence key, so the post-kill
            # re-send of already-folded work MUST reconcile as a dedup
            up.add_params(md.MSG_ARG_KEY_UPLOAD_KEY, f"{rid}:{round_idx}:h:0")
            return up

        uploads_sent = 0
        edge_kills = 0
        t0 = time.monotonic()
        server.run_in_thread()
        server.start()
        if topo is None:
            if kill is not None:
                raise ValueError("kill injection needs a tree (fanout >= 1)")
            groups = [(None, list(range(1, n_clients + 1)))]
        else:
            groups = [(r, sorted(topo.children_of[r])) for r in topo.edge_ranks]
        for round_idx in range(rounds):
            for ordinal, (erank, children) in enumerate(groups):
                kill_here = (erank is not None and kill is not None
                             and kill[0] == round_idx and kill[1] == ordinal)
                sent_this_edge: list[Message] = []
                for k, rid in enumerate(children):
                    if kill_here and k == kill[2]:
                        # SIGKILL the edge mid-round: receive loop and
                        # timers stop abruptly, nothing is shipped
                        edges[erank].hard_kill()
                        edge_kills += 1
                        time.sleep(0.15)  # let the dead loop's poll expire
                        replacement = EdgeAggregatorManager(
                            cfg, topo, rank=erank, backend="INPROC")
                        edges[erank] = replacement
                        replacement.run_in_thread()
                        replacement.recovery_resume()
                        # re-send everything already folded, under the
                        # original keys: journaled dedup must swallow all
                        for prev in sent_this_edge:
                            router.route(prev)
                            uploads_sent += 1
                        base_d = replacement.deduped_uploads
                        wait_for(lambda: edges[erank].deduped_uploads
                                 >= base_d + len(sent_this_edge),
                                 f"dedup of re-sent uploads at edge {erank}")
                    with cond:
                        while (rid, round_idx) not in dispatches:
                            if not cond.wait(timeout=0.1) and \
                                    time.monotonic() > deadline:
                                raise RuntimeError(
                                    f"no dispatch for client {rid} round "
                                    f"{round_idx} (run_id={run_id})")
                        epoch = dispatches[(rid, round_idx)]
                    up = upload_for(rid, round_idx, epoch)
                    # baseline BEFORE routing: the fold can land between the
                    # route and a post-route read, and the wait would hang
                    base_f = (0 if erank is None
                              else edges[erank].folds + edges[erank].relays)
                    router.route(up)
                    uploads_sent += 1
                    sent_this_edge.append(up)
                    if erank is None:
                        # flat leg: pace on the root's own fold ledger (the
                        # flags clear at the round boundary, hence the OR)
                        wait_for(lambda: rid in server.aggregator
                                 .flag_client_model_uploaded
                                 or server.round_idx > round_idx
                                 or server.done.is_set(),
                                 f"root fold of client {rid}")
                    else:
                        wait_for(lambda: edges[erank].folds
                                 + edges[erank].relays >= base_f + 1,
                                 f"fold of client {rid} at edge {erank}")
                # serialize the root's partial folds: edge ordinal order in
                # BOTH legs, so the clean and kill runs are op-identical.
                # The last edge of a round completes it and CLEARS the
                # upload flags, so round_idx advancing also satisfies this.
                wait_for(lambda: all(
                    c in server.aggregator.flag_client_model_uploaded
                    for c in children) or server.round_idx > round_idx
                    or server.done.is_set(),
                    f"root accounting of edge {erank} round {round_idx}")
        completed = server.done.wait(
            max(0.1, deadline - time.monotonic()))
        wall = time.monotonic() - t0
        stop.set()
        shared.put(None)
        wt.join(timeout=5.0)
        peak_root = int(server.aggregator.peak_buffered_updates)
        peak_edges = max(
            (e._fold.peak_buffered for e in edges.values()
             if e._fold is not None), default=0)
        folds = sum(e.folds for e in edges.values())
        relays = sum(e.relays for e in edges.values())
        dedups = sum(e.deduped_uploads for e in edges.values())
        global_leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(
            jax.device_get(server.aggregator.global_vars))]
        server.finish()
        for e in edges.values():
            e.finish()
        InProcRouter.reset(run_id)
        if not completed:
            raise RuntimeError(
                f"edge kill soak did not finish {rounds} rounds in "
                f"{timeout_s}s (folds={folds}, dedups={dedups})")
        return {
            "clients": n_clients,
            "fanout": fanout,
            "rounds": rounds,
            "edges": 0 if topo is None else len(topo.edge_ranks),
            "edge_kills": edge_kills,
            "uploads_sent": uploads_sent,
            "edge_folds": folds,
            "edge_relays": relays,
            "edge_dedups": dedups,
            # zero-unaccounted-loss: every upload ever sent is a fold, a
            # dedup, or a relay at exactly one edge, across both lifetimes
            # (flat leg: uploads bypass the edge tier, identity is vacuous)
            "unaccounted": (0 if topo is None
                            else uploads_sent - folds - relays - dedups),
            "partials_sent": sum(e.partials_sent for e in edges.values()),
            "root_ingress_bytes": int(server.upload_ingress_bytes),
            "root_deduped": int(server.deduped_uploads),
            "peak_buffered_root": peak_root,
            "peak_buffered_edge": peak_edges,
            "wall_s": round(wall, 4),
            "global_leaves": global_leaves,
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# client-side survivability harnesses (ISSUE 13)
# ---------------------------------------------------------------------------

def run_client_kill_soak(n_clients: int = 6, versions: int = 6,
                         buffer_k: int = 3, concurrency: int = 3,
                         kill_marks: tuple = ((2, 1), (4, 2)),
                         codec: Optional[str] = "topk",
                         redispatch_timeout_s: float = 1.0, seed: int = 0,
                         timeout_s: float = 240.0) -> dict:
    """REAL in-proc clients under the buffered-async server, with seeded
    client kills mid-run (ISSUE 13): each ``(rank, at_version)`` in
    ``kill_marks`` hard-kills that client manager the first time the server
    version reaches the mark, then rebuilds it against the same client
    journal — the replacement resumes mid-conversation (EF residuals,
    epoch, attempt counters) and the run is driven to completion.

    The client-side accounting identity: every kill comes back as exactly
    one restart, and every restart is either a journal resume or a cold
    rejoin (``unaccounted`` = kills − resumed − cold == 0); any duplicate
    upload a crashed client re-sent is visible as a server-side dedup, never
    a double fold.  ``kill_marks=()`` is the clean leg the bench ratio
    divides by (same real-client shape, zero kills)."""
    import shutil
    import tempfile

    import fedml_tpu

    from ..comm.inproc import InProcRouter
    from ..data import loader
    from ..models import model_hub
    from . import build_client, build_server

    workdir = tempfile.mkdtemp(prefix="soak_clientkill_")
    run_id = f"soak_clientkill_{seed}_{n_clients}_{versions}_{len(kill_marks)}"
    try:
        cfg = _soak_config(
            run_id, n_clients, concurrency, buffer_k, versions,
            staleness_exponent=0.5,
            redispatch_timeout_s=redispatch_timeout_s,
            extra_flags={
                "server_journal_dir": f"{workdir}/server_journal",
                "client_journal_dir": f"{workdir}/client_journal",
                # lr-model leaves are small; lower the floor so the topk/qsgd8
                # EF contract is actually exercised across the kills
                **({"comm_compression": codec,
                    "comm_compress_min_size": 64} if codec else {}),
            })
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)

        InProcRouter.reset(run_id)
        clients = {r: build_client(cfg, ds, model, rank=r, backend="INPROC")
                   for r in range(1, n_clients + 1)}
        for c in clients.values():
            c.run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC")
        t0 = time.monotonic()
        server.run_in_thread()
        server.start()

        pending = sorted(kill_marks, key=lambda m: m[1])
        kills = resumed = cold = 0
        deadline = time.monotonic() + timeout_s
        while not server.done.wait(0.002):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"client-kill soak did not reach {versions} versions in "
                    f"{timeout_s}s: {server.async_summary()}")
            # bare version read: an intentionally racy poll, same discipline
            # as the server-kill soak — the journals provide the consistency
            while pending and server.server_version >= pending[0][1]:
                rank, _mark = pending.pop(0)
                clients[rank].hard_kill()
                kills += 1
                time.sleep(0.05)  # let the dead receive loop drain out
                replacement = build_client(cfg, ds, model, rank=rank,
                                           backend="INPROC")
                if replacement.resumed_from_journal:
                    resumed += 1
                else:
                    cold += 1
                replacement.run_in_thread()
                clients[rank] = replacement
        wall = time.monotonic() - t0
        summary = server.async_summary()
        peak = int(server.aggregator.peak_buffered_updates)
        server.finish()
        for c in clients.values():
            c.done.wait(5.0)
        finished = sum(1 for c in clients.values() if c.done.is_set())
        for c in clients.values():
            c.finish()
        InProcRouter.reset(run_id)
        return {
            "clients": n_clients,
            "versions": summary["server_version"],
            "wall_s": round(wall, 4),
            "versions_per_sec": round(summary["server_version"] / max(wall, 1e-9), 4),
            "arrivals": summary["arrivals"],
            "kills": kills,
            "resumed_from_journal": resumed,
            "cold_rejoins": cold,
            "unaccounted": kills - resumed - cold,
            "deduped": summary["deduped"],
            "rejected_stale": summary["rejected_stale"],
            "timeout_redispatches": summary["timeout_redispatches"],
            "peak_buffered_updates": peak,
            "clients_finished": finished,
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def run_client_crash_parity(codec: str = "topk", rounds: int = 3,
                            kill_before_round: int = 2,
                            seed: int = 0) -> dict:
    """EF-residual durability proof (ISSUE 13 acceptance): the same 1-client
    compressed run twice — REFERENCE (never crashed) and CRASHED (the client
    hard-killed just before receiving ``kill_before_round``'s dispatch, then
    rebuilt from its journal mid-run).  One client makes every fold order
    deterministic, so the comparison is BITWISE: the resumed client must
    carry the exact error-feedback residuals (topk) / produce the exact
    stochastic-rounding stream (qsgd8) of its uncrashed twin, and the final
    global models must match bit for bit.

    The kill is injected at the router (single-arg ``route()`` tap, the
    fabric's documented tap shape): the dispatch that would start
    ``kill_before_round`` is held, the client killed, a replacement built
    against the same journal, and the held dispatch delivered to it —
    deterministic, no polling race."""
    import shutil
    import tempfile

    import jax

    import fedml_tpu

    from ..comm.inproc import InProcRouter
    from ..data import loader
    from ..models import model_hub
    from . import build_client, build_server, message_define as md

    workdir = tempfile.mkdtemp(prefix="soak_parity_")

    def _cfg(run_id, extra):
        from fedml_tpu.arguments import Config

        return Config(
            training_type="cross_silo", dataset="synthetic", model="lr",
            client_num_in_total=1, client_num_per_round=1, comm_round=rounds,
            epochs=1, batch_size=16, learning_rate=0.1,
            partition_method="homo", synthetic_train_size=64,
            synthetic_test_size=64, frequency_of_the_test=0,
            compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
            random_seed=seed,
            extra={"comm_compression": codec, "comm_compress_min_size": 64,
                   **extra},
        )

    def _run(run_id, extra, tap_factory=None):
        cfg = _cfg(run_id, extra)
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        InProcRouter.reset(run_id)
        router = InProcRouter.get(run_id)
        holder = {"client": build_client(cfg, ds, model, rank=1,
                                         backend="INPROC")}
        if tap_factory is not None:
            router.route = tap_factory(router, router.route, cfg, ds, model,
                                       holder)
        holder["thread"] = holder["client"].run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC")
        try:
            server.run_until_done(timeout=120.0)
            holder["client"].done.wait(5.0)
        finally:
            holder["client"].finish()
        InProcRouter.reset(run_id)
        return server, holder["client"]

    try:
        ref_server, ref_client = _run(f"parity_ref_{codec}_{seed}", {})

        swapped = {"n": 0}

        def tap_factory(router, orig_route, cfg, ds, model, holder):
            def tap(msg):
                if (msg.get_type() == md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT
                        and int(msg.get_control(md.MSG_ARG_KEY_ROUND_INDEX, -1))
                        == kill_before_round
                        and swapped["n"] == 0):
                    swapped["n"] = 1
                    holder["client"].hard_kill()
                    # join the dead receive loop BEFORE delivering: a loop
                    # blocked in its inbox poll could otherwise still grab
                    # this dispatch and (being killed) drop it — and the
                    # sync protocol has no redispatch to recover that
                    holder["thread"].join(timeout=5.0)
                    holder["client"] = build_client(cfg, ds, model, rank=1,
                                                    backend="INPROC")
                    holder["thread"] = holder["client"].run_in_thread()
                orig_route(msg)
            return tap

        crash_server, crash_client = _run(
            f"parity_crash_{codec}_{seed}",
            {"client_journal_dir": f"{workdir}/client_journal"},
            tap_factory)

        ref_res = ref_client._comm_residuals or []
        crash_res = crash_client._comm_residuals or []
        bitwise_residuals = len(ref_res) == len(crash_res) and all(
            (a is None and b is None)
            or (a is not None and b is not None
                and np.array_equal(np.asarray(a), np.asarray(b)))
            for a, b in zip(ref_res, crash_res))
        ref_leaves = jax.tree_util.tree_leaves(
            jax.device_get(ref_server.aggregator.global_vars))
        crash_leaves = jax.tree_util.tree_leaves(
            jax.device_get(crash_server.aggregator.global_vars))
        bitwise_global = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(ref_leaves, crash_leaves))
        return {
            "codec": codec,
            "rounds": rounds,
            "killed_before_round": kill_before_round,
            "swapped": swapped["n"],
            "resumed": bool(crash_client.resumed_from_journal),
            "residual_leaves": sum(1 for r in ref_res if r is not None),
            "bitwise_residuals": bool(bitwise_residuals),
            "bitwise_global": bool(bitwise_global),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# real-process SIGKILL soak (ISSUE 13 tentpole d)
# ---------------------------------------------------------------------------

def _free_port_block(n: int, attempts: int = 64) -> int:
    """Find a base port such that base..base+n-1 are all bindable right now
    (the TCP transport derives each rank's listener as base+rank)."""
    import socket

    rng = np.random.default_rng([os.getpid(), int(time.time())])
    for _ in range(attempts):
        base = int(rng.integers(20000, 60000))
        socks = []
        try:
            for off in range(n):
                s = socket.socket()
                s.bind(("0.0.0.0", base + off))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port block found")


def _tail(path: str, nbytes: int = 4000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - nbytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return "<no log>"


def run_multiproc_kill_soak(n_clients: int = 3, versions: int = 160,
                            buffer_k: int = 3, concurrency: int = 3,
                            kill_server_at: int = 80,
                            client_kills: tuple = ((1, 20), (2, 45)),
                            journal_every_rounds: int = 5,
                            redispatch_timeout_s: float = 1.0, seed: int = 0,
                            chaos: Optional[dict] = None,
                            timeout_s: float = 420.0) -> dict:
    """REAL OS processes, REAL SIGKILLs (ISSUE 13): one buffered-async
    server process + ``n_clients`` real client processes over the TCP
    backend, each party journaling (server recovery journal + per-client
    journals).  The supervisor watches round progress through the server's
    journal steps (read-only — atomic replace makes concurrent reads safe),
    SIGKILLs the server at ``kill_server_at`` and each ``(rank,
    at_version)`` client at its mark, restarts every victim against its
    journal, and drives the run to completion.

    Unlike the in-process ``hard_kill`` soaks (which share journal semantics
    but not OS teardown), this exercises the whole real surface: process
    death mid-flock, listener teardown and port rebinding, connection
    refusals from dead peers, reconnect backoff against a listener that is
    genuinely gone, and cold interpreter restarts.

    The accounting identity, extended with client-side terms: the run
    completes all ``versions`` with MONOTONE continuity (journal steps never
    regress; the recovered server resumes at the last committed step); every
    client kill comes back as exactly one restart, each either a journal
    resume or a cold rejoin (``unaccounted`` == 0); and no upload folds
    twice — crash-resent duplicates reconcile as the server's ``deduped``
    counter, enforced by the journaled idempotence-key table.

    ``chaos`` (ISSUE 14 satellite, the ROADMAP carried-over item) threads
    ``chaos_*`` flags into EVERY worker's cfg: each real process's TCP
    backend wraps itself in its own seeded :class:`ChaosCommManager`
    (FedMLCommManager does this from the flags), so seeded drop/delay/
    duplicate/corrupt faults ride the same run as the genuine SIGKILLs on
    both protocol legs.  The accounting identity is unchanged and still
    must close: chaos losses are recovered by the redispatch watchdog and
    reconnect backoff, duplicates reconcile as journaled-key dedups, and
    every client kill still comes back as exactly one journal resume
    (``unaccounted == 0``).  The server worker reports its wrapper's
    injected-fault counters in ``server_summary.json`` (the ``chaos`` key
    of the result).

    Sizing note: rounds are CHEAP (tiny lr model, warm compile cache) while
    a SIGKILL restart costs a full interpreter boot (~5-10s), so the run
    needs enough versions that rounds are still left when victims come back
    — the defaults (160 versions at a 5-round journal cadence, kills spread
    across the first half) keep every restart mid-run."""
    import glob
    import json
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile

    from .journal import ServerJournal

    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    workdir = tempfile.mkdtemp(prefix="soak_multiproc_")
    summary_path = os.path.join(workdir, "server_summary.json")
    journal_dir = os.path.join(workdir, "server_journal")
    base_port = _free_port_block(n_clients + 1)
    cfg_path = os.path.join(workdir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump({
            "training_type": "cross_silo", "dataset": "synthetic",
            "model": "lr", "client_num_in_total": n_clients,
            "client_num_per_round": concurrency, "comm_round": versions,
            "epochs": 1, "batch_size": 16, "learning_rate": 0.1,
            "partition_method": "homo",
            "synthetic_train_size": 64 * n_clients, "synthetic_test_size": 64,
            "frequency_of_the_test": 0, "compute_dtype": "float32",
            "metrics_jsonl_path": "", "random_seed": seed,
            "run_id": f"mpsoak_{seed}", "backend": "TCP",
            "extra": {
                "async_aggregation": True, "async_buffer_k": buffer_k,
                "async_concurrency": concurrency,
                "async_redispatch_timeout_s": redispatch_timeout_s,
                "server_journal_dir": journal_dir,
                "server_journal_every_rounds": journal_every_rounds,
                "client_journal_dir": os.path.join(workdir, "client_journal"),
                "comm_compression": "topk", "comm_compress_min_size": 64,
                "tcp_base_port": base_port,
                # seeded fault schedule on the REAL transport (ISSUE 14):
                # every worker process wraps its TCP backend from these
                # flags, so chaos and genuine SIGKILLs compose in one run
                **({"chaos_seed": seed, **chaos} if chaos else {}),
            },
        }, f)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["SOAK_WORKER_TIMEOUT_S"] = str(timeout_s)
    boots: dict[int, int] = {}

    def spawn(role: str, rank: int):
        boots[rank] = boots.get(rank, 0) + 1
        log_path = os.path.join(
            workdir, f"{role}_{rank}_boot{boots[rank]}.log")
        with open(log_path, "wb") as lf:
            return subprocess.Popen(
                [sys.executable, "-m", "fedml_tpu.cross_silo.soak_worker",
                 cfg_path, role, str(rank), workdir],
                stdout=lf, stderr=subprocess.STDOUT, env=env, cwd=repo_root)

    def logs() -> str:
        return "\n".join(
            f"--- {p} ---\n{_tail(p)}"
            for p in sorted(glob.glob(os.path.join(workdir, "*.log"))))

    journal_reader = ServerJournal(journal_dir)
    procs: dict[int, subprocess.Popen] = {
        r: spawn("client", r) for r in range(1, n_clients + 1)}
    procs[0] = spawn("server", 0)
    pending_client_kills = sorted(client_kills, key=lambda m: m[1])
    server_killed = False
    versions_at_kill = None
    max_step_seen = 0
    monotone = True
    server_restarts = 0
    client_restarts = 0
    try:
        deadline = time.monotonic() + timeout_s
        while not os.path.exists(summary_path):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"multiproc soak did not complete in {timeout_s}s "
                    f"(journal step {max_step_seen}/{versions})\n{logs()}")
            steps = journal_reader.steps()
            step = max(steps) if steps else 0
            if step < max_step_seen:
                monotone = False  # journal regressed: recovery broke continuity
            max_step_seen = max(max_step_seen, step)
            if not server_killed and max_step_seen >= kill_server_at:
                server_killed = True
                versions_at_kill = max_step_seen
                procs[0].send_signal(signal.SIGKILL)
                procs[0].wait(timeout=30)
                time.sleep(0.2)
                procs[0] = spawn("server", 0)
                server_restarts += 1
            while (pending_client_kills
                   and max_step_seen >= pending_client_kills[0][1]):
                rank, _mark = pending_client_kills.pop(0)
                procs[rank].send_signal(signal.SIGKILL)
                procs[rank].wait(timeout=30)
                time.sleep(0.2)
                procs[rank] = spawn("client", rank)
                client_restarts += 1
            # a worker that died on its own (not our SIGKILL) is a failure
            for rank, p in procs.items():
                if p.poll() not in (None, 0):
                    raise RuntimeError(
                        f"worker rank {rank} exited rc={p.poll()} "
                        f"unexpectedly\n{logs()}")
            time.sleep(0.02)
        with open(summary_path) as f:
            summary = json.load(f)
        # FINISH reached the fleet: give clients a bounded drain window
        drain = time.monotonic() + 30.0
        while (time.monotonic() < drain
               and any(procs[r].poll() is None
                       for r in range(1, n_clients + 1))):
            time.sleep(0.2)
        clients_finished = sum(
            1 for r in range(1, n_clients + 1) if procs[r].poll() == 0)
        resumed = cold = 0
        for bp in glob.glob(os.path.join(workdir, "boot_r*.json")):
            with open(bp) as f:
                boot = json.load(f)
            if boot.get("restart"):
                if boot.get("resumed"):
                    resumed += 1
                else:
                    cold += 1
        return {
            "clients": n_clients,
            "versions": summary["server_version"],
            "versions_at_kill": versions_at_kill,
            "recovered_step": summary.get("recovered_step"),
            "session_epoch": summary["session_epoch"],
            "monotone": bool(
                monotone and summary["server_version"] >= max_step_seen
                and (summary.get("recovered_step") or 0) <= (versions_at_kill
                                                             or versions)),
            "completed": bool(summary.get("completed")),
            "arrivals": summary["arrivals"],
            "server_kills": server_restarts,
            "client_kills": client_restarts,
            "resumed_from_journal": resumed,
            "cold_rejoins": cold,
            "unaccounted": client_restarts - resumed - cold,
            "deduped": summary["deduped"],
            "rejected_stale": summary["rejected_stale"],
            "timeout_redispatches": summary["timeout_redispatches"],
            "clients_finished": clients_finished,
            "chaos": summary.get("chaos"),
        }
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            with contextlib.suppress(Exception):
                p.wait(timeout=10)
        shutil.rmtree(workdir, ignore_errors=True)
