"""Federated analytics over the wire — the cross-silo FA runner.

Parity with ``fa/runner.py:5`` (``FARunner`` dispatches
``training_type='cross_silo'`` to ``fa/cross_silo/fa_server.py`` /
``fa_client.py``, which mirror the FL managers): the SAME round protocol as
cross-silo FL — check status, INIT, submissions, aggregate, SYNC, FINISH —
but the payloads are analytics submissions (counts, tries, candidate sets)
instead of model weights, and the per-round downlink is the aggregator's
``init_msg`` (TrieHH's current prefix trie, k-percentile's current bounds)
instead of global params.

Rides every comm backend the FL managers do (INPROC/TCP/gRPC/MQTT) because
it reuses the same ``FedMLCommManager`` + ``Message`` machinery and the flat
message-type namespace (FA uses 20-22).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from ..core import rng
from ..cross_silo import message_define as md
from ..obs.metrics import MetricsLogger
from .analyzers import create_analyzer_pair
from .frame import FAClientAnalyzer, FAServerAggregator

log = logging.getLogger("fedml_tpu.fa.cross_silo")

MSG_TYPE_S2C_FA_ROUND = 20      # init_msg + round_idx (INIT and SYNC alike)
MSG_TYPE_C2S_FA_SUBMISSION = 21
MSG_ARG_KEY_FA_PAYLOAD = "fa_payload"


def fa_encode(obj):
    """Analytics payloads are Python containers (sets, Counters, dicts with
    non-string keys) that the JSON control channel cannot carry — encode them
    as tagged structures; :func:`fa_decode` restores the exact types."""
    from collections import Counter

    if isinstance(obj, Counter):
        return {"__fa__": "counter", "v": [[fa_encode(k), int(c)] for k, c in sorted(obj.items(), key=lambda kv: repr(kv[0]))]}
    if isinstance(obj, (set, frozenset)):
        return {"__fa__": "set", "v": [fa_encode(x) for x in sorted(obj, key=repr)]}
    if isinstance(obj, dict):
        return {"__fa__": "dict", "v": [[fa_encode(k), fa_encode(v)] for k, v in obj.items()]}
    if isinstance(obj, tuple):
        return {"__fa__": "tuple", "v": [fa_encode(x) for x in obj]}
    if isinstance(obj, list):
        return [fa_encode(x) for x in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return {"__fa__": "array", "v": obj.tolist(), "dtype": str(obj.dtype)}
    return obj


def fa_decode(obj):
    from collections import Counter

    if isinstance(obj, dict) and "__fa__" in obj:
        tag = obj["__fa__"]
        if tag == "counter":
            return Counter({fa_decode(k): int(c) for k, c in obj["v"]})
        if tag == "set":
            return {fa_decode(x) for x in obj["v"]}
        if tag == "dict":
            return {fa_decode(k): fa_decode(v) for k, v in obj["v"]}
        if tag == "tuple":
            return tuple(fa_decode(x) for x in obj["v"])
        if tag == "array":
            return np.asarray(obj["v"], dtype=obj["dtype"])
        raise ValueError(f"unknown fa payload tag {tag!r}")
    if isinstance(obj, list):
        return [fa_decode(x) for x in obj]
    return obj


class FAServerManager(FedMLCommManager):
    """Reference ``FACrossSiloServer``: drive rounds of analytics."""

    def __init__(self, cfg, aggregator: FAServerAggregator,
                 backend: Optional[str] = None, logger: Optional[MetricsLogger] = None):
        super().__init__(cfg, rank=0, size=cfg.client_num_in_total + 1, backend=backend)
        self.aggregator = aggregator
        self.cfg = cfg
        self.round_idx = 0
        self.client_ids = list(range(1, cfg.client_num_in_total + 1))
        self.per_round = min(cfg.client_num_per_round, len(self.client_ids))
        self.active_clients: set[int] = set()
        self.submissions: dict[int, object] = {}
        self.selected: list[int] = []
        self.done = threading.Event()
        self.history: list[dict] = []
        self.logger = logger or MetricsLogger(stdout=False)
        self._lock = threading.Lock()
        self._round0_sent = False
        self.root_key = rng.root_key(cfg.random_seed)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(md.MSG_TYPE_C2S_CLIENT_STATUS, self.handle_message_client_status)
        self.register_message_receive_handler(MSG_TYPE_C2S_FA_SUBMISSION, self.handle_message_submission)

    def start(self) -> None:
        for cid in self.client_ids:
            self.send_message(Message(md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, 0, cid))

    def handle_message_client_status(self, msg: Message) -> None:
        if msg.get(md.MSG_ARG_KEY_CLIENT_STATUS) == md.CLIENT_STATUS_ONLINE:
            self.active_clients.add(msg.get_sender_id())
        with self._lock:
            # A redelivered ONLINE status (e.g. MQTT QoS-1 redelivery) must not
            # re-sample `selected` mid-round; broadcast round 0 exactly once.
            if self._round0_sent or len(self.active_clients) < len(self.client_ids):
                return
            self._round0_sent = True
        self._broadcast_round()

    def _broadcast_round(self) -> None:  # graftlint: disable=GL004(single receive-loop thread dispatches both callers; the lock only orders round-0 idempotence),GL008(same single-receive-thread invariant: round_idx/selected mutate only on that thread; run_until_done reads after done.wait())
        """Sample this round's clients and send them the aggregator's
        init_msg (reference FA downlink; trie state, bounds, ...)."""
        if self.per_round >= len(self.client_ids):
            self.selected = list(self.client_ids)
        else:
            idx = rng.sample_clients_np(self.round_idx, len(self.client_ids), self.per_round)
            self.selected = [self.client_ids[i] for i in idx]
        init = self.aggregator.init_msg()
        for cid in self.selected:
            out = Message(MSG_TYPE_S2C_FA_ROUND, 0, cid)
            out.add_params(MSG_ARG_KEY_FA_PAYLOAD, fa_encode(init))
            out.add_params(md.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(out)

    def handle_message_submission(self, msg: Message) -> None:
        with self._lock:
            if msg.get(md.MSG_ARG_KEY_ROUND_INDEX) != self.round_idx:
                return
            self.submissions[msg.get_sender_id()] = fa_decode(msg.get(MSG_ARG_KEY_FA_PAYLOAD))
            if len(self.submissions) < len(self.selected):
                return
            subs = [self.submissions[c] for c in sorted(self.submissions)]
            self.submissions.clear()
            self.aggregator.aggregate(subs)
            metrics = {"round": self.round_idx, "submissions": len(subs)}
            self.logger.log(metrics)
            self.history.append(metrics)
            self.round_idx += 1
            if self.round_idx >= self.cfg.comm_round:
                for cid in self.client_ids:
                    self.send_message(Message(md.MSG_TYPE_S2C_FINISH, 0, cid))
                self.done.set()
                self.finish()
                return
            self._broadcast_round()

    def result(self):
        return self.aggregator.result()

    def run_until_done(self, timeout: float = 600.0):
        thread = self.run_in_thread()
        self.start()
        if not self.done.wait(timeout):
            self.finish()
            raise TimeoutError(f"FA run did not finish in {timeout}s (round {self.round_idx})")  # graftlint: disable=GL004(diagnostic read on the timeout path; a torn round index only mislabels the error)
        thread.join(timeout=5.0)
        return self.result()


class FAClientManager(FedMLCommManager):
    """Reference ``FACrossSiloClient``: analyze the local shard on request."""

    def __init__(self, cfg, analyzer: FAClientAnalyzer, data: np.ndarray,
                 rank: int, backend: Optional[str] = None):
        super().__init__(cfg, rank=rank, size=cfg.client_num_in_total + 1, backend=backend)
        self.analyzer = analyzer
        self.data = data
        self.cfg = cfg
        self.done = threading.Event()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.handle_message_check_status)
        self.register_message_receive_handler(MSG_TYPE_S2C_FA_ROUND, self.handle_message_round)
        self.register_message_receive_handler(md.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def handle_message_check_status(self, msg: Message) -> None:
        reply = Message(md.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        reply.add_params(md.MSG_ARG_KEY_CLIENT_STATUS, md.CLIENT_STATUS_ONLINE)
        self.send_message(reply)

    def handle_message_round(self, msg: Message) -> None:
        self.analyzer.set_init_msg(fa_decode(msg.get(MSG_ARG_KEY_FA_PAYLOAD)))
        sub = self.analyzer.local_analyze(self.data, self.cfg)
        reply = Message(MSG_TYPE_C2S_FA_SUBMISSION, self.rank, 0)
        reply.add_params(MSG_ARG_KEY_FA_PAYLOAD, fa_encode(sub))
        reply.add_params(md.MSG_ARG_KEY_ROUND_INDEX, msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
        self.send_message(reply)

    def handle_message_finish(self, msg: Message) -> None:
        self.done.set()
        self.finish()


# -- builders + runner --------------------------------------------------------

def build_fa_server(cfg, task: str, backend: Optional[str] = None) -> FAServerManager:
    _, aggregator = create_analyzer_pair(task, cfg)
    return FAServerManager(cfg, aggregator, backend=backend)


def build_fa_client(cfg, task: str, data: np.ndarray, rank: int,
                    backend: Optional[str] = None) -> FAClientManager:
    analyzer, _ = create_analyzer_pair(task, cfg)
    return FAClientManager(cfg, analyzer, data, rank=rank, backend=backend)


def run_fa_process_group(cfg, task: str, client_data: Sequence[np.ndarray],
                         backend: str = "INPROC", timeout: float = 600.0):
    """1 FA server + N FA clients on threads over the chosen backend.
    Returns (result, server)."""
    if backend == "INPROC":
        from ..comm.inproc import InProcRouter

        InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
    server = build_fa_server(cfg, task, backend=backend)
    clients = [
        build_fa_client(cfg, task, np.asarray(client_data[r - 1]), rank=r, backend=backend)
        for r in range(1, cfg.client_num_in_total + 1)
    ]
    for c in clients:
        c.run_in_thread()
    try:
        result = server.run_until_done(timeout=timeout)
    finally:
        for c in clients:
            c.finish()
    return result, server
