"""Federated analytics frame.

Parity with ``fa/base_frame/`` (``FAClientAnalyzer``
``client_analyzer.py:5``, ``FAServerAggregator`` ``server_aggregator.py:5``)
and ``FARunner``/``FASimulatorSingleProcess`` (``fa/runner.py:5``,
``fa/simulation/sp/simulator.py:9``): clients run a local analysis over their
raw data, the server aggregates submissions — same round structure as FL but
over analytics payloads instead of model weights.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import numpy as np

from ..core import rng
from ..obs.metrics import MetricsLogger


class FAClientAnalyzer:
    """Local analysis operator (reference ``client_analyzer.py``)."""

    def __init__(self, cfg=None):
        self.cfg = cfg
        self.init_msg: Any = None

    def set_init_msg(self, msg: Any) -> None:
        self.init_msg = msg

    def local_analyze(self, data: np.ndarray, cfg) -> Any:
        raise NotImplementedError


class FAServerAggregator:
    """Server aggregation operator (reference ``server_aggregator.py``)."""

    def __init__(self, cfg=None):
        self.cfg = cfg
        self.server_data: Any = None

    def init_msg(self) -> Any:
        return None

    def aggregate(self, submissions: list) -> Any:
        raise NotImplementedError

    def result(self) -> Any:
        return self.server_data


class FASimulator:
    """Single-process FA simulator (``FASimulatorSingleProcess``):
    sample clients -> local_analyze -> aggregate, for comm_round rounds."""

    def __init__(self, cfg, client_data: Sequence[np.ndarray],
                 analyzer: FAClientAnalyzer, aggregator: FAServerAggregator,
                 logger: Optional[MetricsLogger] = None):
        self.cfg = cfg
        self.client_data = list(client_data)
        self.analyzer = analyzer
        self.aggregator = aggregator
        self.key = rng.root_key(cfg.random_seed)
        self.logger = logger or MetricsLogger(stdout=False)

    def run(self) -> Any:
        n = len(self.client_data)
        m = min(self.cfg.client_num_per_round, n)
        for r in range(self.cfg.comm_round):
            sampled = np.asarray(rng.sample_clients(self.key, r, n, m))
            self.analyzer.set_init_msg(self.aggregator.init_msg())
            subs = [self.analyzer.local_analyze(self.client_data[int(c)], self.cfg) for c in sampled]
            self.aggregator.aggregate(subs)
            self.logger.log({"round": r, "submissions": len(subs)})
        return self.aggregator.result()
